"""Executor: compiles a recorded Program into one jitted XLA step.

TPU-native replacement for the reference's interpreter Executor
(reference: framework/executor.cc:166 Run — a per-op C++ loop; and
python/paddle/fluid/executor.py:475/916). Here `run()` compiles (once per
feed-signature) a pure function
    (param_values, opt_state, feed) -> (fetches, new_params, new_opt_state)
covering forward + backward (jax.grad over the recorded graph, replacing the
compile-time transpiler fluid/backward.py:1363 append_backward) + the
optimizer update — a single HLO per training step, with donated buffers.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, stable_uid
from ..core import dtypes as _dt
from ..observability import tracer as _otrace
from .graph import Program, Variable, default_main_program


class _Scope:
    """Name → value holder (reference: framework/scope.h:52). Params are the
    Parameter objects themselves (their ._data is the state)."""

    def __init__(self):
        self.vars: Dict[str, Any] = {}

    def find_var(self, name):
        return self.vars.get(name)

    def var(self, name):
        return self.vars.setdefault(name, None)


_GLOBAL_SCOPE = _Scope()


def global_scope():
    return _GLOBAL_SCOPE


def _replay(program: Program, env: Dict[int, Any], param_env: Dict[int, Any]):
    """Execute the recorded op list over an environment keyed by Variable id.
    Values for concrete Tensors (params) come from param_env (traced)."""
    for op in program.ops:
        args_flat = []
        for leaf in op.arg_leaves:
            if isinstance(leaf, Variable):
                if id(leaf) not in env:
                    raise RuntimeError(
                        f"Variable {leaf.name} used before definition "
                        f"(op {op.type}); is it fed?")
                args_flat.append(env[id(leaf)])
            elif isinstance(leaf, Tensor):
                args_flat.append(param_env[id(leaf)])
            else:
                args_flat.append(leaf)
        args = jax.tree_util.tree_unflatten(op.arg_treedef, args_flat)
        out = op.fn(*args, **op.attrs)
        out_leaves, _ = jax.tree_util.tree_flatten(out)
        for v, val in zip(op.out_vars, out_leaves):
            env[id(v)] = val


class Executor:
    """reference: fluid/executor.py:475."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def close(self):
        self._cache.clear()

    def run(self, program=None, feed=None, fetch_list=None,
            feed_var_name="feed", fetch_var_name="fetch", scope=None,
            return_numpy=True, use_program_cache=True):
        program = program if program is not None else default_main_program()
        data_parallel = bool(getattr(program, "_data_parallel", False))
        if hasattr(program, "_program"):   # CompiledProgram wrapper
            program = program._program
        feed = feed or {}
        fetch_list = list(fetch_list or [])

        if not program.ops:
            return [] if fetch_list == [] else [None] * len(fetch_list)

        feed_names = tuple(sorted(feed.keys()))
        feed_vals = {}
        for k in feed_names:
            v = feed[k]
            if isinstance(v, Tensor):
                v = v._data
            feed_vals[k] = jnp.asarray(v)
        if data_parallel:
            self._shard_feeds_dp(feed_vals, program)
        sig = tuple((k, tuple(feed_vals[k].shape), str(feed_vals[k].dtype))
                    for k in feed_names)
        fetch_key = tuple(f.name if isinstance(f, Variable) else str(f)
                          for f in fetch_list)
        key = (id(program), program._version, sig, fetch_key)

        params = program.all_parameters()
        opt = program._optimizer
        entry = self._cache.get(key) if use_program_cache else None
        fresh = entry is None
        if fresh:
            with _otrace.span("jit/compile", {"fn": "executor_program"}):
                entry = self._compile(program, feed_names, fetch_list,
                                      params, opt, feed_vals)
            if use_program_cache:
                self._cache[key] = entry

        # first entry() call traces+compiles the XLA program, so the fresh
        # run's span contains that cost on the timeline
        with _otrace.span("static/executor_run", {"fresh": fresh}
                          if fresh else None):
            return self._run_entry(entry, program, params, opt, feed_vals,
                                   feed_names, return_numpy)

    def _run_entry(self, entry, program, params, opt, feed_vals, feed_names,
                   return_numpy):
        param_raws = [p._data for p in params]
        if opt is not None:
            for p in params:
                if stable_uid(p) not in opt._state:
                    opt._state[stable_uid(p)] = opt._init_state(p)
            opt_states = [opt._state[stable_uid(p)] for p in params]
            lr = jnp.asarray(opt.get_lr(), jnp.float32)
            step_no = jnp.asarray(opt._global_step + 1, jnp.float32)
            fetches, new_params, new_states, effects = entry(
                param_raws, opt_states, [feed_vals[k] for k in feed_names],
                lr, step_no)
            for p, npr, ns in zip(params, new_params, new_states):
                p._data = npr
                p._inplace_version += 1
                opt._state[stable_uid(p)] = ns
            opt._global_step += 1
        else:
            fetches, effects = entry(param_raws,
                                     [feed_vals[k] for k in feed_names])
        for (holder, _), val in zip(program._state_effects, effects):
            holder._data = val
            holder._inplace_version += 1
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor(f) for f in fetches]

    # ------------------------------------------------------------------
    def _shard_feeds_dp(self, feed_vals, program):
        """Static data parallelism (reference: ParallelExecutor): shard
        every feed's batch dim over the mesh's "dp" axis (or an implicit
        all-device mesh) and replicate the params — GSPMD partitions the
        compiled step and inserts the gradient all-reduce."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..distributed import mesh as _mesh_mod

        mesh = _mesh_mod.get_mesh()
        if mesh is None:
            devs = jax.devices()
            if len(devs) == 1:
                return  # single device: DP is a no-op, not an error
            mesh = _mesh_mod.build_mesh({"dp": len(devs)}, devs)
        axis = "dp" if "dp" in mesh.axis_names else mesh.axis_names[0]
        n = int(mesh.shape[axis])
        for k, v in feed_vals.items():
            if v.ndim >= 1 and v.shape[0] % n == 0:
                spec = P(axis, *([None] * (v.ndim - 1)))
                feed_vals[k] = jax.device_put(v, NamedSharding(mesh, spec))
        repl = NamedSharding(mesh, P())
        for p in program.all_parameters():
            sh = getattr(p._data, "sharding", None)
            if sh != repl:
                p._data = jax.device_put(p._data, repl)

    def _compile(self, program: Program, feed_names, fetch_list, params, opt,
                 feed_vals):
        data_vars = {name: program.vars[name] for name in feed_names
                     if name in program.vars}

        def build_env(param_raws, feed_raws):
            env: Dict[int, Any] = {}
            for name, raw in zip(feed_names, feed_raws):
                if name in data_vars:
                    env[id(data_vars[name])] = raw
            param_env = {id(p): r for p, r in zip(params, param_raws)}
            return env, param_env

        def fetch_from(env, param_env, grads_by_param=None):
            out = []
            for f in fetch_list:
                if isinstance(f, Variable):
                    if id(f) in env:
                        out.append(env[id(f)])
                    elif f.name in program._grad_map and grads_by_param is not None:
                        tgt = program._grad_map[f.name]
                        out.append(grads_by_param[id(tgt)])
                    else:
                        raise RuntimeError(f"cannot fetch {f.name}")
                elif isinstance(f, Tensor):
                    out.append(param_env[id(f)])
                else:
                    raise RuntimeError(f"bad fetch entry {f!r}")
            return out

        loss_var = program._loss
        need_grads = any(isinstance(f, Variable) and f.name in program._grad_map
                         for f in fetch_list)

        if opt is None and loss_var is None:
            def infer_step(param_raws, feed_raws):
                env, param_env = build_env(param_raws, feed_raws)
                _replay(program, env, param_env)
                effects = [env[id(v)] for _, v in program._state_effects]
                return fetch_from(env, param_env), effects
            jitted = jax.jit(infer_step)
            jitted.raw_step = infer_step  # trace-audit hook (core.audit)
            jitted.audit_jit_kwargs = {}
            return jitted

        trainable = [p for p in params if not p.stop_gradient]

        def loss_of(trainable_raws, all_param_raws, feed_raws):
            pe = list(all_param_raws)
            ti = 0
            for i, p in enumerate(params):
                if not p.stop_gradient:
                    pe[i] = trainable_raws[ti]
                    ti += 1
            env, param_env = build_env(pe, feed_raws)
            _replay(program, env, param_env)
            return env[id(loss_var)], (env, param_env)

        if opt is None:
            # backward only (append_backward without optimizer)
            def grad_step(param_raws, feed_raws):
                t_raws = [r for p, r in zip(params, param_raws)
                          if not p.stop_gradient]
                (loss, (env, param_env)), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(t_raws, param_raws, feed_raws)
                gmap = {id(p): g for p, g in zip(trainable, grads)}
                effects = [env[id(v)] for _, v in program._state_effects]
                return fetch_from(env, param_env, gmap), effects
            jitted = jax.jit(grad_step)
            jitted.raw_step = grad_step  # trace-audit hook (core.audit)
            jitted.audit_jit_kwargs = {}
            return jitted

        optimizer = opt
        reg_coeffs = [optimizer._regularized_grad(p, None) for p in trainable]
        if optimizer._grad_clip is not None:
            clip = optimizer._grad_clip
        else:
            clip = None

        ctxs = optimizer._param_update_ctx(trainable)

        def train_step(param_raws, opt_states, feed_raws, lr, step_no):
            t_raws = [r for p, r in zip(params, param_raws)
                      if not p.stop_gradient]
            (loss, (env, param_env)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(t_raws, param_raws, feed_raws)
            grads = list(grads)
            # clip first, then L2-regularize — same order as dygraph
            # Optimizer.step (reference apply_gradients: clip → regularize)
            if clip is not None:
                grads = clip._clip_raw(trainable, grads)
            for i, rc in enumerate(reg_coeffs):
                if rc is not None:
                    grads[i] = grads[i] + rc * t_raws[i]
            new_params, new_states = [], []
            gi = 0
            for p, pr, st in zip(params, param_raws, opt_states):
                if p.stop_gradient:
                    new_params.append(pr)
                    new_states.append(st)
                    continue
                p2, s2 = optimizer._update(pr, grads[gi].astype(pr.dtype), st,
                                           lr, step_no, ctxs[gi])
                new_params.append(p2)
                new_states.append(s2)
                gi += 1
            gmap = {id(p): g for p, g in zip(trainable, grads)}
            effects = [env[id(v)] for _, v in program._state_effects]
            return (fetch_from(env, param_env, gmap), new_params, new_states,
                    effects)

        jitted = jax.jit(train_step, donate_argnums=(0, 1))
        # trace-audit hook: the auditor (tools/analyze/trace) re-jits the
        # RAW step under its own trace counter with the same jit kwargs,
        # so the audited program is exactly the deployed one
        jitted.raw_step = train_step
        jitted.audit_jit_kwargs = {"donate_argnums": (0, 1)}
        return jitted


# -- trace-audit registration (tools/analyze/trace, PTA009/PTA010) -----------

def _audit_executor_train_spec():
    """A minimal static Program (Linear + MSE + SGD.minimize) compiled by
    the real Executor; the audited fn is the raw train_step the Executor
    jits with donated param/opt buffers."""
    from ..core import audit
    from ..ops.dispatch import enable_static, disable_static
    from .. import nn, optimizer as optim
    import paddle_tpu as paddle
    from .graph import Program, program_guard, data

    enable_static()
    try:
        main, startup = Program(), Program()
        with program_guard(main, startup):
            x = data("x", [4, 3], "float32")
            y = data("y", [4, 1], "float32")
            lin = nn.Linear(3, 1)
            pred = lin(x)
            loss = paddle.mean((pred - y) ** 2)
            opt = optim.SGD(0.1)
            opt.minimize(loss)
        exe = Executor()
        exe.run(startup)
        feed_names = ("x", "y")
        params = main.all_parameters()
        opt_obj = main._optimizer
        feed_vals = {"x": jnp.zeros((4, 3), jnp.float32),
                     "y": jnp.zeros((4, 1), jnp.float32)}
        entry = exe._compile(main, feed_names, [loss], params, opt_obj,
                             feed_vals)
        for p in params:
            if stable_uid(p) not in opt_obj._state:
                opt_obj._state[stable_uid(p)] = opt_obj._init_state(p)
        base_params = [np.asarray(p._data) for p in params]
        base_states = jax.tree_util.tree_map(
            np.asarray, [opt_obj._state[stable_uid(p)] for p in params])
    finally:
        disable_static()

    def make_args(variant):
        # fresh arrays every call: donate_argnums=(0, 1) consumes them
        rng = np.random.default_rng(11 + variant)
        param_raws = [jnp.asarray(b) for b in base_params]
        opt_states = jax.tree_util.tree_map(jnp.asarray, base_states)
        feeds = [jnp.asarray(rng.standard_normal((4, 3)), jnp.float32),
                 jnp.asarray(rng.standard_normal((4, 1)), jnp.float32)]
        lr = jnp.asarray(0.1, jnp.float32)
        step_no = jnp.asarray(1.0, jnp.float32)
        return (param_raws, opt_states, feeds, lr, step_no)

    from ..core import audit as _audit
    return _audit.AuditSpec(fn=entry.raw_step, make_args=make_args,
                            jit_kwargs=dict(entry.audit_jit_kwargs))


def _register_audit_entrypoints():
    from ..core import audit
    audit.register_entrypoint("executor_train_step",
                              _audit_executor_train_spec,
                              tags=("train", "static"))


_register_audit_entrypoints()
