"""Static-graph Program IR: symbolic Variables + recorded ops.

TPU-native equivalent of the reference Program model
(reference: framework/framework.proto ProgramDesc :202 / OpDesc :43,
python/paddle/fluid/framework.py Program :3974, Block :2479, Variable :799).

Design difference: the reference serializes protobuf op descriptions executed
op-by-op by a C++ interpreter (executor.cc:166). Here a Program records each
op's traceable implementation + argument structure; the Executor compiles the
whole op list (plus backward + optimizer update) into ONE jitted XLA program
per feed signature — replacing the interpreter hot loop with a single HLO
(SURVEY §7 decision 1).

Dynamic dims: `data(shape=[None, ...])` keeps None; recorded output shapes are
inferred with a two-placeholder eval_shape trick (dims that vary with the
placeholder are reported as -1, like the reference's -1 convention).
"""
from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter
from ..core import dtypes as _dt


class Variable:
    """Symbolic graph variable (reference: framework.py:799)."""

    _counter = [0]

    def __init__(self, program, shape, dtype, name=None, is_data=False,
                 stop_gradient=True, persistable=False):
        self._program = program
        self.shape = list(shape)
        self.dtype = np.dtype(dtype) if dtype is not None else None
        if name is None:
            Variable._counter[0] += 1
            name = f"_generated_var_{Variable._counter[0]}"
        self.name = name
        self.is_data = is_data
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self.op = None          # producing OpRecord
        self.out_index = None   # leaf index in producing op's outputs

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def size(self):
        if any(s in (None, -1) for s in self.shape):
            return -1
        return int(np.prod(self.shape)) if self.shape else 1

    def astype(self, dtype):
        from ..ops.dispatch import apply
        d = _dt.convert_dtype(dtype)
        return apply("cast", lambda x: x.astype(d), self)

    cast = astype

    def __repr__(self):
        return (f"Variable(name={self.name}, shape={self.shape}, "
                f"dtype={self.dtype})")

    def __hash__(self):
        return id(self)

    # numpy conversion is not available pre-execution (matches reference)
    def numpy(self):
        raise RuntimeError(
            "Variable has no data in static-graph mode; fetch it via "
            "Executor.run(fetch_list=[...]).")


class OpRecord:
    """One recorded op (reference: framework.proto OpDesc :43)."""

    __slots__ = ("type", "fn", "arg_leaves", "arg_treedef", "attrs",
                 "out_vars", "out_treedef", "idx")

    def __init__(self, type_, fn, arg_leaves, arg_treedef, attrs, out_vars,
                 out_treedef, idx):
        self.type = type_
        self.fn = fn
        self.arg_leaves = arg_leaves      # Variable | Tensor(param ref) | const
        self.arg_treedef = arg_treedef
        self.attrs = attrs
        self.out_vars = out_vars
        self.out_treedef = out_treedef
        self.idx = idx


class Program:
    """reference: framework.py:3974 Program (single-block equivalent)."""

    def __init__(self):
        self.ops: List[OpRecord] = []
        self.vars: Dict[str, Variable] = {}
        self._params: List[Tensor] = []       # concrete Parameters touched
        self._state_effects: List[Tuple[Tensor, Variable]] = []
        self._loss: Optional[Variable] = None
        self._optimizer = None
        self._grad_map: Dict[str, Any] = {}   # grad var name -> param/input var
        self.random_seed = None
        self._version = 0

    # -- var/param bookkeeping ---------------------------------------------
    def add_var(self, var: Variable):
        self.vars[var.name] = var
        return var

    def global_block(self):
        return self

    def all_parameters(self):
        return list(self._params)

    def touch_param(self, p: Tensor):
        if all(p is not q for q in self._params):
            self._params.append(p)

    def record_state_effect(self, holder: Tensor, value: Variable):
        for i, (h, _) in enumerate(self._state_effects):
            if h is holder:
                self._state_effects[i] = (holder, value)
                return
        self._state_effects.append((holder, value))

    def list_vars(self):
        return list(self.vars.values())

    def clone(self, for_test=False):
        import copy
        p = Program.__new__(Program)
        p.ops = list(self.ops)
        p.vars = dict(self.vars)
        p._params = list(self._params)
        p._state_effects = [] if for_test else list(self._state_effects)
        p._loss = self._loss
        p._optimizer = None if for_test else self._optimizer
        p._grad_map = dict(self._grad_map)
        p.random_seed = self.random_seed
        p._version = self._version
        return p

    def __repr__(self):
        lines = [f"Program({len(self.ops)} ops, {len(self.vars)} vars)"]
        for op in self.ops:
            ins = [getattr(l, "name", "<const>") for l in op.arg_leaves]
            outs = [v.name for v in op.out_vars]
            lines.append(f"  {{{op.type}}} inputs={ins} -> outputs={outs}")
        return "\n".join(lines)

    to_string = __repr__


# -- global program state (reference: framework.py default_main_program) ----
_main_program = [Program()]
_startup_program = [Program()]


def default_main_program() -> Program:
    return _main_program[0]


def default_startup_program() -> Program:
    return _startup_program[0]


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    prev_m, prev_s = _main_program[0], _startup_program[0]
    _main_program[0] = main_program
    if startup_program is not None:
        _startup_program[0] = startup_program
    try:
        yield
    finally:
        _main_program[0] = prev_m
        _startup_program[0] = prev_s


def data(name, shape, dtype="float32", lod_level=0):
    """reference: python/paddle/static/input.py data — a feed slot."""
    prog = default_main_program()
    var = Variable(prog, shape, _dt.convert_dtype(dtype), name=name,
                   is_data=True, stop_gradient=True)
    return prog.add_var(var)


# -- shape inference --------------------------------------------------------

_PLACEHOLDERS = (2, 3)


def _avals_for(leaves, placeholder):
    avals = []
    for l in leaves:
        if isinstance(l, Variable):
            shape = tuple(placeholder if (s is None or s == -1) else int(s)
                          for s in l.shape)
            avals.append(jax.ShapeDtypeStruct(shape, l.dtype or np.float32))
        elif isinstance(l, Tensor):
            avals.append(jax.ShapeDtypeStruct(tuple(l.shape), l.dtype))
        else:
            avals.append(l)
    return avals


def infer_out_structure(fn, leaves, treedef, attrs):
    """Two-placeholder eval_shape: dims that track the placeholder are
    dynamic (-1)."""
    results = []
    for ph in _PLACEHOLDERS:
        avals = _avals_for(leaves, ph)

        def call(*dyn):
            it = iter(dyn)
            full = [next(it) if isinstance(l, (Variable, Tensor)) else l
                    for l in leaves]
            args = jax.tree_util.tree_unflatten(treedef, full)
            return fn(*args, **attrs)
        dyn_avals = [a for a, l in zip(avals, leaves)
                     if isinstance(l, (Variable, Tensor))]
        results.append(jax.eval_shape(call, *dyn_avals))
        if not _has_dynamic(leaves):
            results.append(results[0])
            break
    s1, s2 = results[0], results[1]
    l1, td = jax.tree_util.tree_flatten(s1)
    l2, _ = jax.tree_util.tree_flatten(s2)
    out_shapes = []
    for a, b in zip(l1, l2):
        shape = [da if da == db else -1 for da, db in zip(a.shape, b.shape)]
        out_shapes.append((shape, a.dtype))
    return out_shapes, td


def _has_dynamic(leaves):
    return any(isinstance(l, Variable)
               and any(s in (None, -1) for s in l.shape) for l in leaves)


# -- the static dispatch handler -------------------------------------------

def static_handler(name, fn, args, attrs, leaves, treedef):
    """Installed into ops.dispatch: append an OpRecord instead of executing
    (the reference appends an OpDesc via LayerHelper.append_op)."""
    prog = default_main_program()
    # params referenced by the graph
    for l in leaves:
        if isinstance(l, Tensor):
            prog.touch_param(l)
    out_shapes, out_td = infer_out_structure(fn, leaves, attrs=attrs,
                                             treedef=treedef)
    out_vars = []
    for shape, dtype in out_shapes:
        v = Variable(prog, shape, dtype,
                     stop_gradient=all(
                         getattr(l, "stop_gradient", True)
                         for l in leaves if isinstance(l, (Variable, Tensor))))
        prog.add_var(v)
        out_vars.append(v)
    rec = OpRecord(name, fn, list(leaves), treedef, dict(attrs), out_vars,
                   out_td, len(prog.ops))
    prog.ops.append(rec)
    prog._version += 1
    for i, v in enumerate(out_vars):
        v.op = rec
        v.out_index = i
    result = jax.tree_util.tree_unflatten(out_td, out_vars)
    return result


def _attach_variable_methods():
    """Give Variable the same op-method surface as Tensor (the methods call
    ops functions, which route back through dispatch → static_handler)."""
    from ..core.tensor import Tensor as _T
    skip = {"numpy", "item", "set_value", "astype", "backward", "detach",
            "__repr__", "__hash__", "__init__"}
    for attr in dir(_T):
        if attr in skip or (attr.startswith("__") and attr not in (
                "__add__", "__radd__", "__sub__", "__rsub__", "__mul__",
                "__rmul__", "__truediv__", "__rtruediv__", "__floordiv__",
                "__mod__", "__rmod__", "__pow__", "__rpow__", "__matmul__",
                "__neg__", "__abs__", "__eq__", "__ne__", "__gt__", "__ge__",
                "__lt__", "__le__", "__getitem__", "__invert__", "__and__",
                "__or__", "__xor__")):
            continue
        val = _T.__dict__.get(attr)
        # check Variable.__dict__, not hasattr: object supplies default rich
        # comparisons (__eq__/__gt__/...) which must be overridden here
        if callable(val) and attr not in Variable.__dict__:
            setattr(Variable, attr, val)


_attach_variable_methods()
