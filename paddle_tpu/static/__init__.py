"""paddle.static parity: declarative Program mode.

Reference: python/paddle/static/__init__.py — Program, program_guard, data,
Executor, append_backward, save/load_inference_model, CompiledProgram.
"""
from __future__ import annotations

import pickle

import numpy as np

from .graph import (Program, Variable, program_guard, data,
                    default_main_program, default_startup_program,
                    static_handler)
from .executor import Executor, global_scope
from ..ops import dispatch as _dispatch
from ..core.tensor import Tensor

# install the graph-recording handler into the op dispatch funnel
_dispatch.register_static_handler(static_handler)


from ..jit import InputSpec  # noqa: E402


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """reference: fluid/backward.py:1363 — there, a compile-time transpiler
    appending grad OpDescs; here, marks the loss so the Executor compiles
    jax.grad over the recorded graph. Returns (param, grad_var) pairs."""
    prog = loss._program
    prog._loss = loss
    params_grads = []
    plist = parameter_list if parameter_list is not None else prog.all_parameters()
    for i, p in enumerate(plist):
        if getattr(p, "stop_gradient", True):
            continue
        gname = (p.name or f"param_{i}") + "@GRAD"
        gv = Variable(prog, p.shape, p.dtype, name=gname)
        prog.add_var(gv)
        prog._grad_map[gname] = p
        params_grads.append((p, gv))
    return params_grads


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """reference: fluid/backward.py:1958."""
    t = targets[0] if isinstance(targets, (list, tuple)) else targets
    return [g for _, g in append_backward(t, parameter_list=list(inputs))]


class CompiledProgram:
    """reference: fluid/compiler.py:88 — multi-device compilation wrapper.
    On TPU the Executor already compiles whole programs;
    ``with_data_parallel`` marks the program so Executor.run shards each
    feed's batch dim over the mesh (GSPMD then partitions the compiled
    step and inserts the gradient all-reduce — the role of the reference's
    ParallelExecutor graph passes, parallel_executor.cc:618)."""

    def __init__(self, program, build_strategy=None):
        self._program = program
        self._build_strategy = build_strategy
        self._data_parallel = False

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        self._data_parallel = True
        return self

    def __getattr__(self, name):
        return getattr(self._program, name)


class BuildStrategy:
    """reference: details/build_strategy.h:54 — fusion/memory knobs. XLA owns
    these decisions; fields accepted and recorded for compatibility."""

    def __init__(self):
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.enable_inplace = True
        self.memory_optimize = True
        self.reduce_strategy = None
        self.gradient_scale_strategy = None


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor,
                         program=None):
    """reference: fluid/io.py:1199 — prunes to feed/fetch and serializes.
    Here: pickle the param arrays + record the program replay closure is not
    serializable, so we re-trace via jax.export like jit.save."""
    program = program or default_main_program()
    import jax
    import jax.numpy as jnp
    from jax import export as jax_export
    from .executor import _replay

    params = program.all_parameters()
    feed_list = list(feed_vars)
    fetch_list = list(fetch_vars)

    def infer(param_raws, *feed_raws):
        env = {id(v): r for v, r in zip(feed_list, feed_raws)}
        param_env = {id(p): r for p, r in zip(params, param_raws)}
        _replay(program, env, param_env)
        return [env[id(f)] for f in fetch_list]

    param_avals = [jax.ShapeDtypeStruct(tuple(p.shape), p.dtype) for p in params]
    feed_avals = [jax.ShapeDtypeStruct(
        tuple(1 if (s is None or s == -1) else s for s in v.shape), v.dtype)
        for v in feed_list]
    exported = jax_export.export(jax.jit(infer))(param_avals, *feed_avals)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    with open(path_prefix + ".pdiparams", "wb") as f:
        pickle.dump({"params": [np.asarray(p._data) for p in params],
                     "n_out": len(fetch_list)}, f, protocol=4)


def load_inference_model(path_prefix, executor=None):
    from ..jit import load as jit_load
    tl = jit_load(path_prefix)
    return tl, None, None


def name_scope(prefix=None):
    import contextlib

    @contextlib.contextmanager
    def _ns():
        yield
    return _ns()


# static.nn: op-style wrappers (reference: fluid/layers/nn.py via
# paddle.static.nn — each call creates fresh parameters, like the reference's
# LayerHelper.create_parameter per call site)
class nn:
    # control flow (reference: fluid/layers/control_flow.py cond/While)
    from ..ops.control_flow import (cond, while_loop, case,  # noqa: F401
                                    switch_case)
    cond = staticmethod(cond)
    while_loop = staticmethod(while_loop)
    case = staticmethod(case)
    switch_case = staticmethod(switch_case)

    @staticmethod
    def fc(x, size, num_flatten_dims=1, activation=None, name=None):
        from ..nn import functional as F
        from ..nn.layers_common import Linear
        lay = Linear(int(x.shape[-1]), size)
        out = F.linear(x, lay.weight, lay.bias)
        if activation:
            out = getattr(F, activation)(out)
        return out

    @staticmethod
    def embedding(input, size, param_attr=None, dtype="float32"):
        from ..nn.layers_common import Embedding
        lay = Embedding(size[0], size[1], weight_attr=param_attr)
        return lay(input)

    @staticmethod
    def Assert(cond, data=None, summarize=20, name=None):
        """reference: fluid/layers/control_flow.py Assert (assert_op).
        Host-side check in eager; under trace uses checkify-free
        debug.check semantics via error on concrete False only."""
        import numpy as np
        import jax
        from ..core.tensor import Tensor
        c = cond._data if isinstance(cond, Tensor) else cond
        if isinstance(c, jax.core.Tracer):
            # traced: XLA has no side-effecting assert; document + no-op
            # (the reference's op also only fires at run time on CPU).
            return cond
        if not bool(np.asarray(c).all()):
            shown = []
            for d in (data or []):
                arr = d.numpy() if isinstance(d, Tensor) else np.asarray(d)
                shown.append(np.array2string(arr.ravel()[:summarize]))
            raise AssertionError(
                f"paddle.static.nn.Assert failed; data={shown}")
        return cond

    @staticmethod
    def Print(input, first_n=-1, message=None, summarize=20,
              print_tensor_name=True, print_tensor_type=True,
              print_tensor_shape=True, print_tensor_lod=False,
              print_phase="both", name=None):
        """reference: operators/controlflow (print_op) — debug print that
        passes the tensor through. Uses jax.debug.print under trace so it
        fires inside compiled programs too."""
        import jax
        from ..core.tensor import Tensor
        raw = input._data if isinstance(input, Tensor) else input
        prefix = message or (name or "var")
        if isinstance(raw, jax.core.Tracer):
            jax.debug.print(prefix + ": {x}", x=raw)
        else:
            head = " ".join(str(v) for v in
                            __import__("numpy").asarray(raw).ravel()[:summarize])
            shp = f" shape={tuple(raw.shape)}" if print_tensor_shape else ""
            print(f"{prefix}{shp}: {head}")
        return input

    @staticmethod
    def dynamic_rnn(step_fn, inputs, initial_states, lengths=None,
                    name=None):
        """Functional analog of the fluid-era ``DynamicRNN`` block API
        (reference: fluid/layers/control_flow.py DynamicRNN — there an
        imperative ``with drnn.block():`` that appends While ops over LoD
        sequences; see also rnn.py StaticRNN).

        The imperative block cannot be suspended into an XLA loop (a
        python ``with`` body runs exactly once), so the TPU form takes
        the step as a FUNCTION — the same translation the reference
        itself later made with paddle.nn.RNN:

            def step(x_t, h):                 # [B, D_in], states
                h2 = some_layer(x_t, h)
                return h2, h2                 # (output_t, new_states)
            outs, last = static.nn.dynamic_rnn(step, x, h0, lengths)

        ``inputs`` is batch-major [B, T, ...] (the repo-wide padded+
        lengths convention replacing LoD, ops/sequence.py); ``lengths``
        [B] masks the padded tail: outputs beyond a row's length are
        zero and its final state stops updating there, matching
        DynamicRNN's per-sequence early exit. Executes as a python loop
        over the static T (UNROLLED under trace — the step is re-traced
        per timestep; for long sequences prefer nn.RNN, which scans).
        """
        import jax
        from ..core.tensor import Tensor
        from .. import ops as _ops

        is_tensor = lambda t: isinstance(t, Tensor)
        states, state_td = jax.tree_util.tree_flatten(
            initial_states, is_leaf=is_tensor)
        T = int(inputs.shape[1])
        outs = []
        cur = list(states)
        for t in range(T):
            x_t = inputs[:, t]
            st = jax.tree_util.tree_unflatten(state_td, cur)
            o_t, new_st = step_fn(x_t, st)
            new_flat, _ = jax.tree_util.tree_flatten(new_st,
                                                     is_leaf=is_tensor)
            if lengths is not None:
                alive = _ops.cast(
                    _ops.less_than(
                        _ops.full([inputs.shape[0]], float(t), "float32"),
                        _ops.cast(lengths, "float32")), o_t.dtype)
                masks = {}      # one reshape per distinct rank

                def m(rank):
                    if rank not in masks:
                        masks[rank] = _ops.reshape(
                            alive, [-1] + [1] * (rank - 1))
                    return masks[rank]
                o_t = o_t * m(len(o_t.shape))
                new_flat = [n * m(len(n.shape)) +
                            c * (1.0 - m(len(n.shape)))
                            for n, c in zip(new_flat, cur)]
            cur = new_flat
            outs.append(o_t)
        stacked = _ops.stack(outs, axis=1)
        last = jax.tree_util.tree_unflatten(state_td, cur)
        return stacked, last


# -- fluid-era surface tail (reference: paddle/static/__init__.py exports) ---

class Scope:
    """reference: core Scope — variable container. The executor keeps one
    flat dict-backed scope (static/executor.py global_scope)."""

    def __init__(self):
        self._vars = {}

    def var(self, name):
        return self._vars.setdefault(name, None)

    def find_var(self, name):
        return self._vars.get(name)


import contextlib as _ctx  # noqa: E402


@_ctx.contextmanager
def scope_guard(scope):
    """reference: executor.py scope_guard. Honest no-op here: the
    jit-based executor keeps all state per-Program (each Program owns
    its parameters), so there is no process-global variable scope to
    swap — the context only yields the given scope object for code that
    passes it around explicitly."""
    yield scope


@_ctx.contextmanager
def device_guard(device=None):
    """reference: framework.py device_guard — per-op device placement.
    XLA owns placement under jit; the context is accepted and ignored
    (documented no-op, like the reference on unsupported devices)."""
    yield


def cpu_places(device_count=None):
    import jax
    n = device_count or len([d for d in jax.devices()
                             if d.platform == "cpu"]) or 1
    from ..core.device import CPUPlace
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    return []      # no CUDA devices in a TPU build (parity: empty list)


def xpu_places(device_ids=None):
    return []


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..compat_surface import create_parameter as _cp
    return _cp(shape, dtype, name=name, attr=attr, is_bias=is_bias,
               default_initializer=default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """reference: fluid/layers/tensor.py create_global_var."""
    from ..ops.creation import full
    from ..core.tensor import Tensor
    t = full(shape, value, dtype)
    if name:
        t.name = name
    return t


class WeightNormParamAttr(object):
    """reference: fluid/param_attr.py WeightNormParamAttr — ParamAttr
    carrying a weight-norm dim; consumed by nn.utils.weight_norm here."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.trainable = trainable


class ParallelExecutor:
    """reference: parallel_executor.py — superseded by Executor over a
    mesh (static/executor.py shards feeds; GSPMD inserts the grad
    allreduce). Kept as a thin alias so fluid-era scripts construct."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        from .executor import Executor
        self._exe = Executor()
        self._program = main_program

    def run(self, fetch_list=None, feed=None, feed_dict=None,
            return_numpy=True):
        return self._exe.run(program=self._program, feed=feed or feed_dict,
                             fetch_list=fetch_list,
                             return_numpy=return_numpy)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """reference: fluid/layers/nn.py py_func — host-python op. The
    dispatch-level equivalent is ops.custom.register_custom_op (host
    tier); this shim routes a one-off callable through it."""
    from ..ops.custom import register_custom_op
    import uuid
    name = f"py_func_{uuid.uuid4().hex[:8]}"
    fn = register_custom_op(name, func, backward_func)
    xs = x if isinstance(x, (list, tuple)) else [x]
    return fn(*xs)


# program/persistable (de)serialization: the Program here compiles to a
# StableHLO artifact; (de)serialize maps onto jit.save/load files
def serialize_program(feed_vars, fetch_vars, **kwargs):
    raise NotImplementedError(
        "serialize_program: the compiled artifact is StableHLO — use "
        "paddle.static.save_inference_model(path, feed, fetch, exe) / "
        "load_inference_model, or jit.save on a Layer")


serialize_persistables = serialize_program
deserialize_program = serialize_program
deserialize_persistables = serialize_program
normalize_program = serialize_program
save_to_file = serialize_program
load_from_file = serialize_program


def save(program, model_path, protocol=4, **configs):
    """reference: static/io.py save — persist a static Program's
    parameter values (the program itself re-derives from python).
    Parameters are the Program's touched Tensors (graph.py
    all_parameters), keyed by name with positional fallbacks."""
    import pickle
    import numpy as np
    params = list(getattr(program, "all_parameters", list)() or [])
    state = {}
    for i, p in enumerate(params):
        key = getattr(p, "name", None) or f"param_{i}"
        state[key] = np.asarray(p.numpy())
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    """Restore values saved by :func:`save` back into the Program's
    parameters (matched by name, positional fallback)."""
    import os
    import pickle
    import jax.numpy as jnp
    path = model_path + ".pdparams"
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    with open(path, "rb") as f:
        state = pickle.load(f)
    if program is not None and hasattr(program, "all_parameters"):
        for i, p in enumerate(program.all_parameters()):
            key = getattr(p, "name", None) or f"param_{i}"
            if key in state:
                p._data = jnp.asarray(state[key])
                p._inplace_version += 1
    return state


def load_program_state(model_path, var_list=None):
    return load(None, model_path)


def set_program_state(program, state_dict):
    raise NotImplementedError(
        "set_program_state: static Programs re-derive parameters from "
        "python; assign through the Program's variables or use the "
        "dygraph set_state_dict path")


save_vars = save
load_vars = load


def accuracy(input, label, k=1, correct=None, total=None):
    from ..metric import accuracy as _acc
    return _acc(input, label, k=k, correct=correct, total=total)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    from ..metric import Auc
    m = Auc(curve=curve, num_thresholds=num_thresholds)
    m.update(input, label)
    return m.accumulate()


from .. import amp  # noqa: E402,F401
Print = nn.Print
