"""paddle.tensor namespace (reference: python/paddle/tensor/ —
creation/linalg/logic/manipulation/math/random/search/stat modules whose
functions are all re-exported at the paddle top level). The op surface
here lives in paddle_tpu/ops/; this package keeps the `paddle.tensor.*`
import path working for ported code."""
from ..ops import *  # noqa: F401,F403
from ..ops import linalg  # noqa: F401
from ..ops.linalg import cholesky, inverse, matrix_power  # noqa: F401


def rank(input):
    """reference: fluid/layers/nn.py rank — 0-d int tensor of ndim."""
    import numpy as np
    from ..core.tensor import Tensor
    from .. import to_tensor
    n = len(input.shape) if isinstance(input, Tensor) else np.ndim(input)
    return to_tensor(np.asarray(n, np.int32))
