"""Distributed launcher CLI: ``python -m paddle_tpu.distributed.launch``.

TPU-native equivalent of the reference launcher
(reference: python/paddle/distributed/fleet/launch.py:364 launch /
:217 launch_collective; launch_utils.py:267 get_cluster, :452
start_local_trainers, :559 watch_local_trainers, :308
terminate_local_procs).

The env contract is preserved verbatim (PADDLE_TRAINER_ID,
PADDLE_CURRENT_ENDPOINT, PADDLE_TRAINERS_NUM, PADDLE_TRAINER_ENDPOINTS) so
reference launch scripts port unchanged; ``init_parallel_env`` turns it into
``jax.distributed.initialize`` (endpoint[0] = coordinator). On TPU pods the
standard layout is ONE process per host (XLA owns all local chips), so
``--nproc_per_node`` defaults to 1; multi-chip-per-process parallelism is
mesh sharding, not process fan-out.

``--elastic`` switches the watch loop from "any nonzero exit tears the job
down" to a supervisor that restarts failed ranks with exponential backoff +
jitter under a ``--max_restarts`` budget, treats
:data:`~paddle_tpu.distributed.elastic.PREEMPTION_EXIT_CODE` as a free
resume, tails the dead rank's workerlog for diagnosis, and drains children
gracefully on SIGTERM/SIGINT (full contract: docs/fault_tolerance.md).
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

from .elastic import (PREEMPTION_EXIT_CODE, DIVERGENCE_EXIT_CODE,
                      ELASTIC_ENV_VAR, RestartBudget)


def _parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="launch a distributed training job")
    p.add_argument("--ips", type=str, default="127.0.0.1",
                   help="comma-separated host ips (reference: --ips)")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per node (1 per TPU host is standard)")
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", "0")))
    p.add_argument("--start_port", type=int,
                   default=int(os.environ.get("FLAGS_START_PORT", "6070")))
    p.add_argument("--log_dir", type=str, default=None,
                   help="per-rank log files (reference: launch_utils.py:544)")
    p.add_argument("--run_mode", type=str, default="collective")
    p.add_argument("--devices", "--gpus", "--selected_devices", type=str,
                   default=None, dest="devices")
    p.add_argument("--elastic", action="store_true",
                   help="supervise ranks: restart failures instead of "
                        "tearing the job down (docs/fault_tolerance.md)")
    p.add_argument("--max_restarts", type=int, default=3,
                   help="crash-restart budget per job (preemption exits "
                        "are free and do not consume it)")
    p.add_argument("--grace_period", type=float, default=10.0,
                   help="seconds between graceful-drain SIGTERM and SIGKILL")
    p.add_argument("--restart_backoff", type=float,
                   default=float(os.environ.get(
                       "PADDLE_TPU_RESTART_BACKOFF", "1.0")),
                   help="initial restart backoff in seconds (doubles per "
                        "crash, +/-20%% jitter, capped at 30s)")
    # cohort options (elastic_runtime; docs/fault_tolerance.md "Surviving
    # host loss") — all ride on --elastic
    p.add_argument("--step_deadline", type=float, default=0.0,
                   help="guarded-step deadline in seconds: children arm a "
                        "StepWatchdog that converts a hung collective into "
                        "exit 121 (0 = off)")
    p.add_argument("--heartbeat", action="store_true",
                   help="run the HeartbeatCoordinator and arm per-host "
                        "beacons (liveness, step lag, stragglers)")
    p.add_argument("--heartbeat_port", type=int, default=0,
                   help="coordinator port (0 = ephemeral)")
    p.add_argument("--heartbeat_interval", type=float, default=None,
                   help="beacon period in seconds (default "
                        "PADDLE_TPU_HEARTBEAT_INTERVAL or 1.0)")
    p.add_argument("--shrink_on_loss", action="store_true",
                   help="re-form without the lost host instead of "
                        "respawning it (dp degree recomputed from the "
                        "smaller world)")
    p.add_argument("--spare_ips", type=str, default="",
                   help="comma-separated replacement host ips: a lost "
                        "endpoint is substituted from this pool before "
                        "any shrink")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def get_cluster(ips: List[str], nproc_per_node: int, start_port: int):
    """All (ip, port) endpoints, rank-major (reference: get_cluster)."""
    endpoints = []
    for ip in ips:
        for i in range(nproc_per_node):
            endpoints.append(f"{ip}:{start_port + i}")
    return endpoints


def _spawn_rank(rank: int, local_rank: int, endpoints: List[str],
                script: str, script_args: List[str],
                log_dir: Optional[str] = None,
                extra_env: Optional[dict] = None,
                restart_num: int = 0):
    """Spawn one trainer with the PADDLE_* env contract. Restarts append to
    the same workerlog with a separator so the full history stays in one
    file."""
    env = dict(os.environ)
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
        "PADDLE_TRAINERS_NUM": str(len(endpoints)),
        "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
        "FLAGS_selected_devices": str(local_rank),
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_TPU_RESTART_NUM": str(restart_num),
    })
    if extra_env:
        env.update(extra_env)
    cmd = [sys.executable, "-u", script] + list(script_args)
    log_path = None
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(log_dir, f"workerlog.{rank}")
        log_f = open(log_path, "a" if restart_num else "w")
        if restart_num:
            log_f.write(f"\n----- restart {restart_num} -----\n")
            log_f.flush()
        proc = subprocess.Popen(cmd, env=env, stdout=log_f, stderr=log_f)
        proc._log_file = log_f
    else:
        proc = subprocess.Popen(cmd, env=env)
    proc._rank = rank
    proc._local_rank = local_rank
    proc._log_path = log_path
    return proc


def start_local_trainers(endpoints: List[str], node_ips: List[str],
                         node_rank: int, nproc_per_node: int,
                         script: str, script_args: List[str],
                         log_dir: Optional[str] = None,
                         extra_env: Optional[dict] = None):
    """Spawn this node's trainer processes with the PADDLE_* contract
    (reference: launch_utils.py:452)."""
    base_rank = node_rank * nproc_per_node
    return [_spawn_rank(base_rank + lr, lr, endpoints, script, script_args,
                        log_dir, extra_env)
            for lr in range(nproc_per_node)]


def terminate_local_procs(procs, grace_period: float = 5.0):
    """SIGTERM, wait up to ``grace_period``, then SIGKILL
    (reference: launch_utils.py:308)."""
    for p in procs:
        if p.poll() is None:
            p.terminate()
    deadline = time.time() + grace_period
    for p in procs:
        try:
            # Reaping children here is the handler's intended last act
            # before exit; nothing else can run in this process anyway.
            p.wait(timeout=max(0.1, deadline - time.time()))  # noqa: PTA007 -- bounded teardown wait; the supervisor exits right after
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()  # noqa: PTA007 -- SIGKILL already sent; wait only reaps the zombie
    for p in procs:
        f = getattr(p, "_log_file", None)
        if f:
            f.close()


def watch_local_trainers(procs) -> int:
    """Poll children; any nonzero exit tears the job down
    (reference: launch_utils.py:559)."""
    alive = list(procs)
    while alive:
        time.sleep(0.2)
        for p in list(alive):
            ret = p.poll()
            if ret is None:
                continue
            alive.remove(p)
            if ret != 0:
                sys.stderr.write(
                    f"trainer rank {p._rank} exited with code {ret}; "
                    f"terminating the job\n")
                terminate_local_procs(alive)
                return ret
    return 0


def _tail_log(path: Optional[str], lines: int = 40) -> str:
    if not path or not os.path.exists(path):
        return ""
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - 64 * 1024))
            data = f.read().decode("utf-8", errors="replace")
        return "\n".join(data.splitlines()[-lines:])
    except OSError as e:
        return f"<could not read {path}: {e}>"


class ElasticSupervisor:
    """Restart failed ranks instead of tearing the job down.

    Semantics (docs/fault_tolerance.md):

    - exit 0            → rank done, not restarted
    - PREEMPTION_EXIT_CODE → graceful drain; restart for free
    - DIVERGENCE_EXIT_CODE → sentinel halted a deterministic numerical
      divergence; never restarted (same state → same NaNs), job torn down
    - other nonzero     → crash; restart with exponential backoff + jitter
      while the shared ``max_restarts`` budget lasts, else tear down and
      propagate that exit code
    - SIGTERM/SIGINT on the supervisor → forward SIGTERM to children
      (their PreemptionGuard commits a final checkpoint), wait
      ``grace_period``, SIGKILL stragglers
    """

    def __init__(self, endpoints, script, script_args, log_dir=None,
                 max_restarts=3, grace_period=10.0, restart_backoff=1.0,
                 extra_env=None, poll_interval=0.2, sleep=time.sleep,
                 node_rank=0, nproc_per_node=None):
        self.endpoints = endpoints
        self.node_rank = int(node_rank)
        self.nproc_per_node = (len(endpoints) if nproc_per_node is None
                               else int(nproc_per_node))
        self.script = script
        self.script_args = script_args
        self.log_dir = log_dir
        self.max_restarts = int(max_restarts)
        self.grace_period = float(grace_period)
        self.backoff0 = float(restart_backoff)
        self.poll_interval = poll_interval
        self._sleep = sleep
        self.extra_env = dict(extra_env or {})
        self.extra_env.setdefault(ELASTIC_ENV_VAR, "1")
        # shared accounting object — the serving replica Router reuses the
        # same RestartBudget semantics for replica resurrection
        self.budget = RestartBudget(self.max_restarts, self.backoff0)
        self._drain = False
        self._restart_counts = {}   # rank -> total respawns (incl. free)

    @property
    def restarts_used(self) -> int:
        return self.budget.used

    def request_drain(self, signum=None, frame=None):
        self._drain = True

    def _respawn(self, dead):
        rank = dead._rank
        f = getattr(dead, "_log_file", None)
        if f:
            f.close()
        n = self._restart_counts.get(rank, 0) + 1
        self._restart_counts[rank] = n
        return _spawn_rank(rank, dead._local_rank, self.endpoints,
                           self.script, self.script_args, self.log_dir,
                           self.extra_env, restart_num=n)

    def run(self) -> int:
        alive = start_local_trainers(
            self.endpoints, None, self.node_rank, self.nproc_per_node,
            self.script, self.script_args, self.log_dir, self.extra_env)
        prev = {}
        for sig in (signal.SIGTERM, signal.SIGINT):
            prev[sig] = signal.signal(sig, self.request_drain)
        try:
            while alive:
                if self._drain:
                    sys.stderr.write(
                        "elastic supervisor: draining "
                        f"{len(alive)} rank(s) (grace "
                        f"{self.grace_period}s)\n")
                    terminate_local_procs(alive, self.grace_period)
                    return 1
                self._sleep(self.poll_interval)
                for p in list(alive):
                    ret = p.poll()
                    if ret is None:
                        continue
                    alive.remove(p)
                    f = getattr(p, "_log_file", None)
                    if f:
                        f.close()
                    if ret == 0:
                        continue
                    tail = _tail_log(p._log_path)
                    if tail:
                        sys.stderr.write(
                            f"----- workerlog.{p._rank} (tail) -----\n"
                            f"{tail}\n----- end workerlog.{p._rank} -----\n")
                    if ret == PREEMPTION_EXIT_CODE:
                        sys.stderr.write(
                            f"rank {p._rank} drained after preemption "
                            f"(exit {ret}); restarting (free — budget "
                            f"{self.max_restarts - self.restarts_used} "
                            f"left)\n")
                        alive.append(self._respawn(p))
                        continue
                    if ret == DIVERGENCE_EXIT_CODE:
                        # the sentinel halted a deterministic divergence:
                        # the same state replays the same NaNs, so a
                        # restart only burns budget — tear down instead
                        sys.stderr.write(
                            f"rank {p._rank} halted on numerical "
                            f"divergence (exit {ret}); not restarting — "
                            f"terminating the job\n")
                        terminate_local_procs(alive, self.grace_period)
                        return ret
                    if not self.budget.try_consume():
                        sys.stderr.write(
                            f"rank {p._rank} exited with code {ret}; "
                            f"restart budget ({self.max_restarts}) "
                            f"exhausted — terminating the job\n")
                        terminate_local_procs(alive, self.grace_period)
                        return ret
                    pause = self.budget.pause()
                    sys.stderr.write(
                        f"rank {p._rank} exited with code {ret}; "
                        f"restarting in {pause:.2f}s "
                        f"({self.restarts_used}/{self.max_restarts} "
                        f"restarts used)\n")
                    self._sleep(pause)
                    if self._drain:
                        break
                    alive.append(self._respawn(p))
            return 0
        finally:
            for sig, h in prev.items():
                signal.signal(sig, h)
            terminate_local_procs(alive, self.grace_period)


def launch(argv=None) -> int:
    args = _parse_args(argv)
    ips = [ip.strip() for ip in args.ips.split(",") if ip.strip()]
    endpoints = get_cluster(ips, args.nproc_per_node, args.start_port)

    if args.elastic:
        # the cohort supervisor subsumes ElasticSupervisor: identical
        # per-rank semantics for single-rank worlds, whole-cohort
        # re-formation for multi-rank ones and for exit 121 (imported
        # lazily — elastic_runtime pulls observability, which plain
        # non-elastic launches never need)
        from .elastic_runtime.cohort import CohortSupervisor
        spares = []
        for ip in args.spare_ips.split(","):
            ip = ip.strip()
            if ip:
                spares.extend(f"{ip}:{args.start_port + i}"
                              for i in range(args.nproc_per_node))
        sup = CohortSupervisor(
            endpoints, args.training_script, args.training_script_args,
            log_dir=args.log_dir, max_restarts=args.max_restarts,
            grace_period=args.grace_period,
            restart_backoff=args.restart_backoff,
            node_rank=args.node_rank, nproc_per_node=args.nproc_per_node,
            step_deadline=args.step_deadline, heartbeat=args.heartbeat,
            heartbeat_port=args.heartbeat_port,
            heartbeat_interval=args.heartbeat_interval,
            shrink_on_loss=args.shrink_on_loss, spare_endpoints=spares)
        return sup.run()

    procs = start_local_trainers(
        endpoints, ips, args.node_rank, args.nproc_per_node,
        args.training_script, args.training_script_args, args.log_dir)

    def _sig(_signum, _frame):
        terminate_local_procs(procs, args.grace_period)
        sys.exit(1)

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    rc = watch_local_trainers(procs)
    terminate_local_procs(procs)
    return rc


if __name__ == "__main__":
    sys.exit(launch())
