"""Distributed launcher CLI: ``python -m paddle_tpu.distributed.launch``.

TPU-native equivalent of the reference launcher
(reference: python/paddle/distributed/fleet/launch.py:364 launch /
:217 launch_collective; launch_utils.py:267 get_cluster, :452
start_local_trainers, :559 watch_local_trainers, :308
terminate_local_procs).

The env contract is preserved verbatim (PADDLE_TRAINER_ID,
PADDLE_CURRENT_ENDPOINT, PADDLE_TRAINERS_NUM, PADDLE_TRAINER_ENDPOINTS) so
reference launch scripts port unchanged; ``init_parallel_env`` turns it into
``jax.distributed.initialize`` (endpoint[0] = coordinator). On TPU pods the
standard layout is ONE process per host (XLA owns all local chips), so
``--nproc_per_node`` defaults to 1; multi-chip-per-process parallelism is
mesh sharding, not process fan-out.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional


def _parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="launch a distributed training job")
    p.add_argument("--ips", type=str, default="127.0.0.1",
                   help="comma-separated host ips (reference: --ips)")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per node (1 per TPU host is standard)")
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", "0")))
    p.add_argument("--start_port", type=int,
                   default=int(os.environ.get("FLAGS_START_PORT", "6070")))
    p.add_argument("--log_dir", type=str, default=None,
                   help="per-rank log files (reference: launch_utils.py:544)")
    p.add_argument("--run_mode", type=str, default="collective")
    p.add_argument("--devices", "--gpus", "--selected_devices", type=str,
                   default=None, dest="devices")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def get_cluster(ips: List[str], nproc_per_node: int, start_port: int):
    """All (ip, port) endpoints, rank-major (reference: get_cluster)."""
    endpoints = []
    for ip in ips:
        for i in range(nproc_per_node):
            endpoints.append(f"{ip}:{start_port + i}")
    return endpoints


def start_local_trainers(endpoints: List[str], node_ips: List[str],
                         node_rank: int, nproc_per_node: int,
                         script: str, script_args: List[str],
                         log_dir: Optional[str] = None,
                         extra_env: Optional[dict] = None):
    """Spawn this node's trainer processes with the PADDLE_* contract
    (reference: launch_utils.py:452)."""
    procs = []
    base_rank = node_rank * nproc_per_node
    for local_rank in range(nproc_per_node):
        rank = base_rank + local_rank
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
            "PADDLE_TRAINERS_NUM": str(len(endpoints)),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
            "FLAGS_selected_devices": str(local_rank),
            "PADDLE_LOCAL_RANK": str(local_rank),
        })
        if extra_env:
            env.update(extra_env)
        cmd = [sys.executable, "-u", script] + list(script_args)
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            log_f = open(os.path.join(log_dir, f"workerlog.{rank}"), "w")
            proc = subprocess.Popen(cmd, env=env, stdout=log_f, stderr=log_f)
            proc._log_file = log_f
        else:
            proc = subprocess.Popen(cmd, env=env)
        proc._rank = rank
        procs.append(proc)
    return procs


def terminate_local_procs(procs):
    """SIGTERM then SIGKILL (reference: launch_utils.py:308)."""
    for p in procs:
        if p.poll() is None:
            p.terminate()
    deadline = time.time() + 5
    for p in procs:
        try:
            p.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            p.kill()
    for p in procs:
        f = getattr(p, "_log_file", None)
        if f:
            f.close()


def watch_local_trainers(procs) -> int:
    """Poll children; any nonzero exit tears the job down
    (reference: launch_utils.py:559)."""
    alive = list(procs)
    while alive:
        time.sleep(0.2)
        for p in list(alive):
            ret = p.poll()
            if ret is None:
                continue
            alive.remove(p)
            if ret != 0:
                sys.stderr.write(
                    f"trainer rank {p._rank} exited with code {ret}; "
                    f"terminating the job\n")
                terminate_local_procs(alive)
                return ret
    return 0


def launch(argv=None) -> int:
    args = _parse_args(argv)
    ips = [ip.strip() for ip in args.ips.split(",") if ip.strip()]
    endpoints = get_cluster(ips, args.nproc_per_node, args.start_port)
    procs = start_local_trainers(
        endpoints, ips, args.node_rank, args.nproc_per_node,
        args.training_script, args.training_script_args, args.log_dir)

    def _sig(_signum, _frame):
        terminate_local_procs(procs)
        sys.exit(1)

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    rc = watch_local_trainers(procs)
    terminate_local_procs(procs)
    return rc


if __name__ == "__main__":
    sys.exit(launch())
