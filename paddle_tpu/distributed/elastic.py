"""Preemption-aware elastic training support.

The trainer half of the elastic contract (supervisor half:
``paddle_tpu.distributed.launch --elastic``; full contract:
docs/fault_tolerance.md). A :class:`PreemptionGuard` arms SIGTERM/SIGINT so
the training loop can observe "the platform wants this process gone", commit
a final checkpoint, and exit with :data:`PREEMPTION_EXIT_CODE` — which the
supervisor treats as "restart for free, don't burn the restart budget"
(reference analog: EDL's auto-checkpoint + launch_utils watch loop, which
only ever tears the whole job down; here preemption becomes a resumable
event instead).

Import-light on purpose: the guard must be usable before any backend touch.
"""
from __future__ import annotations

import os
import signal
import sys
import threading
from typing import Callable, Optional, Sequence

#: Reserved exit code for "drained after preemption; resume me". Chosen
#: outside the shell (126-128) and signal (128+N) ranges and unlikely to
#: collide with user scripts. The elastic supervisor restarts this rank
#: without counting it against --max_restarts.
PREEMPTION_EXIT_CODE = 117

#: Exit code the numerical-anomaly sentinel uses for a *deterministic*
#: divergence (``halt`` rung). Unlike a preemption (free restart) or a crash
#: (budgeted restart), a diverged run would diverge again from the same
#: state, so the supervisor tears the job down instead of respawning.
DIVERGENCE_EXIT_CODE = 119

#: Exit code the StepWatchdog (elastic_runtime.watchdog) uses when a guarded
#: train step blows its deadline — the signature of a peer host dying
#: mid-collective (the survivors don't crash, they stall forever inside the
#: allreduce). The cohort supervisor treats it as "a peer is gone": it tears
#: down ALL local workers, bumps the cohort generation, and re-forms the
#: world, instead of respawning the one rank that happened to notice.
HOST_LOST_EXIT_CODE = 121

#: Env var the elastic supervisor sets in every child so training loops can
#: auto-arm a PreemptionGuard without code changes.
ELASTIC_ENV_VAR = "PADDLE_TPU_ELASTIC"

_DEFAULT_SIGNALS = (signal.SIGTERM, signal.SIGINT)


def under_elastic_supervisor() -> bool:
    return bool(os.environ.get(ELASTIC_ENV_VAR))


class ChainedSignalHandler:
    """Install a callback on signals WITHOUT clobbering what was there.

    Several subsystems legitimately want the same signals — the elastic
    :class:`PreemptionGuard` arms SIGTERM for checkpoint-then-exit, and the
    serving ``Engine`` arms SIGTERM for graceful drain. A plain
    ``signal.signal`` call from the second one silently disables the first.
    This helper saves the previous handler at install time and invokes it
    *after* the callback, so every interested party observes the signal;
    :meth:`uninstall` restores the saved handlers.

    Installation is a no-op off the main thread (CPython only delivers
    signals to the main thread, and ``signal.signal`` raises elsewhere).
    """

    def __init__(self, callback: Callable[[int, object], None],
                 signals: Sequence[int] = _DEFAULT_SIGNALS):
        self._callback = callback
        self._signals = tuple(signals)
        self._prev = {}
        self._installed = False

    @property
    def installed(self) -> bool:
        return self._installed

    def install(self):
        if (self._installed
                or threading.current_thread() is not threading.main_thread()):
            return self
        for sig in self._signals:
            self._prev[sig] = signal.signal(sig, self._on_signal)
        self._installed = True
        return self

    def uninstall(self):
        """Restore the handlers saved at install time — but only where we
        are still the current handler. If a third party re-registered a
        signal after our install, blindly restoring would silently disable
        *them* (the exact clobbering this class exists to prevent), so
        their handler is left in place."""
        if not self._installed:
            return
        for sig, prev in self._prev.items():
            # == not `is`: each `self._on_signal` access builds a fresh
            # bound method; equality compares __self__ and __func__
            if signal.getsignal(sig) == self._on_signal:
                signal.signal(sig, prev)
        self._prev.clear()
        self._installed = False

    def _on_signal(self, signum, frame):
        self._callback(signum, frame)
        prev = self._prev.get(signum)
        if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL):
            prev(signum, frame)


class PreemptionGuard:
    """Signal-armed preemption flag for training loops.

    ::

        guard = PreemptionGuard()          # arms SIGTERM/SIGINT
        for epoch in epochs:
            train_one_epoch(...)
            guard.exit_if_preempted(save_fn=lambda: ckpt.save(epoch))

    The handler only sets a flag (async-signal-safe); all real work — the
    final checkpoint, the exit — happens at the next poll point in the
    training loop, so a preemption can never tear a half-written shard.
    Previous handlers are chained, and :meth:`uninstall` restores them.
    """

    def __init__(self, signals: Sequence[int] = _DEFAULT_SIGNALS,
                 install: bool = True):
        self._event = threading.Event()
        self._chain = ChainedSignalHandler(self._handler, signals)
        if install:
            self.install()

    # -- signal plumbing ----------------------------------------------------
    def install(self):
        self._chain.install()
        return self

    def uninstall(self):
        self._chain.uninstall()

    @property
    def _installed(self) -> bool:  # kept for older callers/tests
        return self._chain.installed

    def _handler(self, signum, frame):
        self._event.set()

    # -- polling API --------------------------------------------------------
    @property
    def preempted(self) -> bool:
        return self._event.is_set()

    def should_stop(self) -> bool:
        return self.preempted

    def preempt(self):
        """Mark preemption programmatically (tests, cloud-notice pollers)."""
        self._event.set()

    def exit_if_preempted(self, save_fn: Optional[Callable[[], None]] = None,
                          code: int = PREEMPTION_EXIT_CODE):
        """At a safe point: if preempted, run ``save_fn`` (the final
        checkpoint commit) and exit with the reserved resume code."""
        if not self.preempted:
            return
        if save_fn is not None:
            save_fn()
        self.uninstall()
        sys.exit(code)

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False


class RestartBudget:
    """Thread-safe restart accounting shared by the elastic supervisor and
    the serving replica router.

    One consumer (the supervisor) respawns a whole training job; the other
    (the router's health loop) resurrects individual replica workers. Both
    want the same semantics: a hard cap on non-preemption restarts plus
    exponential backoff with ±20% jitter, capped — so thundering-herd
    resurrections after a shared fault are decorrelated. ``try_consume``
    atomically claims one restart (False when exhausted); ``pause`` derives
    the backoff from how many restarts have been consumed so far.
    """

    def __init__(self, max_restarts: int = 3, backoff: float = 1.0,
                 cap: float = 30.0, rng=None):
        import random as _random
        self.max_restarts = int(max_restarts)
        self.backoff0 = float(backoff)
        self.cap = float(cap)
        self._rng = rng if rng is not None else _random.Random()
        self._lock = threading.Lock()
        self._used = 0

    @property
    def used(self) -> int:
        with self._lock:
            return self._used

    @property
    def remaining(self) -> int:
        with self._lock:
            return max(0, self.max_restarts - self._used)

    def try_consume(self) -> bool:
        """Atomically claim one restart; False when the budget is spent."""
        with self._lock:
            if self._used >= self.max_restarts:
                return False
            self._used += 1
            return True

    def pause(self) -> float:
        """Backoff for the restart just consumed: ``backoff * 2**(used-1)``
        capped, with ±20% jitter (same curve the supervisor always used)."""
        with self._lock:
            used = self._used
        base = min(self.backoff0 * (2 ** max(0, used - 1)), self.cap)
        return base * (1.0 + 0.2 * (2.0 * self._rng.random() - 1.0))

    def __repr__(self):
        return (f"RestartBudget(used={self.used}/{self.max_restarts}, "
                f"backoff={self.backoff0}, cap={self.cap})")


def maybe_auto_guard(guard: Optional[PreemptionGuard]) -> Optional[PreemptionGuard]:
    """Return ``guard``, or a fresh one when running under the elastic
    supervisor (which sets :data:`ELASTIC_ENV_VAR` in every child)."""
    if guard is not None:
        return guard
    if under_elastic_supervisor():
        return PreemptionGuard()
    return None
