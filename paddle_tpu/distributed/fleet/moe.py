"""Mixture-of-Experts with expert parallelism over an "ep" mesh axis.

The reference snapshot predates its MoE work (no expert-parallel code in
the tree); like sequence parallelism this is the parity-plus capability
the TPU build plan treats as first-class: expert weights are sharded over
"ep" (each rank owns E/ep experts) and tokens travel to their expert's
rank and back via two all_to_alls over ICI — the TPU-native form of the
reference-era brpc PS "send the row to its shard" idea applied to dense
expert FFNs.

Routing is Switch-style top-1 with a fixed per-expert capacity so every
shape is static: a token over capacity is dropped (its output is the
residual zero), the standard trade for one compiled program.
Differentiable end to end (the dispatch/combine tensors are one-hots
weighted by the gate probability, so gate grads flow).
"""
from __future__ import annotations

import math
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import mesh as _mesh


def _moe_local(x, wg, w1, w2, axis: str, capacity: int):
    """Runs INSIDE shard_map. x [Nl, D] local tokens; wg [D, E] replicated
    gate; w1 [El, D, F], w2 [El, F, D] this rank's experts. Returns
    [Nl, D] plus the load-balancing aux loss."""
    ep = lax.axis_size(axis)
    Nl, D = x.shape
    El = w1.shape[0]
    E = El * ep

    logits = x @ wg                                    # [Nl, E]
    probs = jax.nn.softmax(logits, axis=-1)
    gate_p = jnp.max(probs, axis=-1)                   # top-1 prob
    expert = jnp.argmax(probs, axis=-1)                # [Nl]

    # Position of each token within its expert's queue (0-based). Counted
    # in int32: bf16 inputs can't represent integers past 256, so a
    # x.dtype cumsum would collide capacity slots for >256 local tokens.
    onehot_i = jax.nn.one_hot(expert, E, dtype=jnp.int32)
    onehot = onehot_i.astype(x.dtype)                  # [Nl, E]
    pos = jnp.cumsum(onehot_i, axis=0) * onehot_i - 1  # [Nl, E] int32
    keep = (pos >= 0) & (pos < capacity)
    slot = jax.nn.one_hot(pos, capacity, dtype=x.dtype)  # [Nl, E, C]
    dispatch = slot * keep.astype(x.dtype)[..., None]  # [Nl, E, C]
    combine = dispatch * gate_p[:, None, None]

    # gather expert inputs [E, C, D], then all_to_all so each rank holds
    # ITS experts' tokens from every rank: [E, C, D] -> [ep, El, C, D]
    exp_in = jnp.einsum("nec,nd->ecd", dispatch, x)
    exp_in = exp_in.reshape(ep, El, capacity, D)
    exp_in = lax.all_to_all(exp_in, axis, split_axis=0, concat_axis=0,
                            tiled=False)               # [ep, El, C, D]
    exp_in = jnp.swapaxes(exp_in, 0, 1).reshape(El, ep * capacity, D)

    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", exp_in, w1))
    out = jnp.einsum("ecf,efd->ecd", h, w2)            # [El, ep*C, D]

    out = jnp.swapaxes(out.reshape(El, ep, capacity, D), 0, 1)
    out = lax.all_to_all(out, axis, split_axis=0, concat_axis=0,
                         tiled=False)                  # [ep, El, C, D]
    out = out.reshape(E, capacity, D)
    y = jnp.einsum("nec,ecd->nd", combine, out)        # [Nl, D]

    # Switch aux loss: E * sum_e fraction_tokens_e * mean_prob_e
    frac = jnp.mean(onehot, axis=0)
    mean_p = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac * mean_p)
    return y, aux


def moe_ffn(x, gate_w, expert_w1, expert_w2, mesh=None, axis: str = "ep",
            capacity_factor: float = 1.25):
    """Expert-parallel Switch FFN.

    x GLOBAL [B, T, D] (batch sharded over ``axis``); gate_w [D, E]
    replicated; expert_w1 [E, D, F] / expert_w2 [E, F, D] sharded on the
    expert dim over ``axis``. Returns ([B, T, D], aux_loss).
    """
    m = mesh or _mesh.ensure_mesh()
    ep = int(m.shape[axis])  # noqa: PTA001 -- mesh axis size is a static host int, never a tracer
    B, T, D = x.shape
    E = expert_w1.shape[0]
    if E % ep != 0:
        raise ValueError(f"{E} experts not divisible by ep={ep}")
    n_local = (B // ep) * T
    capacity = max(1, int(math.ceil(n_local * capacity_factor / E)))  # noqa: PTA001 -- static shapes × config float, concrete at trace time

    def per_rank(xb, wg, w1, w2):
        Bl = xb.shape[0]
        y, aux = _moe_local(xb.reshape(Bl * T, D), wg, w1, w2, axis,
                            capacity)
        return y.reshape(Bl, T, D), lax.pmean(aux, axis)

    fn = jax.shard_map(
        per_rank, mesh=m,
        in_specs=(P(axis, None, None), P(), P(axis, None, None),
                  P(axis, None, None)),
        out_specs=(P(axis, None, None), P()))
    return fn(x, gate_w, expert_w1, expert_w2)


def _moe_impl(xx, wg, w1, w2, axis="ep", capacity_factor=1.25):
    # module-level for eager-cache keyability (see _ring_impl)
    return moe_ffn(xx, wg, w1, w2, mesh=None, axis=axis,
                   capacity_factor=capacity_factor)


class MoELayer:
    """Functional expert-parallel layer over raw param arrays (models own
    their params; this owns the schedule — mirrors RingAttention). Uses
    the ambient mesh."""

    def __init__(self, mesh=None, axis: str = "ep",
                 capacity_factor: float = 1.25):
        if mesh is not None and mesh is not _mesh.get_mesh():
            raise ValueError(
                "MoELayer uses the ambient mesh (set_mesh); pass mesh= "
                "only to moe_ffn directly")
        self._axis = axis
        self._cf = capacity_factor

    def __call__(self, x, gate_w, expert_w1, expert_w2):
        from ...ops.dispatch import apply
        return apply("moe_ffn", _moe_impl, x, gate_w, expert_w1, expert_w2,
                     axis=self._axis, capacity_factor=self._cf)
