"""Tensor-parallel layers over the "mp" mesh axis.

TPU-native equivalent of the reference's Megatron-style parallel layers
(reference: python/paddle/distributed/fleet/meta_parallel/parallel_layers/
mp_layers.py — VocabParallelEmbedding :29, ColumnParallelLinear :96,
RowParallelLinear :169; collective helpers c_identity/_mp_allreduce/c_concat
from operators/collective/).

Design: the reference materializes PER-RANK weight shards and inserts
explicit collectives. Here each layer owns the *global* weight annotated
with a PartitionSpec over "mp"; forward pins activations with sharding
constraints and XLA's SPMD partitioner derives the same compute/collective
pattern (identity forward + allreduce backward for column, allreduce
forward for row) — provably the same math, with the partitioner free to
fuse/overlap the collectives on ICI.

gather_output / input_is_parallel keep their reference meanings, expressed
as the sharding of the returned/accepted activation:
- ColumnParallelLinear(gather_output=False) returns y pinned to
  P(..., "mp") (each mp rank holds its output columns);
- RowParallelLinear(input_is_parallel=True) accepts x pinned to
  P(..., "mp") and returns the replicated (allreduced) result.
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax
from jax.sharding import PartitionSpec as P

from ...core.tensor import Tensor
from ...nn.layer_base import Layer
from ...nn import functional as F
from ...nn import initializer as I
from ...ops.dispatch import apply
from .. import mesh as _mesh


def _mp_size() -> int:
    m = _mesh.get_mesh()
    if m is None or "mp" not in m.axis_names:
        return 1
    return int(m.shape["mp"])


def _pin(x, *spec_axes):
    """Sharding-constrain a Tensor (no-op without an mp axis)."""
    if _mp_size() <= 1:
        return x
    spec = P(*spec_axes)
    return apply("c_identity",
                 lambda r: _mesh.constrain(r, spec), x)


def _shard_param(p: Tensor, spec_axes):
    if _mp_size() > 1:
        _mesh.shard_tensor(p, P(*spec_axes))
    return p


class VocabParallelEmbedding(Layer):
    """reference: mp_layers.py:29 — embedding table sharded on the vocab dim."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.XavierNormal())
        _shard_param(self.weight, ("mp", None))

    def forward(self, x):
        out = F.embedding(x, self.weight)
        # gathered result is replicated (reference: c_allreduce after the
        # masked local lookup)
        return _pin(out, *((None,) * (len(out.shape) - 1) + (None,)))


class ColumnParallelLinear(Layer):
    """reference: mp_layers.py:96 — weight split along the output dim."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        if out_features % max(_mp_size(), 1) != 0:
            raise ValueError(
                f"out_features {out_features} not divisible by mp degree "
                f"{_mp_size()}")
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        _shard_param(self.weight, (None, "mp"))
        self.bias = self.create_parameter(
            [out_features], attr=None, is_bias=True) if has_bias else None
        if self.bias is not None:
            _shard_param(self.bias, ("mp",))

    def forward(self, x):
        # input must be replicated (c_identity in the reference = identity
        # fwd, allreduce bwd — exactly what the partitioner derives)
        y = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            return _pin(y, *((None,) * len(y.shape)))
        return _pin(y, *((None,) * (len(y.shape) - 1) + ("mp",)))


class RowParallelLinear(Layer):
    """reference: mp_layers.py:169 — weight split along the input dim."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        if in_features % max(_mp_size(), 1) != 0:
            raise ValueError(
                f"in_features {in_features} not divisible by mp degree "
                f"{_mp_size()}")
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        _shard_param(self.weight, ("mp", None))
        # bias is applied after the reduction, kept replicated (reference
        # adds it post-allreduce)
        self.bias = self.create_parameter(
            [out_features], attr=None, is_bias=True) if has_bias else None

    def forward(self, x):
        if self.input_is_parallel:
            x = _pin(x, *((None,) * (len(x.shape) - 1) + ("mp",)))
        y = F.linear(x, self.weight, None)
        y = _pin(y, *((None,) * len(y.shape)))  # replicated ⇒ psum inserted
        if self.bias is not None:
            y = y + self.bias
        return y


class ParallelCrossEntropy(Layer):
    """reference: mp_layers.py ParallelCrossEntropy — softmax CE over
    mp-sharded logits. With global-weight semantics the plain CE is already
    correct; the constraint keeps the logits sharded through the loss."""

    def __init__(self, mp_group=None, name=None):
        super().__init__()

    def forward(self, logits, labels):
        logits = _pin(logits, *((None,) * (len(logits.shape) - 1) + ("mp",)))
        return F.cross_entropy(logits, labels)


# named RNG streams for parallel dropout — the core generator already
# implements the reference's RNGStatesTracker (parallel_layers/random.py:30)
from ...core.generator import get_rng_state_tracker  # noqa: E402,F401


def model_parallel_random_seed(seed):
    """reference: parallel_layers/random.py model_parallel_random_seed."""
    tracker = get_rng_state_tracker()
    tracker.reset()
    tracker.add("model_parallel_rng", int(seed))


def split(x, size, operation, axis=0, num_partitions=None, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """reference: python/paddle/distributed/collective.py:1154 ``split`` —
    the one-call model-parallel layer builder for ported static scripts:
    creates the partitioned weight and applies the parallel op.

    operation='linear': size=(in, out); axis=1 → column-parallel (output
    split), axis=0 → row-parallel (input split).
    operation='embedding': size=(vocab, hidden); the table is
    vocab-partitioned.

    Like the reference, this is a *builder* (creates parameters) meant to
    be called once at model-construction time; reuse the returned layer's
    parameters for repeated application by building the layer directly
    (Column/RowParallelLinear / VocabParallelEmbedding).
    """
    if num_partitions is not None and num_partitions != max(_mp_size(), 1):
        raise ValueError(
            f"num_partitions={num_partitions} does not match the mesh's "
            f"mp degree {_mp_size()}")
    if bias_attr not in (None, False, True):
        # the TP layers take has_bias only; a custom bias initializer
        # would be silently dropped — refuse instead
        raise NotImplementedError(
            "split() supports bias_attr None/True/False; build the "
            "Column/RowParallelLinear directly for a custom bias attr")
    if operation == "linear":
        in_f, out_f = size
        if axis == 1:
            layer = ColumnParallelLinear(
                in_f, out_f, weight_attr=weight_attr,
                has_bias=bias_attr is not False, gather_output=gather_out)
        elif axis == 0:
            layer = RowParallelLinear(
                in_f, out_f, weight_attr=weight_attr,
                has_bias=bias_attr is not False)
        else:
            raise ValueError("linear split axis must be 0 or 1")
        return layer(x)
    if operation == "embedding":
        if bias_attr not in (None, False):
            raise ValueError("embedding split takes no bias")
        vocab, hidden = size
        layer = VocabParallelEmbedding(vocab, hidden,
                                       weight_attr=weight_attr)
        return layer(x)
    raise ValueError(f"unknown split operation {operation!r}")
