"""Auditable-entrypoint registrations for the fleet's mesh programs.

The trace tier (PTA009/PTA010/PTA012) only sees programs that register
here. ``bench_audit`` covers the dp×sp ring-flash path and
``distributed.collective`` covers compressed allreduce; this module adds
the two remaining mesh topologies ROADMAP item 3 composes — the pipeline
("pp" ppermute chain + boundary psum/pmean) and the MoE expert mesh
("ep" all_to_all dispatch/combine pair) — so the collective-schedule
audit gates all four. Shapes are tiny and the meshes adapt to however
many (virtual CPU) devices the audit process has, down to a 1-device
fallback.
"""
from __future__ import annotations


def _audit_pipeline_spec():
    """GPipe train step over a ("pp",) mesh: S stacked residual blocks,
    one per stage, microbatches rotating through the ppermute chain with
    a log-softmax loss on the exiting microbatch (head_takes_input, as
    the grads-parity test drives it). The schedule PTA012 should see:
    per-tick ppermute shifts under the scan plus the boundary
    psum/pmean — all rank-uniform."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from ...core import audit
    from . import pipeline_engine as PE

    devices = np.array(jax.devices())  # noqa: PTA002 -- host-side device-list layout at audit registration, not a step path
    S = devices.size
    mesh = jax.sharding.Mesh(devices.reshape(S), ("pp",))
    M, mb, seq, d, V = 2 * S, 2, 6, 16, 32

    def embed_fn(p, ids):
        return p["tok"][ids]

    def block_fn(p, h):
        return h + jnp.tanh(h @ p["w"])

    def head_fn(p, h, labels):
        lo = jax.nn.log_softmax(h @ p["wo"])
        return -jnp.mean(jnp.take_along_axis(lo, labels[..., None],
                                             axis=-1))

    def train_step(params, xs):
        def loss_fn(ps):
            emb, blocks, head = ps
            losses = PE.gpipe_blocks(embed_fn, block_fn, head_fn, emb,
                                     blocks, head, xs, mesh=mesh,
                                     head_takes_input=True)
            return jnp.mean(losses)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        new = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, params,
                                     grads)
        return new, loss

    def make_args(variant):
        # fresh params per call: donate_argnums=(0,) consumes them
        rng = np.random.default_rng(31 + variant)
        emb = {"tok": jnp.asarray(rng.standard_normal((V, d)) * 0.1,
                                  jnp.float32)}
        blocks = {"w": jnp.asarray(rng.standard_normal((S, d, d)) * 0.1,
                                   jnp.float32)}
        head = {"wo": jnp.asarray(rng.standard_normal((d, V)) * 0.1,
                                  jnp.float32)}
        xs = jnp.asarray(rng.integers(0, V, (M, mb, seq)), jnp.int32)
        return ((emb, blocks, head), xs)

    return audit.AuditSpec(fn=train_step, make_args=make_args,
                           jit_kwargs={"donate_argnums": (0,)})


def _audit_moe_spec():
    """MoE FFN train step over an ("ep",) expert mesh: top-1 dispatch
    all_to_all, per-expert FFN, combine all_to_all, aux-loss pmean. The
    two all_to_alls are the transpose-consistency pair PTA012 checks;
    wire bytes scale with capacity so the collective_bytes gate catches
    capacity-factor regressions."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from ...core import audit
    from .moe import moe_ffn

    devices = np.array(jax.devices())  # noqa: PTA002 -- host-side device-list layout at audit registration, not a step path
    ep = devices.size
    mesh = jax.sharding.Mesh(devices.reshape(ep), ("ep",))
    B, T, D, F = 2 * ep, 4, 16, 32
    E = 2 * ep                         # experts per rank = 2

    def train_step(params, x, y):
        def loss_fn(ps):
            wg, w1, w2 = ps
            out, aux = moe_ffn(x, wg, w1, w2, mesh=mesh, axis="ep",
                               capacity_factor=2.0)
            return jnp.mean((out - y) ** 2) + 0.01 * aux

        loss, grads = jax.value_and_grad(loss_fn)(params)
        new = tuple(p - 0.1 * g for p, g in zip(params, grads))
        return new, loss

    def make_args(variant):
        rng = np.random.default_rng(37 + variant)

        def w(*shape):
            return jnp.asarray(rng.standard_normal(shape) * 0.1,
                               jnp.float32)

        params = (w(D, E), w(E, D, F), w(E, F, D))
        x = jnp.asarray(rng.standard_normal((B, T, D)), jnp.float32)
        y = jnp.asarray(rng.standard_normal((B, T, D)), jnp.float32)
        return (params, x, y)

    return audit.AuditSpec(fn=train_step, make_args=make_args,
                           jit_kwargs={"donate_argnums": (0,)})


def _register_audit_entrypoints():
    from ...core import audit
    audit.register_entrypoint("pipeline_train_step", _audit_pipeline_spec,
                              tags=("train", "bench", "distributed"))
    audit.register_entrypoint("moe_train_step", _audit_moe_spec,
                              tags=("train", "bench", "distributed"))


_register_audit_entrypoints()
