"""Sequence/context parallelism: ring attention over an "sp" mesh axis.

The reference snapshot has NO sequence parallelism (SURVEY §5.7 verified
absent — long sequences are handled only by recompute+sharding+pipeline);
this module is the parity-plus capability the TPU build plan calls for:
scale *sequence length* across chips so attention's O(T²) memory is split
S ways while each chip's matmuls stay MXU-sized.

Design (the standard TPU ring formulation): Q/K/V are sharded on the
sequence dim over the "sp" axis. Each rank keeps its Q block resident and
walks the K/V ring — S steps of (blockwise attention + streaming-softmax
accumulation + ppermute of the K/V block to the next rank) — so ICI
carries exactly one K/V block per step, overlapped by XLA with the
block's matmuls. Numerics are exact (same streaming-max/denominator
algebra as flash attention), verified against dense attention in tests.
Differentiable end-to-end: AD through scan+ppermute yields the reverse
ring schedule automatically.
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import mesh as _mesh

_NEG = -1e30  # -inf stand-in: keeps the streaming-softmax algebra nan-free


def _ring_attention_local(q, k, v, axis: str, causal: bool, scale):
    """Runs INSIDE shard_map. q/k/v: local [B, H, Tl, D] blocks (sequence
    dim sharded over ``axis``). Returns local attention output."""
    S = lax.axis_size(axis)
    rank = lax.axis_index(axis)
    B, H, Tl, D = q.shape
    qpos = rank * Tl + jnp.arange(Tl)
    acc = jnp.float32  # flash-attention rule: accumulators in f32 even
    # for bf16/fp16 inputs (matches the f32-stats-in-op AMP convention)

    def step(carry, s):
        o, m, l, kc, vc = carry
        src = jnp.mod(rank - s, S)           # whose K/V block we hold now
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, kc,
                            preferred_element_type=acc) * scale
        if causal:
            kpos = src * Tl + jnp.arange(Tl)
            mask = qpos[:, None] >= kpos[None, :]
            scores = jnp.where(mask[None, None], scores, _NEG)
        smax = jnp.max(scores, axis=-1)                      # [B,H,Tl]
        new_m = jnp.maximum(m, smax)
        # guard: a fully-masked block keeps new_m at _NEG; exp(0)=1 there
        # is harmless because p is all zeros
        p = jnp.exp(scores - new_m[..., None])
        p = jnp.where(scores <= _NEG, 0.0, p)
        corr = jnp.exp(jnp.clip(m - new_m, _NEG, 0.0))
        l2 = l * corr + jnp.sum(p, axis=-1)
        o2 = o * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vc.astype(acc),
            preferred_element_type=acc)
        # rotate the K/V ring one step forward
        perm = [(i, (i + 1) % S) for i in range(S)]
        kn = lax.ppermute(kc, axis, perm=perm)
        vn = lax.ppermute(vc, axis, perm=perm)
        return (o2, new_m, l2, kn, vn), None

    # derive the initial carries from q so they inherit ALL of q's varying
    # axes (sp plus any batch axis the caller sharded over)
    o0 = q.astype(acc) * 0
    base = jnp.sum(o0, axis=-1)                       # [B,H,Tl], q's vma
    m0 = base + _NEG
    l0 = base
    (o, m, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v), jnp.arange(S))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh=None, axis: str = "sp",
                   causal: bool = False, scale: Optional[float] = None,
                   batch_axes=None):
    """Exact attention with the sequence dim sharded over ``axis``.

    q/k/v: GLOBAL [B, H, T, D] arrays (T divisible by the axis size).
    Returns [B, H, T, D], sequence-sharded the same way. Pass
    ``batch_axes`` (e.g. "dp") when the batch dim is data-parallel —
    otherwise the shard_map replicates it over the other mesh axes.
    Call from un-mapped code — this wraps its own shard_map; inside an
    existing shard_map use :func:`_ring_attention_local` directly.
    """
    m = mesh or _mesh.ensure_mesh()
    if scale is None:
        scale = 1.0 / float(np.sqrt(q.shape[-1]))
    spec = P(batch_axes, None, axis, None)
    fn = jax.shard_map(
        lambda qq, kk, vv: _ring_attention_local(qq, kk, vv, axis, causal,
                                                 scale),
        mesh=m, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def split_sequence(x, mesh=None, axis: str = "sp", seq_dim: int = 2):
    """Shard a global tensor's sequence dim over the sp axis (the
    scatter edge of sequence parallelism)."""
    m = mesh or _mesh.ensure_mesh()
    spec = [None] * x.ndim
    spec[seq_dim] = axis
    from jax.sharding import NamedSharding
    return jax.device_put(x, NamedSharding(m, P(*spec)))


def gather_sequence(x, mesh=None, axis: str = "sp", seq_dim: int = 2):
    """Gather (replicate) the sequence dim of a sequence-sharded tensor;
    other dims keep whatever sharding they had."""
    m = mesh or _mesh.ensure_mesh()
    from jax.sharding import NamedSharding
    sh = getattr(x, "sharding", None)
    spec = [None] * x.ndim
    if sh is not None and hasattr(sh, "spec"):
        cur = list(sh.spec) + [None] * (x.ndim - len(sh.spec))
        spec = cur
    spec[seq_dim] = None
    return jax.device_put(x, NamedSharding(m, P(*spec)))


def _ring_impl(qq, kk, vv, axis="sp", causal=False, batch_axes=None):
    # module-level (no closure) so the eager op cache can key it: a
    # per-call lambda over a Mesh is _UNCACHEABLE and re-traces the whole
    # ring program each call (dispatch.py cache rules)
    ba = tuple(batch_axes) if isinstance(batch_axes, (list, tuple)) \
        else batch_axes
    return ring_attention(qq, kk, vv, mesh=None, axis=axis, causal=causal,
                          batch_axes=ba)


class RingAttention:
    """Layer-ish wrapper so models can swap their attention core for the
    sequence-parallel one (EP/CP engines in later frameworks expose the
    same shape: SURVEY §5.7 TPU build implication)."""

    def __init__(self, mesh=None, axis: str = "sp", causal: bool = False,
                 batch_axes=None, use_flash: bool = False):
        if mesh is not None and mesh is not _mesh.get_mesh():
            raise ValueError(
                "RingAttention uses the ambient mesh (set_mesh); pass "
                "mesh= only to ring_attention directly")
        self._axis = axis
        self._causal = causal
        self._batch_axes = batch_axes
        # use_flash: run the Pallas kernel per chunk (forward-only today
        # — the lse-merge custom_vjp is future work; training paths keep
        # the dense-chunk ring whose AD is exact)
        self._use_flash = use_flash

    def __call__(self, q, k, v):
        from ...ops.dispatch import apply
        # through the op funnel: tape-recorded (backprop works), visible
        # to AMP/nan-check/profiler like every other op
        if self._use_flash:
            return apply("ring_flash_attention", _ring_flash_impl,
                         q, k, v, axis=self._axis, causal=self._causal,
                         batch_axes=self._batch_axes)
        return apply("ring_attention", _ring_impl, q, k, v,
                     axis=self._axis, causal=self._causal,
                     batch_axes=self._batch_axes)


def _ring_blocks(Tl: int, D: int, dtype):
    """Block edges for the ring-flash chunk kernel. This path calls the
    kernel core without a padding wrapper, so blocks MUST divide Tl
    exactly — a tuned winner that doesn't divide is discarded (the tuner
    enumerates with ``require_divides=True``, so this only filters stale
    or hand-edited cache entries)."""
    default = Tl if Tl <= 128 else (128 if Tl % 128 == 0 else 16)
    try:
        from ...tuner import get_flash_blocks
        tuned = get_flash_blocks(Tl, Tl, D, dtype, False, ring=True)
    except Exception:
        tuned = None
    if tuned is not None:
        bq, bk = int(tuned[0]), int(tuned[1])
        if (bq > 0 and bk > 0 and Tl % bq == 0 and Tl % bk == 0
                and bq % 16 == 0 and bk % 16 == 0):
            return bq, bk
    return default, default


def _ring_flash_local(q, k, v, axis: str, causal: bool, scale,
                      interpret: bool):
    """Ring attention whose LOCAL chunk compute is the Pallas flash
    kernel (ops/pallas_attention.py) instead of a dense [Tl, Tl] block
    product — the full composition of the two long-context mechanisms:
    flash handles within-chunk memory, the ring handles cross-chip
    sequence scale. Per ring step the kernel emits (normalized chunk
    output, logsumexp rows); chunks merge by the standard lse algebra

        lse' = logaddexp(lse, lse_c)
        o'   = o * exp(lse - lse') + o_c * exp(lse_c - lse')

    Causality across chunks is positional: a K/V chunk strictly in the
    future (src > rank) is masked out entirely, the diagonal chunk runs
    the kernel's causal path, past chunks run non-causal. Runs INSIDE
    shard_map; q/k/v are local [B, H, Tl, D] blocks with Tl a multiple
    of 16 (the kernel's sublane tile).
    """
    from ...ops.pallas_attention import _fa_fwd_with_lse

    S = lax.axis_size(axis)
    rank = lax.axis_index(axis)
    B, H, Tl, D = q.shape
    if Tl % 16:
        raise ValueError(f"ring_flash_attention: per-shard sequence {Tl} "
                         f"must be a multiple of 16")
    bq, bk = _ring_blocks(Tl, D, q.dtype)
    BH = B * H
    qb = q.reshape(BH, Tl, D)

    def kernel(kc, vc, causal_flag):
        return _fa_fwd_with_lse(qb, kc.reshape(BH, Tl, D),
                                vc.reshape(BH, Tl, D), causal_flag,
                                scale, bq, bk, interpret, Tl)

    def _r3(out_lse):
        o_c, lse_c = out_lse
        return o_c, lse_c.reshape(BH, Tl).astype(jnp.float32)

    def step(carry, s):
        o, lse, kc, vc = carry
        src = jnp.mod(rank - s, S)
        if causal:
            # 3-way switch: past chunk = full kernel, diagonal = causal
            # kernel, future chunk = no kernel launch at all (zeros,
            # masked lse) — skipping ~(S-1)/2S of the launches
            idx = jnp.where(src > rank, 2,
                            jnp.where(src == rank, 1, 0))
            o_c, lse_c = lax.switch(
                idx,
                [lambda: _r3(kernel(kc, vc, False)),
                 lambda: _r3(kernel(kc, vc, True)),
                 lambda: (jnp.zeros((BH, Tl, D), qb.dtype),
                          jnp.full((BH, Tl), _NEG, jnp.float32))])
        else:
            o_c, lse_c = kernel(kc, vc, False)
            lse_c = lse_c.reshape(BH, Tl)
        o_c = o_c.astype(jnp.float32)
        lse_new = jnp.logaddexp(lse, lse_c)
        w_old = jnp.exp(jnp.clip(lse - lse_new, _NEG, 0.0))
        w_new = jnp.exp(jnp.clip(lse_c - lse_new, _NEG, 0.0))
        o = o * w_old[..., None] + o_c * w_new[..., None]
        perm = [(i, (i + 1) % S) for i in range(S)]
        kn = lax.ppermute(kc, axis, perm=perm)
        vn = lax.ppermute(vc, axis, perm=perm)
        return (o, lse_new, kn, vn), None

    # plain initializers: check_vma=False on the enclosing shard_map, so
    # no varying-axes inheritance trick is needed (unlike the dense ring)
    o0 = jnp.zeros((BH, Tl, D), jnp.float32)
    lse0 = jnp.full((BH, Tl), _NEG, jnp.float32)
    (o, lse, _, _), _ = lax.scan(
        step, (o0, lse0, k, v), jnp.arange(S))
    return o.reshape(B, H, Tl, D).astype(q.dtype)


def _grad_guard(fn):
    """Forward-only marker: differentiation raises a clear error instead
    of the un-vjp'd pallas_call's bare AssertionError."""
    guarded = jax.custom_vjp(fn)

    def fwd(*args):
        raise NotImplementedError(
            "ring_flash_attention is forward-only (the lse-merge "
            "custom_vjp is not implemented); use the dense-chunk "
            "ring_attention / RingAttention(use_flash=False) for "
            "training")

    def bwd(res, g):   # pragma: no cover — fwd always raises first
        raise NotImplementedError
    guarded.defvjp(fwd, bwd)
    return guarded


def ring_flash_attention(q, k, v, mesh=None, axis: str = "sp",
                         causal: bool = False, scale: Optional[float] = None,
                         batch_axes=None, interpret: Optional[bool] = None):
    """Sequence-parallel attention with the Pallas flash kernel as the
    per-chunk compute (see :func:`_ring_flash_local`). Same contract as
    :func:`ring_attention`: GLOBAL [B, H, T, D] arrays, T divisible by
    the axis size, returns the same sharding. ``interpret`` defaults to
    True off-TPU so CPU-mesh tests run the kernel in interpret mode."""
    m = mesh or _mesh.ensure_mesh()
    if scale is None:
        scale = 1.0 / float(np.sqrt(q.shape[-1]))
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    spec = P(batch_axes, None, axis, None)
    # check_vma=False: pallas_call's out ShapeDtypeStructs carry no
    # varying-mesh-axes annotation, which strict shard_map rejects; the
    # sharding contract is fully pinned by in_specs/out_specs here
    fn = jax.shard_map(
        lambda qq, kk, vv: _ring_flash_local(qq, kk, vv, axis, causal,
                                             scale, interpret),
        mesh=m, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return _grad_guard(fn)(q, k, v)


def _ring_flash_impl(qq, kk, vv, axis="sp", causal=False, batch_axes=None):
    # module-level for the op cache (see _ring_impl)
    ba = tuple(batch_axes) if isinstance(batch_axes, (list, tuple)) \
        else batch_axes
    return ring_flash_attention(qq, kk, vv, mesh=None, axis=axis,
                                causal=causal, batch_axes=ba)
