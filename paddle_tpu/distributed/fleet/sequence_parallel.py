"""Sequence/context parallelism: ring attention over an "sp" mesh axis.

The reference snapshot has NO sequence parallelism (SURVEY §5.7 verified
absent — long sequences are handled only by recompute+sharding+pipeline);
this module is the parity-plus capability the TPU build plan calls for:
scale *sequence length* across chips so attention's O(T²) memory is split
S ways while each chip's matmuls stay MXU-sized.

Design (the standard TPU ring formulation): Q/K/V are sharded on the
sequence dim over the "sp" axis. Each rank keeps its Q block resident and
walks the K/V ring — S steps of (blockwise attention + streaming-softmax
accumulation + ppermute of the K/V block to the next rank) — so ICI
carries exactly one K/V block per step, overlapped by XLA with the
block's matmuls. Numerics are exact (same streaming-max/denominator
algebra as flash attention), verified against dense attention in tests.

Two chunk-compute variants share the ring schedule:

- :func:`ring_attention` — dense [Tl, Tl] score blocks per step.
  Differentiable end-to-end: AD through scan+ppermute yields the reverse
  ring schedule automatically.
- :func:`ring_flash_attention` — the Pallas flash kernel per step, with
  a hand-written :func:`jax.custom_vjp` backward (the kernel has no AD
  rule). Forward saves per-rank (o, lse); backward walks the K/V ring a
  second time running the FlashAttention recomputation schedule per
  chunk (``ops/pallas_attention._fa_bwd_with_lse``): dQ accumulates
  locally while each K/V block's dK/dV accumulator travels the ring
  *with* its block, so after exactly S ppermute steps every accumulator
  has collected all ranks' contributions and is back home. No [Tl, Tl]
  score tensor ever materializes in either direction — see
  docs/performance.md "Long-context training".

The shard-mapped callables for both variants are cached per
(mesh, axis, causal, scale, batch_axes[, interpret]) signature so warm
eager calls reuse jit traces instead of rebuilding a fresh
``jax.shard_map`` over a new lambda each call.
"""
from __future__ import annotations

import collections
import functools
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import mesh as _mesh

_NEG = -1e30  # -inf stand-in: keeps the streaming-softmax algebra nan-free

#: python-side trace counter, bumped once per (re)trace of each ring
#: local function — the compile-counter regression tests assert warm
#: calls leave these untouched
_TRACE_COUNTS = collections.Counter()

#: shard-mapped ring callables keyed by signature (see _ring_callable);
#: bounded in practice by the handful of (mesh, flags) combinations a
#: process uses, so no eviction policy
_RING_CACHE = {}


def _ring_attention_local(q, k, v, axis: str, causal: bool, scale):
    """Runs INSIDE shard_map. q/k/v: local [B, H, Tl, D] blocks (sequence
    dim sharded over ``axis``). Returns local attention output."""
    _TRACE_COUNTS["ring_dense"] += 1
    S = lax.axis_size(axis)
    rank = lax.axis_index(axis)
    B, H, Tl, D = q.shape
    qpos = rank * Tl + jnp.arange(Tl)
    acc = jnp.float32  # flash-attention rule: accumulators in f32 even
    # for bf16/fp16 inputs (matches the f32-stats-in-op AMP convention)

    def step(carry, s):
        o, m, l, kc, vc = carry
        src = jnp.mod(rank - s, S)           # whose K/V block we hold now
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, kc,
                            preferred_element_type=acc) * scale
        if causal:
            kpos = src * Tl + jnp.arange(Tl)
            mask = qpos[:, None] >= kpos[None, :]
            scores = jnp.where(mask[None, None], scores, _NEG)
        smax = jnp.max(scores, axis=-1)                      # [B,H,Tl]
        new_m = jnp.maximum(m, smax)
        # guard: a fully-masked block keeps new_m at _NEG; exp(0)=1 there
        # is harmless because p is all zeros
        p = jnp.exp(scores - new_m[..., None])
        p = jnp.where(scores <= _NEG, 0.0, p)
        corr = jnp.exp(jnp.clip(m - new_m, _NEG, 0.0))
        l2 = l * corr + jnp.sum(p, axis=-1)
        o2 = o * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vc.astype(acc),
            preferred_element_type=acc)
        # rotate the K/V ring one step forward
        perm = [(i, (i + 1) % S) for i in range(S)]
        kn = lax.ppermute(kc, axis, perm=perm)
        vn = lax.ppermute(vc, axis, perm=perm)
        return (o2, new_m, l2, kn, vn), None

    # derive the initial carries from q so they inherit ALL of q's varying
    # axes (sp plus any batch axis the caller sharded over)
    o0 = q.astype(acc) * 0
    base = jnp.sum(o0, axis=-1)                       # [B,H,Tl], q's vma
    m0 = base + _NEG
    l0 = base
    (o, m, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v), jnp.arange(S))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def _canon_batch_axes(batch_axes):
    return tuple(batch_axes) if isinstance(batch_axes, (list, tuple)) \
        else batch_axes


def _ring_callable(kind, mesh, axis, causal, scale, batch_axes,
                   interpret=None):
    """The shard-mapped ring callable for one signature, built once and
    cached. A fresh ``jax.shard_map`` over a new lambda per call would
    defeat jit's trace cache (the callable's identity IS the cache key),
    so every eager warm call would retrace the whole ring program."""
    key = (kind, mesh, axis, bool(causal), float(scale),  # noqa: PTA001 -- causal/scale are trace-time python config (never traced values); the cache key must be hashable
           _canon_batch_axes(batch_axes), interpret)
    fn = _RING_CACHE.get(key)
    if fn is None:
        spec = P(batch_axes, None, axis, None)
        if kind == "dense":
            # jit-wrapped: a bare shard_map call re-traces the local fn on
            # every eager invocation; pjit's trace cache (keyed on the
            # stable callable identity we cache here + avals) makes warm
            # calls zero-trace
            fn = jax.jit(jax.shard_map(
                functools.partial(_ring_attention_local, axis=axis,
                                  causal=causal, scale=scale),
                mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec))
        else:
            fn = _build_ring_flash(mesh, spec, axis, causal, scale,
                                   batch_axes, interpret)
        _RING_CACHE[key] = fn
    return fn


def ring_attention(q, k, v, mesh=None, axis: str = "sp",
                   causal: bool = False, scale: Optional[float] = None,
                   batch_axes=None):
    """Exact attention with the sequence dim sharded over ``axis``.

    q/k/v: GLOBAL [B, H, T, D] arrays (T divisible by the axis size).
    Returns [B, H, T, D], sequence-sharded the same way. Pass
    ``batch_axes`` (e.g. "dp") when the batch dim is data-parallel —
    otherwise the shard_map replicates it over the other mesh axes.
    Call from un-mapped code — this wraps its own shard_map; inside an
    existing shard_map use :func:`_ring_attention_local` directly.
    """
    m = mesh or _mesh.ensure_mesh()
    if scale is None:
        scale = 1.0 / float(np.sqrt(q.shape[-1]))  # noqa: PTA001 -- head dim is a static shape, a trace-time python int
    return _ring_callable("dense", m, axis, causal, scale, batch_axes)(
        q, k, v)


def split_sequence(x, mesh=None, axis: str = "sp", seq_dim: int = 2):
    """Shard a global tensor's sequence dim over the sp axis (the
    scatter edge of sequence parallelism)."""
    m = mesh or _mesh.ensure_mesh()
    spec = [None] * x.ndim
    spec[seq_dim] = axis
    from jax.sharding import NamedSharding
    return jax.device_put(x, NamedSharding(m, P(*spec)))


def gather_sequence(x, mesh=None, axis: str = "sp", seq_dim: int = 2):
    """Gather (replicate) the sequence dim of a sequence-sharded tensor;
    other dims keep whatever sharding they had."""
    m = mesh or _mesh.ensure_mesh()
    from jax.sharding import NamedSharding
    sh = getattr(x, "sharding", None)
    spec = [None] * x.ndim
    if sh is not None and hasattr(sh, "spec"):
        cur = list(sh.spec) + [None] * (x.ndim - len(sh.spec))
        spec = cur
    spec[seq_dim] = None
    return jax.device_put(x, NamedSharding(m, P(*spec)))


def _ring_impl(qq, kk, vv, axis="sp", causal=False, batch_axes=None):
    # module-level (no closure) so the eager op cache can key it: a
    # per-call lambda over a Mesh is _UNCACHEABLE and re-traces the whole
    # ring program each call (dispatch.py cache rules)
    return ring_attention(qq, kk, vv, mesh=None, axis=axis, causal=causal,
                          batch_axes=_canon_batch_axes(batch_axes))


class RingAttention:
    """Layer-ish wrapper so models can swap their attention core for the
    sequence-parallel one (EP/CP engines in later frameworks expose the
    same shape: SURVEY §5.7 TPU build implication)."""

    def __init__(self, mesh=None, axis: str = "sp", causal: bool = False,
                 batch_axes=None, use_flash: bool = False):
        if mesh is not None and mesh is not _mesh.get_mesh():
            raise ValueError(
                "RingAttention uses the ambient mesh (set_mesh); pass "
                "mesh= only to ring_attention directly")
        self._axis = axis
        self._causal = causal
        self._batch_axes = batch_axes
        # use_flash: run the Pallas kernel per chunk. Fully trainable —
        # ring_flash_attention carries a custom_vjp whose backward runs
        # the flash recomputation schedule around the ring, so this is
        # the long-context TRAINING fast path, not just inference
        self._use_flash = use_flash

    def __call__(self, q, k, v):
        from ...ops.dispatch import apply
        # through the op funnel: tape-recorded (backprop works), visible
        # to AMP/nan-check/profiler like every other op
        if self._use_flash:
            return apply("ring_flash_attention", _ring_flash_impl,
                         q, k, v, axis=self._axis, causal=self._causal,
                         batch_axes=self._batch_axes)
        return apply("ring_attention", _ring_impl, q, k, v,
                     axis=self._axis, causal=self._causal,
                     batch_axes=self._batch_axes)


def _sanitize_ring_blocks(tuned, Tl: int):
    """Shared divisibility sanitizer for tuned ring block pairs: the ring
    path calls the kernel core without a padding wrapper, so blocks MUST
    divide Tl exactly and stay 16-row sublane multiples. Returns the
    (bq, bk) pair or None when the entry is unusable."""
    if tuned is None:
        return None
    bq, bk = int(tuned[0]), int(tuned[1])
    if (bq > 0 and bk > 0 and Tl % bq == 0 and Tl % bk == 0
            and bq % 16 == 0 and bk % 16 == 0):
        return bq, bk
    return None


def _ring_blocks(Tl: int, D: int, dtype, bwd: bool = False):
    """Block edges for the ring-flash chunk kernel (``bwd`` selects the
    backward-kernel family). Tuned winners that don't divide Tl are
    discarded by :func:`_sanitize_ring_blocks` (the tuner enumerates with
    ``require_divides=True``, so this only filters stale or hand-edited
    cache entries); a missing backward winner falls back to the forward
    family's before the heuristic default."""
    default = Tl if Tl <= 128 else (128 if Tl % 128 == 0 else 16)
    try:
        from ...tuner import get_flash_blocks
        got = _sanitize_ring_blocks(
            get_flash_blocks(Tl, Tl, D, dtype, False, ring=True, bwd=bwd),
            Tl)
        if got is None and bwd:
            got = _sanitize_ring_blocks(
                get_flash_blocks(Tl, Tl, D, dtype, False, ring=True), Tl)
    except Exception:
        got = None
    return got if got is not None else (default, default)


def _ring_flash_fwd_local(q, k, v, axis: str, causal: bool, scale,
                          interpret: bool):
    """Ring attention whose LOCAL chunk compute is the Pallas flash
    kernel (ops/pallas_attention.py) instead of a dense [Tl, Tl] block
    product — the full composition of the two long-context mechanisms:
    flash handles within-chunk memory, the ring handles cross-chip
    sequence scale. Per ring step the kernel emits (normalized chunk
    output, logsumexp rows); chunks merge by the standard lse algebra

        lse' = logaddexp(lse, lse_c)
        o'   = o * exp(lse - lse') + o_c * exp(lse_c - lse')

    Causality across chunks is positional: a K/V chunk strictly in the
    future (src > rank) is masked out entirely, the diagonal chunk runs
    the kernel's causal path, past chunks run non-causal. Runs INSIDE
    shard_map; q/k/v are local [B, H, Tl, D] blocks with Tl a multiple
    of 16 (the kernel's sublane tile).

    Returns ``(o [B,H,Tl,D], lse [B,H,Tl] f32)`` — the merged logsumexp
    rows are the backward residual (with them, per-chunk
    ``p = exp(s·scale − lse)`` IS the global softmax weight, so the
    backward never re-merges).
    """
    from ...ops.pallas_attention import _fa_fwd_with_lse

    _TRACE_COUNTS["ring_flash_fwd"] += 1
    S = lax.axis_size(axis)
    rank = lax.axis_index(axis)
    B, H, Tl, D = q.shape
    if Tl % 16:
        raise ValueError(f"ring_flash_attention: per-shard sequence {Tl} "
                         f"must be a multiple of 16")
    bq, bk = _ring_blocks(Tl, D, q.dtype)
    BH = B * H
    qb = q.reshape(BH, Tl, D)

    def kernel(kc, vc, causal_flag):
        return _fa_fwd_with_lse(qb, kc.reshape(BH, Tl, D),
                                vc.reshape(BH, Tl, D), causal_flag,
                                scale, bq, bk, interpret, Tl)

    def _r3(out_lse):
        o_c, lse_c = out_lse
        return o_c, lse_c.reshape(BH, Tl).astype(jnp.float32)

    def step(carry, s):
        o, lse, kc, vc = carry
        src = jnp.mod(rank - s, S)
        if causal:
            # 3-way switch: past chunk = full kernel, diagonal = causal
            # kernel, future chunk = no kernel launch at all (zeros,
            # masked lse) — skipping ~(S-1)/2S of the launches
            idx = jnp.where(src > rank, 2,
                            jnp.where(src == rank, 1, 0))
            o_c, lse_c = lax.switch(
                idx,
                [lambda: _r3(kernel(kc, vc, False)),
                 lambda: _r3(kernel(kc, vc, True)),
                 lambda: (jnp.zeros((BH, Tl, D), qb.dtype),
                          jnp.full((BH, Tl), _NEG, jnp.float32))])
        else:
            o_c, lse_c = kernel(kc, vc, False)
            lse_c = lse_c.reshape(BH, Tl)
        o_c = o_c.astype(jnp.float32)
        lse_new = jnp.logaddexp(lse, lse_c)
        w_old = jnp.exp(jnp.clip(lse - lse_new, _NEG, 0.0))
        w_new = jnp.exp(jnp.clip(lse_c - lse_new, _NEG, 0.0))
        o = o * w_old[..., None] + o_c * w_new[..., None]
        perm = [(i, (i + 1) % S) for i in range(S)]
        kn = lax.ppermute(kc, axis, perm=perm)
        vn = lax.ppermute(vc, axis, perm=perm)
        return (o, lse_new, kn, vn), None

    # plain initializers: check_vma=False on the enclosing shard_map, so
    # no varying-axes inheritance trick is needed (unlike the dense ring)
    o0 = jnp.zeros((BH, Tl, D), jnp.float32)
    lse0 = jnp.full((BH, Tl), _NEG, jnp.float32)
    (o, lse, _, _), _ = lax.scan(
        step, (o0, lse0, k, v), jnp.arange(S))
    return (o.reshape(B, H, Tl, D).astype(q.dtype),
            lse.reshape(B, H, Tl))


def _ring_flash_bwd_local(q, k, v, o, lse, do, axis: str, causal: bool,
                          scale, interpret: bool):
    """Backward ring schedule (runs INSIDE shard_map). Residual layout:
    per-rank local ``q/k/v/o [B,H,Tl,D]`` plus the merged ``lse
    [B,H,Tl]`` f32 rows from the forward. Because lse is the GLOBAL
    logsumexp, each chunk's ``p = exp(s·scale − lse)`` recomputed by
    ``_fa_bwd_with_lse`` is already the globally-normalized softmax
    weight — the forward's lse-merge weights are folded into the
    gradient scaling for free, and ``delta = rowsum(dO∘O)`` is computed
    ONCE per rank (it is chunk-independent).

    Schedule: walk the K/V ring again (same forward perm). dQ accumulates
    locally in f32; each K/V block travels with its own f32 dK/dV
    accumulator — block b sits on rank b+s at step s, so after S
    ppermute steps every accumulator has collected all ranks'
    contributions and is back on its home rank. The causal 3-way switch
    skips kernel launches for future chunks exactly as the forward does
    (the ppermutes stay outside the switch: every rank must participate
    in every collective).
    """
    from ...ops.pallas_attention import _fa_bwd_with_lse

    _TRACE_COUNTS["ring_flash_bwd"] += 1
    S = lax.axis_size(axis)
    rank = lax.axis_index(axis)
    B, H, Tl, D = q.shape
    bq, bk = _ring_blocks(Tl, D, q.dtype, bwd=True)
    BH = B * H
    f32 = jnp.float32
    qb = q.reshape(BH, Tl, D)
    dob = do.reshape(BH, Tl, D)
    lse_b = lse.reshape(BH, 1, Tl).astype(f32)
    delta = jnp.sum(dob.astype(f32) * o.reshape(BH, Tl, D).astype(f32),
                    axis=-1)[:, None, :]                    # [BH, 1, Tl]

    def chunk_grads(kc, vc, causal_flag):
        return _fa_bwd_with_lse(
            qb, kc.reshape(BH, Tl, D), vc.reshape(BH, Tl, D), dob, None,
            lse_b, causal_flag, scale, bq, bk, interpret, Tl, delta=delta,
            grad_dtypes=(f32, f32, f32))

    def step(carry, s):
        dq, dka, dva, kc, vc = carry
        src = jnp.mod(rank - s, S)
        if causal:
            idx = jnp.where(src > rank, 2,
                            jnp.where(src == rank, 1, 0))
            zero = lambda: (jnp.zeros((BH, Tl, D), f32),
                            jnp.zeros((BH, Tl, D), f32),
                            jnp.zeros((BH, Tl, D), f32))
            dqc, dkc, dvc = lax.switch(
                idx,
                [lambda: chunk_grads(kc, vc, False),
                 lambda: chunk_grads(kc, vc, True),
                 zero])
        else:
            dqc, dkc, dvc = chunk_grads(kc, vc, False)
        dq = dq + dqc
        dka = dka + dkc
        dva = dva + dvc
        perm = [(i, (i + 1) % S) for i in range(S)]
        return (dq,
                lax.ppermute(dka, axis, perm=perm),
                lax.ppermute(dva, axis, perm=perm),
                lax.ppermute(kc, axis, perm=perm),
                lax.ppermute(vc, axis, perm=perm)), None

    z = jnp.zeros((BH, Tl, D), f32)
    (dq, dka, dva, _, _), _ = lax.scan(step, (z, z, z, k, v),
                                       jnp.arange(S))
    shape = (B, H, Tl, D)
    return (dq.reshape(shape).astype(q.dtype),
            dka.reshape(shape).astype(k.dtype),
            dva.reshape(shape).astype(v.dtype))


def _build_ring_flash(mesh, spec, axis, causal, scale, batch_axes,
                      interpret):
    """Assemble the custom_vjp ring-flash callable for one signature.
    The custom_vjp sits OUTSIDE the shard_maps: forward shard_map returns
    (o, lse), backward shard_map consumes the saved (q, k, v, o, lse)
    residuals plus the cotangent. check_vma=False on both: pallas_call's
    out ShapeDtypeStructs carry no varying-mesh-axes annotation, which
    strict shard_map rejects; the sharding contract is fully pinned by
    in_specs/out_specs here."""
    sspec = P(batch_axes, None, axis)              # [B, H, Tl] rows
    # jit-wrapped for the same warm-call zero-trace reason as the dense
    # ring: both the eager forward and each jax.grad-driven backward hit
    # the pjit trace cache instead of re-tracing the ring program
    fwd_sm = jax.jit(jax.shard_map(
        functools.partial(_ring_flash_fwd_local, axis=axis, causal=causal,
                          scale=scale, interpret=interpret),
        mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=(spec, sspec), check_vma=False))
    bwd_sm = jax.jit(jax.shard_map(
        functools.partial(_ring_flash_bwd_local, axis=axis, causal=causal,
                          scale=scale, interpret=interpret),
        mesh=mesh, in_specs=(spec, spec, spec, spec, sspec, spec),
        out_specs=(spec, spec, spec), check_vma=False))

    @jax.custom_vjp
    def ring(q, k, v):
        return fwd_sm(q, k, v)[0]

    def fwd(q, k, v):
        o, lse = fwd_sm(q, k, v)
        return o, (q, k, v, o, lse)

    def bwd(res, do):
        q, k, v, o, lse = res
        return tuple(bwd_sm(q, k, v, o, lse, do))

    ring.defvjp(fwd, bwd)
    return ring


def ring_flash_attention(q, k, v, mesh=None, axis: str = "sp",
                         causal: bool = False, scale: Optional[float] = None,
                         batch_axes=None, interpret: Optional[bool] = None):
    """Sequence-parallel attention with the Pallas flash kernel as the
    per-chunk compute (see :func:`_ring_flash_fwd_local`). Same contract
    as :func:`ring_attention`: GLOBAL [B, H, T, D] arrays, T divisible by
    the axis size, returns the same sharding. Differentiable — the
    attached custom_vjp runs the flash recomputation schedule around the
    ring (:func:`_ring_flash_bwd_local`), so ``jax.grad`` through this is
    the long-context training fast path. ``interpret`` defaults to True
    off-TPU so CPU-mesh tests run the kernel in interpret mode."""
    m = mesh or _mesh.ensure_mesh()
    if scale is None:
        scale = 1.0 / float(np.sqrt(q.shape[-1]))  # noqa: PTA001 -- head dim is a static shape, a trace-time python int
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    return _ring_callable("flash", m, axis, causal, scale, batch_axes,
                          interpret=bool(interpret))(q, k, v)  # noqa: PTA001 -- interpret is a trace-time python flag (platform check above), never a traced value


def _ring_flash_impl(qq, kk, vv, axis="sp", causal=False, batch_axes=None):
    # module-level for the op cache (see _ring_impl)
    return ring_flash_attention(qq, kk, vv, mesh=None, axis=axis,
                                causal=causal,
                                batch_axes=_canon_batch_axes(batch_axes))
