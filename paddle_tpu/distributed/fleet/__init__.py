"""paddle.distributed.fleet parity: the unified distributed-training entry.

TPU-native equivalent of the reference Fleet facade
(reference: python/paddle/distributed/fleet/base/fleet_base.py:72 Fleet —
init :139, distributed_optimizer :744, distributed_model, minimize :1244;
meta-optimizer selection fleet_base.py:1325 + strategy_compiler.py).

Where the reference's meta-optimizers rewrite Programs, `fleet.init`
compiles the DistributedStrategy into the global Mesh + hybrid topology;
`distributed_model` applies the parallel wrappers (DataParallel for dp,
PipelineParallel for pp — TP layers are already mesh-annotated);
`distributed_optimizer` applies strategy levers (sharding, LARS/LAMB swap,
gradient merge) to the optimizer.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from .strategy import DistributedStrategy
from .mp_layers import (VocabParallelEmbedding, ColumnParallelLinear,
                        RowParallelLinear, ParallelCrossEntropy,
                        get_rng_state_tracker, model_parallel_random_seed)
from ..topology import (CommunicateTopology, HybridCommunicateGroup,
                        set_hybrid_communicate_group,
                        get_hybrid_communicate_group)
from .. import mesh as _mesh
from ..env import get_rank, get_world_size, init_parallel_env
from . import utils  # noqa: F401 (recompute lives here)
from . import fs  # noqa: F401 (LocalFS/HDFSClient facade)
from .moe import moe_ffn, MoELayer  # noqa: F401
from .sequence_parallel import (ring_attention, RingAttention,  # noqa: F401
                                split_sequence, gather_sequence)
from .sharded_embedding import (ShardedEmbedding,  # noqa: F401
                                sparse_row_update, make_row_state)


class _FleetState:
    def __init__(self):
        self.strategy: Optional[DistributedStrategy] = None
        self.hcg: Optional[HybridCommunicateGroup] = None
        self.initialized = False


_F = _FleetState()


def init(role_maker=None, is_collective=False, strategy=None):
    """reference: fleet_base.py:139. Collective mode only: the brpc
    parameter-server world is out of scope by ADR
    (docs/adr/0001-parameter-server.md) — its capability is covered by
    fleet.ShardedEmbedding."""
    if role_maker is not None and not is_collective:
        raise NotImplementedError(
            "parameter-server role makers are out of scope "
            "(docs/adr/0001-parameter-server.md); use is_collective=True "
            "and fleet.ShardedEmbedding for large sparse tables")
    if strategy is None:
        strategy = DistributedStrategy()
    _F.strategy = strategy
    init_parallel_env()
    hc = strategy.hybrid_configs
    _F.hcg = HybridCommunicateGroup(
        dp_degree=int(hc.get("dp_degree", 1)),
        mp_degree=int(hc.get("mp_degree", 1)),
        pp_degree=int(hc.get("pp_degree", 1)),
        sharding_degree=int(hc.get("sharding_degree", 1)),
        sep_degree=int(hc.get("sep_degree", 1)))
    set_hybrid_communicate_group(_F.hcg)
    _F.initialized = True
    return _F


def get_hybrid_communicate_group_():
    return _F.hcg


def is_first_worker():
    return get_rank() == 0


def worker_index():
    return get_rank()


def worker_num():
    return get_world_size()


def barrier_worker():
    from ..collective import barrier
    barrier()


def distributed_model(model):
    """reference: fleet_base.py distributed_model — wrap per parallel mode."""
    hcg = _F.hcg
    if hcg is None:
        init()
        hcg = _F.hcg
    mode = hcg.get_parallel_mode()
    if mode == "pipeline":
        from .pipeline_parallel import PipelineParallel
        return PipelineParallel(model, hcg, _F.strategy)
    if mode == "data":
        from ..parallel import DataParallel
        return DataParallel(model)
    # model/tensor parallel: layers are already mesh-annotated; replicate the
    # rest (reference broadcasts non-mp params across the mp ring)
    for _, p in model.named_parameters():
        if p._sharding_spec is None:
            _mesh.replicate_tensor(p)
    return model


def distributed_optimizer(optimizer, strategy=None):
    """reference: fleet_base.py:744 + the meta-optimizer stack. Applies the
    strategy levers that live optimizer-side."""
    st = strategy or _F.strategy or DistributedStrategy()
    _F.strategy = st
    if st.sharding:
        from ..sharding import shard_optimizer_states
        shard_optimizer_states(optimizer)
    if st.lars or st.lamb:
        optimizer = _swap_optimizer(optimizer, st)
    if st.gradient_merge:
        from .utils import GradientMergeOptimizer
        optimizer = GradientMergeOptimizer(
            optimizer, k_steps=int(st.gradient_merge_configs["k_steps"]),
            avg=bool(st.gradient_merge_configs["avg"]))
    return optimizer


def _swap_optimizer(optimizer, st):
    """LARS/LAMB meta-optimizers (reference: meta_optimizers/lars_optimizer
    .py / lamb_optimizer.py) — swap the update rule, keep params/lr."""
    from ... import optimizer as optim
    params = optimizer._parameter_list
    lr = optimizer._learning_rate
    if st.lamb:
        cfg = st.lamb_configs
        return optim.Lamb(learning_rate=lr, parameters=params,
                          lamb_weight_decay=float(cfg["lamb_weight_decay"]))
    cfg = st.lars_configs
    return optim.LarsMomentum(
        learning_rate=lr, parameters=params,
        momentum=getattr(optimizer, "_momentum", 0.9),
        lars_coeff=float(cfg["lars_coeff"]),
        lars_weight_decay=float(cfg["lars_weight_decay"]))


def minimize(loss, startup_program=None, parameter_list=None,
             no_grad_set=None):
    """reference: fleet_base.py:1244 — static-mode minimize delegates to the
    program optimizer; dygraph users call optimizer.step() directly."""
    opt = getattr(loss, "_program", None)
    if opt is not None and loss._program._optimizer is not None:
        return loss._program._optimizer.minimize(loss)
    raise RuntimeError("fleet.minimize requires a static-mode loss with an "
                       "optimizer; in dygraph call optimizer.step()")


from .pipeline_parallel import (PipelineLayer, PipelineParallel,  # noqa: E402
                                LayerDesc, SharedLayerDesc)
from . import pipeline_engine  # noqa: E402,F401


# meta_parallel namespace (reference: fleet.meta_parallel)
class meta_parallel:
    VocabParallelEmbedding = VocabParallelEmbedding
    ColumnParallelLinear = ColumnParallelLinear
    RowParallelLinear = RowParallelLinear
    ParallelCrossEntropy = ParallelCrossEntropy
    PipelineLayer = PipelineLayer
    PipelineParallel = PipelineParallel
    LayerDesc = LayerDesc
    SharedLayerDesc = SharedLayerDesc
    get_rng_state_tracker = staticmethod(get_rng_state_tracker)
