"""paddle.distributed.fleet parity: the unified distributed-training entry.

TPU-native equivalent of the reference Fleet facade
(reference: python/paddle/distributed/fleet/base/fleet_base.py:72 Fleet —
init :139, distributed_optimizer :744, distributed_model, minimize :1244;
meta-optimizer selection fleet_base.py:1325 + strategy_compiler.py).

Where the reference's meta-optimizers rewrite Programs, `fleet.init`
compiles the DistributedStrategy into the global Mesh + hybrid topology;
`distributed_model` applies the parallel wrappers (DataParallel for dp,
PipelineParallel for pp — TP layers are already mesh-annotated);
`distributed_optimizer` applies strategy levers (sharding, LARS/LAMB swap,
gradient merge) to the optimizer.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from .strategy import DistributedStrategy
from .mp_layers import (VocabParallelEmbedding, ColumnParallelLinear,
                        RowParallelLinear, ParallelCrossEntropy,
                        get_rng_state_tracker, model_parallel_random_seed)
from ..topology import (CommunicateTopology, HybridCommunicateGroup,
                        set_hybrid_communicate_group,
                        get_hybrid_communicate_group)
from .. import mesh as _mesh
from ..env import get_rank, get_world_size, init_parallel_env
from . import utils  # noqa: F401 (recompute lives here)
from . import fs  # noqa: F401 (LocalFS/HDFSClient facade)
from .moe import moe_ffn, MoELayer  # noqa: F401
from .sequence_parallel import (ring_attention, RingAttention,  # noqa: F401
                                split_sequence, gather_sequence)
from .sharded_embedding import (ShardedEmbedding,  # noqa: F401
                                sparse_row_update, make_row_state)


class _FleetState:
    def __init__(self):
        self.strategy: Optional[DistributedStrategy] = None
        self.hcg: Optional[HybridCommunicateGroup] = None
        self.initialized = False


_F = _FleetState()


def _validate_strategy(st: DistributedStrategy):
    """Every documented strategy flag either takes effect or raises/warns
    here — no silent no-ops (round-3 verdict: a misconfigured job must
    never run non-accelerated without a signal)."""
    import warnings
    hc = st.hybrid_configs
    if st.dgc:
        raise NotImplementedError(
            "DGC (top-k gradient compression) is out of scope by ADR "
            "(docs/adr/0002-dgc.md): on TPU the dense-gradient allreduce "
            "rides ICI and overlaps with compute, and a sparse top-k "
            "exchange compiles to gather/scatter traffic that is slower "
            "than the dense collective it replaces. Set "
            "strategy.compressed_allreduce = True for the shipped "
            "dense-but-quantized exchange (docs/quantization.md), or use "
            "localsgd / gradient_merge to cut cross-host communication.")
    if st.compressed_allreduce_dtype not in ("int8", "bf16"):
        raise ValueError(
            "compressed_allreduce_dtype must be 'int8' or 'bf16', got "
            f"{st.compressed_allreduce_dtype!r}")
    if st.compressed_allreduce and st.fp16_allreduce:
        warnings.warn(
            "both compressed_allreduce and fp16_allreduce are set; "
            "compressed_allreduce wins (fp16_allreduce is its bf16 "
            "special case without block scales)", UserWarning,
            stacklevel=2)
    if st.pipeline and int(hc.get("pp_degree", 1)) <= 1:
        raise ValueError(
            "strategy.pipeline=True requires hybrid_configs['pp_degree']>1 "
            "(the mesh needs a pp axis to pipeline over)")
    tp_deg = int(st.tensor_parallel_configs.get("tensor_parallel_degree", 1))
    if st.tensor_parallel and tp_deg <= 1 and int(hc.get("mp_degree", 1)) <= 1:
        raise ValueError(
            "strategy.tensor_parallel=True requires tensor_parallel_configs"
            "['tensor_parallel_degree']>1 or hybrid_configs['mp_degree']>1")
    if int(st.nccl_comm_num) != 1:
        warnings.warn(
            "nccl_comm_num has no effect on TPU: XLA owns collective "
            "scheduling and multi-stream overlap (no NCCL rings to tune)",
            UserWarning, stacklevel=3)
    if not st.fuse_all_reduce_ops:
        warnings.warn(
            "fuse_all_reduce_ops=False cannot take effect: XLA always "
            "fuses/schedules collectives itself on TPU", UserWarning,
            stacklevel=3)
    if int(st.fuse_grad_size_in_MB) != 32:
        warnings.warn(
            "fuse_grad_size_in_MB has no effect on TPU: gradient bucketing "
            "is XLA's job", UserWarning, stacklevel=3)
    if st.find_unused_parameters:
        warnings.warn(
            "find_unused_parameters is moot here: one global computation, "
            "no replica can disagree about used parameters "
            "(see DataParallel docstring)", UserWarning, stacklevel=3)


def init(role_maker=None, is_collective=False, strategy=None):
    """reference: fleet_base.py:139. Collective mode only: the brpc
    parameter-server world is out of scope by ADR
    (docs/adr/0001-parameter-server.md) — its capability is covered by
    fleet.ShardedEmbedding."""
    if role_maker is not None and not is_collective:
        raise NotImplementedError(
            "parameter-server role makers are out of scope "
            "(docs/adr/0001-parameter-server.md); use is_collective=True "
            "and fleet.ShardedEmbedding for large sparse tables")
    if strategy is None:
        strategy = DistributedStrategy()
    _validate_strategy(strategy)
    _F.strategy = strategy
    init_parallel_env()
    hc = strategy.hybrid_configs
    mp_degree = int(hc.get("mp_degree", 1))
    if strategy.tensor_parallel and mp_degree <= 1:
        # the standalone tensor_parallel flag takes effect through the mesh
        mp_degree = int(
            strategy.tensor_parallel_configs["tensor_parallel_degree"])
    _F.hcg = HybridCommunicateGroup(
        dp_degree=int(hc.get("dp_degree", 1)),
        mp_degree=mp_degree,
        pp_degree=int(hc.get("pp_degree", 1)),
        sharding_degree=int(hc.get("sharding_degree", 1)),
        sep_degree=int(hc.get("sep_degree", 1)))
    set_hybrid_communicate_group(_F.hcg)
    _F.initialized = True
    return _F


def get_hybrid_communicate_group_():
    return _F.hcg


def is_first_worker():
    return get_rank() == 0


def worker_index():
    return get_rank()


def worker_num():
    return get_world_size()


def barrier_worker():
    from ..collective import barrier
    barrier()


def distributed_model(model):
    """reference: fleet_base.py distributed_model — wrap per parallel mode;
    applies the model-side strategy levers (amp, recompute) the reference's
    meta-optimizer stack would have compiled into the program."""
    hcg = _F.hcg
    if hcg is None:
        init()
        hcg = _F.hcg
    st = _F.strategy or DistributedStrategy()
    if st.amp:
        from ... import amp as _amp
        cfg = st.amp_configs
        if cfg.get("use_pure_fp16"):
            _amp.decorate(model, level="O2")
            _amp.enable_operator_amp(
                level="O2", custom_white_list=cfg.get("custom_white_list"),
                custom_black_list=cfg.get("custom_black_list"))
        else:
            _amp.enable_operator_amp(
                level="O1", custom_white_list=cfg.get("custom_white_list"),
                custom_black_list=cfg.get("custom_black_list"))
    if st.recompute:
        _apply_recompute(model, st.recompute_configs.get("checkpoints", []))
    mode = hcg.get_parallel_mode()
    if (st.fp16_allreduce or st.compressed_allreduce) and mode != "data":
        import warnings
        which = ("compressed_allreduce" if st.compressed_allreduce
                 else "fp16_allreduce")
        warnings.warn(
            f"{which} applies to the DataParallel cross-process "
            f"gradient exchange only; it has no effect in {mode!r} mode",
            UserWarning, stacklevel=2)
    if mode == "pipeline":
        from .pipeline_parallel import PipelineParallel
        return PipelineParallel(model, hcg, _F.strategy)
    if mode == "data":
        from ..parallel import DataParallel
        return DataParallel(
            model, bf16_allreduce=bool(st.fp16_allreduce),
            compressed_allreduce=bool(st.compressed_allreduce),
            compressed_allreduce_dtype=str(st.compressed_allreduce_dtype))
    # model/tensor parallel: layers are already mesh-annotated; replicate the
    # rest (reference broadcasts non-mp params across the mp ring)
    for _, p in model.named_parameters():
        if p._sharding_spec is None:
            _mesh.replicate_tensor(p)
    return model


def distributed_optimizer(optimizer, strategy=None):
    """reference: fleet_base.py:744 + the meta-optimizer stack. Applies the
    strategy levers that live optimizer-side."""
    st = strategy or _F.strategy or DistributedStrategy()
    if st is not _F.strategy:
        _validate_strategy(st)  # a strategy passed here must not dodge init's checks
    _F.strategy = st
    if st.sharding:
        from ..sharding import shard_optimizer_states
        shard_optimizer_states(optimizer)
    if st.lars or st.lamb:
        optimizer = _swap_optimizer(optimizer, st)
    if st.localsgd:
        from .utils import LocalSGDOptimizer
        cfg = st.localsgd_configs
        optimizer = LocalSGDOptimizer(
            optimizer, k_steps=int(cfg["k_steps"]),
            begin_step=int(cfg["begin_step"]))
    if st.gradient_merge:
        # gradient merge wraps OUTSIDE localsgd so LocalSGD counts actual
        # parameter updates, not accumulation micro-steps
        from .utils import GradientMergeOptimizer
        optimizer = GradientMergeOptimizer(
            optimizer, k_steps=int(st.gradient_merge_configs["k_steps"]),
            avg=bool(st.gradient_merge_configs["avg"]))
    return optimizer


def _apply_recompute(model, checkpoints):
    """Strategy-driven recompute (reference: meta_optimizers/recompute —
    there a program rewrite; here each named sublayer's forward is routed
    through fleet.utils.recompute, i.e. jax.checkpoint under a trace)."""
    from . import utils as _utils
    names = set(checkpoints or ())
    if not names:
        import warnings
        warnings.warn(
            "strategy.recompute=True with empty recompute_configs"
            "['checkpoints']: nothing to wrap — name the sublayers to "
            "rematerialize (model.named_sublayers() keys)", UserWarning,
            stacklevel=2)
        return
    matched = set()
    for name, sub in model.named_sublayers():
        if name in names:
            matched.add(name)
            orig = sub.forward
            if getattr(orig, "_fleet_recompute", False):
                continue  # idempotent: re-wrapping would nest jax.checkpoint

            def wrapped(*a, __f=orig, **k):
                return _utils.recompute(__f, *a, **k)

            wrapped._fleet_recompute = True
            sub.forward = wrapped
    missing = names - matched
    if missing:
        raise ValueError(
            f"recompute checkpoints not found among sublayers: "
            f"{sorted(missing)}")


def _swap_optimizer(optimizer, st):
    """LARS/LAMB meta-optimizers (reference: meta_optimizers/lars_optimizer
    .py / lamb_optimizer.py) — swap the update rule, keep params/lr."""
    from ... import optimizer as optim
    params = optimizer._parameter_list
    lr = optimizer._learning_rate
    if st.lamb:
        cfg = st.lamb_configs
        return optim.Lamb(learning_rate=lr, parameters=params,
                          lamb_weight_decay=float(cfg["lamb_weight_decay"]))
    cfg = st.lars_configs
    return optim.LarsMomentum(
        learning_rate=lr, parameters=params,
        momentum=getattr(optimizer, "_momentum", 0.9),
        lars_coeff=float(cfg["lars_coeff"]),
        lars_weight_decay=float(cfg["lars_weight_decay"]))


def minimize(loss, startup_program=None, parameter_list=None,
             no_grad_set=None):
    """reference: fleet_base.py:1244 — static-mode minimize delegates to the
    program optimizer; dygraph users call optimizer.step() directly."""
    opt = getattr(loss, "_program", None)
    if opt is not None and loss._program._optimizer is not None:
        return loss._program._optimizer.minimize(loss)
    raise RuntimeError("fleet.minimize requires a static-mode loss with an "
                       "optimizer; in dygraph call optimizer.step()")


from .pipeline_parallel import (PipelineLayer, PipelineParallel,  # noqa: E402
                                LayerDesc, SharedLayerDesc)
from . import pipeline_engine  # noqa: E402,F401


# meta_parallel namespace (reference: fleet.meta_parallel)
class meta_parallel:
    VocabParallelEmbedding = VocabParallelEmbedding
    ColumnParallelLinear = ColumnParallelLinear
    RowParallelLinear = RowParallelLinear
    ParallelCrossEntropy = ParallelCrossEntropy
    PipelineLayer = PipelineLayer
    PipelineParallel = PipelineParallel
    LayerDesc = LayerDesc
    SharedLayerDesc = SharedLayerDesc
    get_rng_state_tracker = staticmethod(get_rng_state_tracker)


# -- reference distributed/fleet/__init__.py export tail ---------------------
# dataset family (defined in distributed/dataset.py; the reference
# re-exports them under fleet)
from ..dataset import DatasetBase, InMemoryDataset, QueueDataset  # noqa: E402,F401


class FileInstantDataset(QueueDataset):
    """reference: dataset.py FileInstantDataset — QueueDataset variant
    that streams each file once without the queue rotation; identical
    here since QueueDataset already streams files in order."""


class BoxPSDataset:
    """reference: dataset.py BoxPSDataset — BoxPS (GPU parameter-server)
    ingestion. The PS world is ADR'd out (docs/adr/0001); sharded
    embeddings + InMemoryDataset cover the capability."""

    def __init__(self, *a, **k):
        raise RuntimeError(
            "BoxPSDataset belongs to the brpc/BoxPS parameter-server "
            "stack, excluded by docs/adr/0001; use InMemoryDataset/"
            "QueueDataset with fleet.sharded_embedding instead")


class Role:
    """reference: role_maker.py Role constants."""
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class PaddleCloudRoleMaker:
    """reference: role_maker.py PaddleCloudRoleMaker — derives the
    process's role from the PADDLE_* launcher env contract (the same
    contract distributed/launch.py writes)."""

    def __init__(self, is_collective=True, **kwargs):
        import os
        self._is_collective = is_collective
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._eps = [e for e in eps.split(",") if e]
        self._size = len(self._eps) or int(
            os.environ.get("PADDLE_TRAINERS_NUM", 1))

    def _is_worker(self):
        return True

    def _is_server(self):
        return False

    def _worker_index(self):
        return self._rank

    def _worker_num(self):
        return self._size

    def _get_trainer_endpoints(self):
        return list(self._eps)


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """reference: role_maker.py UserDefinedRoleMaker — explicit role
    assignment instead of env-derived."""

    def __init__(self, is_collective=True, init_gloo=False, **kwargs):
        super().__init__(is_collective=is_collective)
        self._rank = int(kwargs.get("current_id", self._rank))
        self._eps = list(kwargs.get("worker_endpoints", self._eps))
        self._size = len(self._eps) or self._size


class UtilBase:
    """reference: utils/fs.py + util_base — small cross-worker helpers
    over the collective API."""

    def all_reduce(self, input, mode="sum"):
        from .. import collective as C
        from ...core.tensor import Tensor
        import numpy as np
        t = input if isinstance(input, Tensor) else Tensor(
            __import__("jax.numpy", fromlist=["asarray"]).asarray(
                np.asarray(input)))
        op = {"sum": C.ReduceOp.SUM, "min": C.ReduceOp.MIN,
              "max": C.ReduceOp.MAX}[mode]
        C.all_reduce(t, op=op)
        return t

    def barrier(self):
        from .. import collective as C
        C.barrier()


class Fleet:
    """reference: fleet/base/fleet_base.py Fleet — the class behind the
    module-level singleton; the functional API (fleet.init/
    distributed_model/distributed_optimizer/minimize) IS the instance
    surface here, so this class simply binds those functions."""

    def __init__(self):
        self.util = UtilBase()

    def init(self, role_maker=None, is_collective=False, strategy=None):
        return init(role_maker, is_collective, strategy)

    def distributed_model(self, model):
        return distributed_model(model)

    def distributed_optimizer(self, optimizer, strategy=None):
        return distributed_optimizer(optimizer, strategy)

    def worker_index(self):
        return worker_index()     # the module-level rank accessor


class MultiSlotDataGenerator:
    """reference: data_generator/__init__.py — PS-trainer data generator
    emitting (slot_name, values) records; generate() adapts a sample
    generator to the slot text protocol the datasets ingest."""

    def generate_sample(self, line):
        raise NotImplementedError(
            "subclass MultiSlotDataGenerator and implement "
            "generate_sample(line) returning a zero-arg generator "
            "function whose iteration yields lists of (slot_name, "
            "values) pairs — the reference data_generator contract")

    def run_from_memory(self, lines):
        out = []
        for line in lines:
            for rec in self.generate_sample(line)():
                out.append(rec)
        return out

    def _format(self, rec):
        parts = []
        for name, values in rec:
            parts.append(f"{len(values)} " + " ".join(
                str(v) for v in values))
        return " ".join(parts)


class MultiSlotStringDataGenerator(MultiSlotDataGenerator):
    """String-slot variant (values kept as strings)."""


from ... import metric as metrics  # noqa: E402,F401
