"""Fleet utilities: recompute (activation checkpointing) + gradient merge.

reference:
- recompute: python/paddle/distributed/fleet/utils/recompute.py:63
  RecomputeFunction — a PyLayer that drops activations in forward and
  re-runs the block under the SAVED RNG state in backward (:54
  swith_rng_state). TPU design: ``jax.checkpoint`` (remat) expresses the
  same trade inside the compiled graph; RNG determinism holds because
  dropout keys are explicit functional inputs (functionalize.py routes
  every draw through the trace key), so the re-run sees identical keys by
  construction.
- gradient merge: python/paddle/fluid/optimizer.py:5949
  GradientMergeOptimizer — accumulate k micro-batch gradients, step once.
"""
from __future__ import annotations

from typing import Callable, List

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...core import autograd_engine as _ag
from ...ops.dispatch import apply


def recompute(function: Callable, *args, **kwargs):
    """reference: fleet/utils/recompute.py:63. Under a trace (to_static /
    hapi fused step — the perf path) the block is wrapped in jax.checkpoint
    so XLA rematerializes instead of stashing activations. In eager mode the
    tape already retains exactly the op-level residuals jax.vjp chose;
    the call is then a transparent passthrough."""
    use_reentrant = kwargs.pop("use_reentrant", True)
    preserve_rng_state = kwargs.pop("preserve_rng_state", True)
    del use_reentrant, preserve_rng_state

    leaves = [a for a in jax.tree_util.tree_leaves(
        args, is_leaf=lambda x: isinstance(x, Tensor))
        if isinstance(a, Tensor)]
    traced = any(isinstance(l._data, jax.core.Tracer) for l in leaves)
    if not traced:
        return function(*args, **kwargs)

    # one op through the funnel whose impl re-runs `function` under
    # jax.checkpoint; Tensors rebuilt inside so nested framework ops trace
    def impl(*raws):
        def inner(*rs):
            ts = [Tensor(r) for r in rs]
            out = function(*_rebuild(args, ts), **kwargs)
            out_leaves = jax.tree_util.tree_leaves(
                out, is_leaf=lambda x: isinstance(x, Tensor))
            return tuple(o._data if isinstance(o, Tensor) else o
                         for o in out_leaves)
        return jax.checkpoint(inner)(*raws)

    out_struct = function(*args, **kwargs)  # trace once for the structure
    out_leaves, td = jax.tree_util.tree_flatten(
        out_struct, is_leaf=lambda x: isinstance(x, Tensor))
    res = apply("recompute", impl, *leaves)
    res_list = list(res) if isinstance(res, (list, tuple)) else [res]
    return jax.tree_util.tree_unflatten(td, res_list)


def _rebuild(args, tensors):
    it = iter(tensors)
    return jax.tree_util.tree_map(
        lambda x: next(it) if isinstance(x, Tensor) else x, args,
        is_leaf=lambda x: isinstance(x, Tensor))


class GradientMergeOptimizer:
    """reference: fluid/optimizer.py:5949 — accumulate k steps of gradients,
    apply once (micro-batch accumulation without touching user loops)."""

    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        self._inner = inner_optimizer
        self._k = int(k_steps)
        self._avg = bool(avg)
        self._acc = {}
        self._count = 0

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_inner"), name)

    def step(self):
        inner = self._inner
        self._count += 1
        for p in inner._parameter_list:
            if p._grad is None:
                continue
            if id(p) in self._acc:
                self._acc[id(p)] = self._acc[id(p)] + p._grad
            else:
                self._acc[id(p)] = p._grad
        if self._count < self._k:
            for p in inner._parameter_list:
                p._grad = None
            return
        for p in inner._parameter_list:
            g = self._acc.pop(id(p), None)
            if g is None:
                continue
            p._grad = g / self._k if self._avg else g
        inner.step()
        self._count = 0
        self._acc = {}

    def clear_grad(self, set_to_zero=False):
        self._inner.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()


class LocalSGDOptimizer:
    """reference: distributed/fleet/meta_optimizers/localsgd_optimizer.py:25
    — run k local optimizer steps between parameter averages instead of
    all-reducing gradients every step.

    TPU framing: inside one process, GSPMD's per-step gradient allreduce
    rides ICI and overlaps with compute, so LocalSGD buys nothing there.
    The win is at the multi-host/DCN boundary — each process trains locally
    for ``k_steps`` and parameters are averaged across processes
    periodically. Pair with ``DataParallel`` and do NOT call
    ``apply_collective_grads`` (the whole point is to skip it); this
    wrapper performs the periodic cross-process parameter average.
    """

    def __init__(self, inner_optimizer, k_steps=1, begin_step=1):
        self._inner = inner_optimizer
        self._k = max(1, int(k_steps))
        self._begin = max(1, int(begin_step))
        self._t = 0

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_inner"), name)

    def _average_params(self):
        from .. import collective as C
        for p in self._inner._parameter_list:
            C.all_reduce(p, op=C.ReduceOp.AVG)

    def step(self):
        self._inner.step()
        self._t += 1
        if self._t >= self._begin and (self._t - self._begin) % self._k == 0:
            self._average_params()

    def clear_grad(self, set_to_zero=False):
        self._inner.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
