"""Filesystem facade: LocalFS + HDFS-shaped client.

Reference: python/paddle/distributed/fleet/utils/fs.py — FS base, LocalFS
:115, HDFSClient :419 (shells out to ``hadoop fs``); used by PS and the
auto-checkpoint saver so checkpoint code is storage-agnostic.
"""
from __future__ import annotations

import os
import shutil
import subprocess
from typing import List, Tuple


class ExecuteError(Exception):
    pass


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class FS:
    """Abstract interface (reference: fs.py:40)."""

    def ls_dir(self, fs_path) -> Tuple[List[str], List[str]]:
        raise NotImplementedError

    def is_file(self, fs_path) -> bool:
        raise NotImplementedError

    def is_dir(self, fs_path) -> bool:
        raise NotImplementedError

    def is_exist(self, fs_path) -> bool:
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def need_upload_download(self) -> bool:
        raise NotImplementedError

    def rename(self, fs_src_path, fs_dst_path):
        raise NotImplementedError

    def mv(self, fs_src_path, fs_dst_path, overwrite=False, test_exists=True):
        raise NotImplementedError

    def upload_dir(self, local_dir, dest_dir):
        raise NotImplementedError

    def list_dirs(self, fs_path) -> List[str]:
        raise NotImplementedError

    def touch(self, fs_path, exist_ok=True):
        raise NotImplementedError


class LocalFS(FS):
    """reference: fs.py:115."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for name in sorted(os.listdir(fs_path)):
            if os.path.isdir(os.path.join(fs_path, name)):
                dirs.append(name)
            else:
                files.append(name)
        return dirs, files

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def _rmr(self, fs_path):
        shutil.rmtree(fs_path, ignore_errors=True)

    def _rm(self, fs_path):
        os.remove(fs_path)

    def delete(self, fs_path):
        if not self.is_exist(fs_path):
            return
        if os.path.isfile(fs_path):
            return self._rm(fs_path)
        return self._rmr(fs_path)

    def need_upload_download(self):
        return False

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if exist_ok:
                return
            raise FSFileExistsError(fs_path)
        with open(fs_path, "a"):
            pass

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if not self.is_exist(src_path):
            raise FSFileNotExistsError(src_path)
        if overwrite and self.is_exist(dst_path):
            self.delete(dst_path)
        if self.is_exist(dst_path):
            raise FSFileExistsError(dst_path)
        return self.rename(src_path, dst_path)

    def upload(self, local_path, fs_path):
        if os.path.isdir(local_path):
            shutil.copytree(local_path, fs_path)
        else:
            shutil.copy2(local_path, fs_path)

    def download(self, fs_path, local_path):
        self.upload(fs_path, local_path)

    def upload_dir(self, local_dir, dest_dir):
        shutil.copytree(local_dir, dest_dir)

    def list_dirs(self, fs_path):
        if not self.is_exist(fs_path):
            return []
        return [d for d in sorted(os.listdir(fs_path))
                if os.path.isdir(os.path.join(fs_path, d))]


class HDFSClient(FS):
    """``hadoop fs`` shell-out client (reference: fs.py:419). Raises at
    construction when no hadoop binary is available — this image has none,
    but checkpoint code written against the facade ports unchanged."""

    def __init__(self, hadoop_home=None, configs=None, time_out=5 * 60 * 1000,
                 sleep_inter=1000):
        self._hadoop = (os.path.join(hadoop_home, "bin", "hadoop")
                        if hadoop_home else shutil.which("hadoop"))
        if self._hadoop is None or not os.path.exists(self._hadoop):
            raise ExecuteError(
                "no hadoop binary found; pass hadoop_home= or use LocalFS")
        self._configs = []
        for k, v in (configs or {}).items():
            self._configs += ["-D", f"{k}={v}"]

    def _run(self, *args) -> str:
        cmd = [self._hadoop, "fs"] + self._configs + list(args)
        res = subprocess.run(cmd, capture_output=True, text=True)
        if res.returncode != 0:
            raise ExecuteError(f"{' '.join(cmd)}: {res.stderr}")
        return res.stdout

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        out = self._run("-ls", fs_path)
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def is_exist(self, fs_path):
        try:
            self._run("-test", "-e", fs_path)
            return True
        except ExecuteError:
            return False

    def is_dir(self, fs_path):
        try:
            self._run("-test", "-d", fs_path)
            return True
        except ExecuteError:
            return False

    def is_file(self, fs_path):
        return self.is_exist(fs_path) and not self.is_dir(fs_path)

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        if self.is_exist(fs_path):
            self._run("-rm", "-r", "-f", fs_path)

    def rename(self, fs_src_path, fs_dst_path):
        self._run("-mv", fs_src_path, fs_dst_path)

    def mv(self, fs_src_path, fs_dst_path, overwrite=False,
           test_exists=True):
        if test_exists and not self.is_exist(fs_src_path):
            raise FSFileNotExistsError(fs_src_path)
        if overwrite and self.is_exist(fs_dst_path):
            self.delete(fs_dst_path)
        if self.is_exist(fs_dst_path):
            raise FSFileExistsError(fs_dst_path)
        self.rename(fs_src_path, fs_dst_path)

    def upload(self, local_path, fs_path):
        self._run("-put", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path) and not exist_ok:
            raise FSFileExistsError(fs_path)
        self._run("-touchz", fs_path)

    def need_upload_download(self):
        return True

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]
