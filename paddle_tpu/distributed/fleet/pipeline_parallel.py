"""PipelineLayer + PipelineParallel: the user-facing pipeline API.

reference:
- PipelineLayer (fleet/meta_parallel/parallel_layers/pp_layers.py:61):
  declares the model as a flat layer list partitioned into stages
  (seg_method "uniform" / "layer:<ClassName>").
- PipelineParallel (fleet/meta_parallel/pipeline_parallel.py:107
  train_batch): GPipe — run all microbatch forwards, then backwards, then
  one optimizer step; activations cross stages via send_v2/recv_v2 with a
  shape-meta handshake (:272 _send_meta).

TPU design: stage placement is mesh layout, not process identity. The
schedule semantics (microbatch accumulation == full-batch step) are exact in
every mode; the compiled rotating-scan engine (pipeline_engine.gpipe_apply)
is used by uniform shape-preserving stacks, where true overlap happens
inside one XLA program. Heterogeneous stage lists run the accumulation
schedule op-by-op — same numerics, with XLA placing each stage's weights.
No shape handshake exists anywhere: stage signatures are static at trace
time (SURVEY §7 hard-part list).
"""
from __future__ import annotations

import re
from typing import Callable, List, Optional, Sequence

import numpy as np

from ...core.tensor import Tensor
from ...nn.layer_base import Layer
from ...nn.container import LayerList, Sequential


class LayerDesc:
    """reference: pp_layers.py LayerDesc — deferred layer construction."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """reference: pp_layers.py SharedLayerDesc (tied embeddings)."""

    def __init__(self, key, layer_cls, *args, forward_func=None, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key
        self.forward_func = forward_func


class PipelineLayer(Layer):
    """reference: pp_layers.py:61."""

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform",
                 recompute_interval=0, recompute_ctx=None):
        super().__init__()
        descs = list(layers)
        built = []
        self._shared = {}
        for d in descs:
            if isinstance(d, SharedLayerDesc):
                if d.key not in self._shared:
                    self._shared[d.key] = d.build_layer()
                built.append(self._shared[d.key])
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer) or callable(d):
                built.append(d)
            else:
                raise TypeError(f"bad pipeline layer entry {d!r}")
        self._all_layers = built
        if topology is not None:
            num_stages = topology.get_dim("pipe")
        self._num_stages = int(num_stages or 1)
        self._loss_fn = loss_fn
        self._seg_method = seg_method
        bounds = self._segment(built, self._num_stages, seg_method)
        self._stage_bounds = bounds
        self._stages = []
        for s in range(self._num_stages):
            stage_layers = built[bounds[s]:bounds[s + 1]]
            stage = Sequential(*[l for l in stage_layers])
            self.add_sublayer(f"stage_{s}", stage)
            self._stages.append(stage)

    @staticmethod
    def _segment(layers, num_stages, seg_method) -> List[int]:
        """reference: pp_layers.py SegmentLayers — uniform by count or cut
        at every layer whose class matches 'layer:<Name>'."""
        n = len(layers)
        if isinstance(seg_method, str) and seg_method.startswith("layer:"):
            cls_name = seg_method.split(":", 1)[1]
            marks = [i for i, l in enumerate(layers)
                     if type(l).__name__ == cls_name]
            if len(marks) < num_stages:
                raise ValueError(
                    f"only {len(marks)} '{cls_name}' layers for "
                    f"{num_stages} stages")
            # distribute marked layers across stages as evenly as possible
            per = len(marks) // num_stages
            extra = len(marks) % num_stages
            bounds = [0]
            idx = 0
            for s in range(num_stages - 1):
                idx += per + (1 if s < extra else 0)
                bounds.append(marks[idx] if idx < len(marks) else n)
            bounds.append(n)
            return bounds
        per = n // num_stages
        extra = n % num_stages
        bounds = [0]
        for s in range(num_stages):
            bounds.append(bounds[-1] + per + (1 if s < extra else 0))
        return bounds

    def get_stage_layers(self, stage_id):
        return self._stages[stage_id]

    @property
    def loss_fn(self):
        return self._loss_fn

    def forward(self, x):
        for stage in self._stages:
            for sub in stage._sub_layers.values():
                x = sub(x) if isinstance(sub, Layer) else sub(x)
        return x

    def stage_param_trees(self):
        """Per-stage raw param pytrees (for the compiled engine when stages
        are structurally identical)."""
        trees = []
        for stage in self._stages:
            trees.append([p._data for _, p in stage.named_parameters()])
        return trees

    def stages_uniform(self) -> bool:
        trees = self.stage_param_trees()
        if not trees:
            return False
        sig0 = [(t.shape, str(t.dtype)) for t in trees[0]]
        return all([(t.shape, str(t.dtype)) for t in tr] == sig0
                   for tr in trees[1:])

    def stage_parameters(self):
        """Per-stage lists of Parameter objects (grad targets for the
        compiled engine)."""
        return [[p for _, p in stage.named_parameters()]
                for stage in self._stages]

    def build_stage_pures(self):
        """Functionalize every stage (arbitrary, heterogeneous Layers) into
        pure fns for the compiled engine — no stages_uniform requirement.
        Returns [(pure, meta)] per stage; pure(param_raws, (x,), key, None)
        -> (out_raw, *effects)."""
        from ...jit.functionalize import build_pure
        pures = []
        for stage, pts in zip(self._stages, self.stage_parameters()):
            pures.append(build_pure(stage.forward, pts))
        return pures


class PipelineParallel(Layer):
    """reference: fleet/meta_parallel/pipeline_parallel.py PipelineParallel."""

    def __init__(self, layers: PipelineLayer, hcg=None, strategy=None):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError(
                "fleet.distributed_model with pp_degree > 1 requires a "
                "PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = (strategy.pipeline_configs if strategy is not None
               else {"accumulate_steps": 1})
        self._accumulate_steps = int(cfg.get("accumulate_steps", 1))
        self.scaler = None

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, *args, **kwargs):
        return self._layers.named_parameters(*args, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, sd, *args, **kwargs):
        return self._layers.set_state_dict(sd, *args, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """GPipe accumulation schedule (reference: pipeline_parallel.py:107):
        M microbatch forward/backwards, one optimizer step. Numerically equal
        to the full-batch step for mean losses."""
        from ... import ops
        x, y = data
        if not isinstance(x, Tensor):
            x = Tensor(np.asarray(x))
        if not isinstance(y, Tensor):
            y = Tensor(np.asarray(y))
        m = self._accumulate_steps
        loss_fn = self._layers.loss_fn
        if loss_fn is None:
            raise RuntimeError("PipelineLayer needs loss_fn for train_batch")
        b = x.shape[0]
        if b % m != 0:
            raise ValueError(f"batch {b} not divisible by accumulate_steps {m}")
        mb = b // m
        total = None
        for i in range(m):
            xs = x[i * mb:(i + 1) * mb]
            ys = y[i * mb:(i + 1) * mb]
            out = self._layers(xs)
            loss = loss_fn(out, ys)
            scaled = loss * (1.0 / m)
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total = float(loss.numpy()) if total is None \
                else total + float(loss.numpy())
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return Tensor(np.float32(total / m))

    def train_batch_compiled(self, data, optimizer, lr_scheduler=None):
        """One GPipe step as ONE compiled SPMD program over the "pp" mesh
        axis (pipeline_engine.gpipe_stages): heterogeneous stage lists are
        supported — per-stage activation signatures are fixed at build time
        by abstract eval (the TPU answer to the reference's _send_meta
        handshake, pipeline_parallel.py:272). Forward through the rotating
        schedule, in-pipe per-microbatch loss, grads by AD through
        scan+ppermute, then the framework optimizer applies the update."""
        import jax
        import jax.numpy as jnp
        from ...core import generator as _gen
        from . import pipeline_engine as PE
        from .. import mesh as _mesh

        x, y = data
        x = x if isinstance(x, Tensor) else Tensor(np.asarray(x))
        y = y if isinstance(y, Tensor) else Tensor(np.asarray(y))
        m = self._accumulate_steps
        loss_fn = self._layers.loss_fn
        if loss_fn is None:
            raise RuntimeError("PipelineLayer needs loss_fn for train_batch")

        if getattr(self, "_compiled_step", None) is None:
            mesh = _mesh.ensure_mesh()
            S = self._layers._num_stages
            pures = self._layers.build_stage_pures()
            stage_tensors = self._layers.stage_parameters()
            loss_params = ([p for _, p in loss_fn.named_parameters()]
                           if isinstance(loss_fn, Layer) else [])
            from ...jit.functionalize import build_pure
            loss_pure, _ = build_pure(
                loss_fn.forward if isinstance(loss_fn, Layer) else loss_fn,
                loss_params)

            def step(all_raws, loss_raws, xs, ys, key):
                def mk(s):
                    pure = pures[s][0]

                    def fn(p, inp):
                        k = jax.random.fold_in(key, s)
                        if s == S - 1:
                            carry, xy = inp
                            out = pure(p, (carry,), k, None)[0]
                            return loss_pure(loss_raws, (out, xy[1]),
                                             jax.random.fold_in(key, S),
                                             None)[0]
                        xin = inp[0] if s == 0 else inp  # (x_mb, y_mb) -> x
                        return pure(p, (xin,), k, None)[0]
                    return fn

                losses = PE.gpipe_stages(
                    [mk(s) for s in range(S)], all_raws, (xs, ys),
                    mesh=mesh, last_takes_input=True)
                return jnp.mean(losses)

            grad_step = jax.jit(jax.value_and_grad(step, argnums=(0, 1)))
            self._compiled_step = (grad_step, stage_tensors, loss_params,
                                   pures)

        grad_step, stage_tensors, loss_params, pures = self._compiled_step
        mb = x.shape[0] // m
        xs = x._data.reshape((m, mb) + tuple(x.shape[1:]))
        ys = y._data.reshape((m, mb) + tuple(y.shape[1:]))
        all_raws = [[p._data for p in ts] for ts in stage_tensors]
        loss_raws = [p._data for p in loss_params]
        loss, (g_stages, g_loss) = grad_step(all_raws, loss_raws, xs, ys,
                                             _gen.next_key())
        # effect metadata is populated during the first trace (inside
        # grad_step); reject unsupported stages BEFORE touching any grads
        # so a caller can fall back to train_batch cleanly
        for pm in pures:
            if pm[1].get("effect_holders"):
                raise NotImplementedError(
                    "compiled pipeline does not yet thread buffer effects "
                    "(e.g. BN running stats) — use train_batch for such "
                    "stages")
        for ts, gs in zip(stage_tensors, g_stages):
            for p, g in zip(ts, gs):
                p._grad = g if p._grad is None else p._grad + g
        for p, g in zip(loss_params, g_loss):
            p._grad = g if p._grad is None else p._grad + g
        optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return Tensor(np.asarray(loss))

    def eval_batch(self, data, compute_loss=True):
        from ...core.autograd_engine import no_grad
        x, y = data
        with no_grad():
            out = self._layers(x if isinstance(x, Tensor)
                               else Tensor(np.asarray(x)))
            if compute_loss and self._layers.loss_fn is not None:
                return self._layers.loss_fn(
                    out, y if isinstance(y, Tensor) else Tensor(np.asarray(y)))
        return out
