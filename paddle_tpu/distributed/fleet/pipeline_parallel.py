"""PipelineLayer + PipelineParallel: the user-facing pipeline API.

reference:
- PipelineLayer (fleet/meta_parallel/parallel_layers/pp_layers.py:61):
  declares the model as a flat layer list partitioned into stages
  (seg_method "uniform" / "layer:<ClassName>").
- PipelineParallel (fleet/meta_parallel/pipeline_parallel.py:107
  train_batch): GPipe — run all microbatch forwards, then backwards, then
  one optimizer step; activations cross stages via send_v2/recv_v2 with a
  shape-meta handshake (:272 _send_meta).

TPU design: stage placement is mesh layout, not process identity. The
schedule semantics (microbatch accumulation == full-batch step) are exact in
every mode; the compiled rotating-scan engine (pipeline_engine.gpipe_apply)
is used by uniform shape-preserving stacks, where true overlap happens
inside one XLA program. Heterogeneous stage lists run the accumulation
schedule op-by-op — same numerics, with XLA placing each stage's weights.
No shape handshake exists anywhere: stage signatures are static at trace
time (SURVEY §7 hard-part list).
"""
from __future__ import annotations

import re
from typing import Callable, List, Optional, Sequence

import numpy as np

from ...core.tensor import Tensor
from ...nn.layer_base import Layer
from ...nn.container import LayerList, Sequential


class LayerDesc:
    """reference: pp_layers.py LayerDesc — deferred layer construction."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """reference: pp_layers.py SharedLayerDesc (tied embeddings)."""

    def __init__(self, key, layer_cls, *args, forward_func=None, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key
        self.forward_func = forward_func


class PipelineLayer(Layer):
    """reference: pp_layers.py:61."""

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform",
                 recompute_interval=0, recompute_ctx=None):
        super().__init__()
        descs = list(layers)
        built = []
        self._shared = {}
        for d in descs:
            if isinstance(d, SharedLayerDesc):
                if d.key not in self._shared:
                    self._shared[d.key] = d.build_layer()
                built.append(self._shared[d.key])
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer) or callable(d):
                built.append(d)
            else:
                raise TypeError(f"bad pipeline layer entry {d!r}")
        self._all_layers = built
        if topology is not None:
            num_stages = topology.get_dim("pipe")
        self._num_stages = int(num_stages or 1)
        self._loss_fn = loss_fn
        self._seg_method = seg_method
        bounds = self._segment(built, self._num_stages, seg_method)
        self._stage_bounds = bounds
        self._stages = []
        for s in range(self._num_stages):
            stage_layers = built[bounds[s]:bounds[s + 1]]
            stage = Sequential(*[l for l in stage_layers])
            self.add_sublayer(f"stage_{s}", stage)
            self._stages.append(stage)

    @staticmethod
    def _segment(layers, num_stages, seg_method) -> List[int]:
        """reference: pp_layers.py SegmentLayers — uniform by count or cut
        at every layer whose class matches 'layer:<Name>'."""
        n = len(layers)
        if isinstance(seg_method, str) and seg_method.startswith("layer:"):
            cls_name = seg_method.split(":", 1)[1]
            marks = [i for i, l in enumerate(layers)
                     if type(l).__name__ == cls_name]
            if len(marks) < num_stages:
                raise ValueError(
                    f"only {len(marks)} '{cls_name}' layers for "
                    f"{num_stages} stages")
            # distribute marked layers across stages as evenly as possible
            per = len(marks) // num_stages
            extra = len(marks) % num_stages
            bounds = [0]
            idx = 0
            for s in range(num_stages - 1):
                idx += per + (1 if s < extra else 0)
                bounds.append(marks[idx] if idx < len(marks) else n)
            bounds.append(n)
            return bounds
        per = n // num_stages
        extra = n % num_stages
        bounds = [0]
        for s in range(num_stages):
            bounds.append(bounds[-1] + per + (1 if s < extra else 0))
        return bounds

    def get_stage_layers(self, stage_id):
        return self._stages[stage_id]

    @property
    def loss_fn(self):
        return self._loss_fn

    def forward(self, x):
        for stage in self._stages:
            for sub in stage._sub_layers.values():
                x = sub(x) if isinstance(sub, Layer) else sub(x)
        return x

    def stage_param_trees(self):
        """Per-stage raw param pytrees (for the compiled engine when stages
        are structurally identical)."""
        trees = []
        for stage in self._stages:
            trees.append([p._data for _, p in stage.named_parameters()])
        return trees

    def stages_uniform(self) -> bool:
        trees = self.stage_param_trees()
        if not trees:
            return False
        sig0 = [(t.shape, str(t.dtype)) for t in trees[0]]
        return all([(t.shape, str(t.dtype)) for t in tr] == sig0
                   for tr in trees[1:])


class PipelineParallel(Layer):
    """reference: fleet/meta_parallel/pipeline_parallel.py PipelineParallel."""

    def __init__(self, layers: PipelineLayer, hcg=None, strategy=None):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError(
                "fleet.distributed_model with pp_degree > 1 requires a "
                "PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = (strategy.pipeline_configs if strategy is not None
               else {"accumulate_steps": 1})
        self._accumulate_steps = int(cfg.get("accumulate_steps", 1))
        self.scaler = None

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, *args, **kwargs):
        return self._layers.named_parameters(*args, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, sd, *args, **kwargs):
        return self._layers.set_state_dict(sd, *args, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """GPipe accumulation schedule (reference: pipeline_parallel.py:107):
        M microbatch forward/backwards, one optimizer step. Numerically equal
        to the full-batch step for mean losses."""
        from ... import ops
        x, y = data
        if not isinstance(x, Tensor):
            x = Tensor(np.asarray(x))
        if not isinstance(y, Tensor):
            y = Tensor(np.asarray(y))
        m = self._accumulate_steps
        loss_fn = self._layers.loss_fn
        if loss_fn is None:
            raise RuntimeError("PipelineLayer needs loss_fn for train_batch")
        b = x.shape[0]
        if b % m != 0:
            raise ValueError(f"batch {b} not divisible by accumulate_steps {m}")
        mb = b // m
        total = None
        for i in range(m):
            xs = x[i * mb:(i + 1) * mb]
            ys = y[i * mb:(i + 1) * mb]
            out = self._layers(xs)
            loss = loss_fn(out, ys)
            scaled = loss * (1.0 / m)
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total = float(loss.numpy()) if total is None \
                else total + float(loss.numpy())
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return Tensor(np.float32(total / m))

    def eval_batch(self, data, compute_loss=True):
        from ...core.autograd_engine import no_grad
        x, y = data
        with no_grad():
            out = self._layers(x if isinstance(x, Tensor)
                               else Tensor(np.asarray(x)))
            if compute_loss and self._layers.loss_fn is not None:
                return self._layers.loss_fn(
                    out, y if isinstance(y, Tensor) else Tensor(np.asarray(y)))
        return out
