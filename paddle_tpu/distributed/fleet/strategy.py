"""DistributedStrategy: the serializable knob tree for distributed training.

TPU-native equivalent of the reference's proto-backed strategy
(reference: paddle/fluid/framework/distributed_strategy.proto:147,
python/paddle/distributed/fleet/base/distributed_strategy.py:104 — there the
strategy selects meta-optimizers that REWRITE the Program; here it compiles
to a Mesh + per-parameter/optimizer-state PartitionSpecs + train-step
options (recompute/gradient merge), and XLA does the rewriting).

Serialization is JSON (save_to_prototxt/load_from_prototxt keep their names
for API parity and read/write the JSON file).
"""
from __future__ import annotations

import copy
import json
from typing import Any, Dict


_DEFAULTS: Dict[str, Any] = {
    "amp": False,
    "amp_configs": {"init_loss_scaling": 32768.0, "use_pure_fp16": False,
                    "custom_white_list": [], "custom_black_list": []},
    "recompute": False,
    "recompute_configs": {"checkpoints": []},
    "gradient_merge": False,
    "gradient_merge_configs": {"k_steps": 1, "avg": True},
    "sharding": False,
    "sharding_configs": {"sharding_degree": 8, "stage": 1},
    "lamb": False,
    "lamb_configs": {"lamb_weight_decay": 0.01, "exclude_from_weight_decay": []},
    "lars": False,
    "lars_configs": {"lars_coeff": 0.001, "lars_weight_decay": 0.0005,
                     "epsilon": 0.0, "exclude_from_weight_decay": []},
    "localsgd": False,
    "localsgd_configs": {"k_steps": 1, "begin_step": 1},
    "dgc": False,
    "dgc_configs": {"rampup_begin_step": 0, "rampup_step": 1,
                    "sparsity": [0.999]},
    "fp16_allreduce": False,
    # quantized gradient allreduce (EQuARX-style block-scaled wire format;
    # docs/quantization.md) — the shipped alternative to the out-of-scope
    # DGC slot above. dtype: "int8" (block-scaled, ~3.9x fewer wire bytes
    # than f32) or "bf16" (2x, exact-sum-in-f32).
    "compressed_allreduce": False,
    "compressed_allreduce_dtype": "int8",
    "pipeline": False,
    "pipeline_configs": {"accumulate_steps": 1, "micro_batch_size": 1},
    "tensor_parallel": False,
    "tensor_parallel_configs": {"tensor_parallel_degree": 1},
    "hybrid_configs": {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                       "sharding_degree": 1, "sep_degree": 1},
    "nccl_comm_num": 1,
    "find_unused_parameters": False,
    "fuse_all_reduce_ops": True,
    "fuse_grad_size_in_MB": 32,
}


class DistributedStrategy:
    def __init__(self):
        self._d = copy.deepcopy(_DEFAULTS)

    def __getattr__(self, name):
        d = object.__getattribute__(self, "_d")
        if name in d:
            return d[name]
        raise AttributeError(name)

    def __setattr__(self, name, value):
        if name == "_d":
            object.__setattr__(self, name, value)
            return
        if name not in self._d:
            raise AttributeError(f"unknown strategy field {name!r}")
        if name.endswith("_configs"):
            merged = dict(self._d[name])
            merged.update(value)
            self._d[name] = merged
        else:
            self._d[name] = value

    # -- serialization (JSON; names kept for reference parity) --------------
    def to_json(self) -> str:
        return json.dumps(self._d, indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "DistributedStrategy":
        st = cls()
        data = json.loads(s)
        for k, v in data.items():
            if k in st._d:
                st._d[k] = v
        return st

    def save_to_prototxt(self, path):
        with open(path, "w") as f:
            f.write(self.to_json())

    def load_from_prototxt(self, path):
        with open(path) as f:
            self._d = DistributedStrategy.from_json(f.read())._d

    def __repr__(self):
        return f"DistributedStrategy({json.dumps(self._d, sort_keys=True)})"

    def __eq__(self, other):
        return isinstance(other, DistributedStrategy) and self._d == other._d

    def mesh_axes(self) -> Dict[str, int]:
        """Compile the hybrid config to mesh axes (only degrees > 1)."""
        hc = self.hybrid_configs
        axes = {}
        for key, axis in (("dp_degree", "dp"), ("pp_degree", "pp"),
                          ("sharding_degree", "sharding"),
                          ("sep_degree", "sp"), ("mp_degree", "mp")):
            if int(hc.get(key, 1)) > 1:
                axes[axis] = int(hc[key])
        return axes
