"""Mesh-sharded embedding tables: the TPU-native answer to the reference's
parameter-server sparse tables (see docs/adr/0001-parameter-server.md).

Reference capability being replaced:
- `paddle/fluid/distributed/table/common_sparse_table.h:112` — vocab rows
  sharded across PS servers, pulled/pushed over brpc, per-row Adam state
- `python/paddle/distributed/fleet/runtime/the_one_ps.py:434` — the
  runtime that rewrites programs into send/recv against those tables

TPU design: the table is ONE jax array sharded on the vocab dimension over
mesh axes; lookups are plain gathers that GSPMD lowers to the right
collectives over ICI, and per-row optimizer state shards with the table.
No RPC layer, no program rewriting — sharding annotations do the work.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor, Parameter
from ...nn.layer_base import Layer
from ...ops.dispatch import apply
from .. import mesh as _mesh


class ShardedEmbedding(Layer):
    """Embedding whose table is sharded on the vocab dim over mesh axes.

    Unlike ``fleet.VocabParallelEmbedding`` (the Megatron TP layer for use
    *inside* shard_map), this is the GSPMD form: construct under a mesh,
    call it from jitted or eager code with global ids — XLA partitions the
    gather. Scales table memory with the number of devices on ``axes``.
    """

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 axes: Tuple[str, ...] = None, mesh=None, weight_attr=None,
                 sparse: bool = False, name: Optional[str] = None):
        super().__init__()
        self._num_embeddings = int(num_embeddings)
        self._embedding_dim = int(embedding_dim)
        m = mesh or _mesh.ensure_mesh()
        self._mesh = m
        axes = tuple(axes) if axes is not None else tuple(m.axis_names)
        n_shards = int(np.prod([m.shape[a] for a in axes])) or 1
        if num_embeddings % n_shards != 0:
            raise ValueError(
                f"num_embeddings {num_embeddings} must divide the {axes} "
                f"shard count {n_shards} (pad the vocab)")
        self._axes = axes
        from ...nn import initializer as I
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0 / np.sqrt(embedding_dim)))
        sharding = NamedSharding(m, P(axes, None))
        self.weight._data = jax.device_put(self.weight._data, sharding)
        self.weight._sharding_spec = P(axes, None)

    @property
    def partition_spec(self):
        return P(self._axes, None)

    def forward(self, ids):
        w, table_spec, m = self.weight, self.partition_spec, self._mesh

        def impl(table, idx):
            out = jnp.take(table, idx, axis=0)
            return jax.lax.with_sharding_constraint(
                out, NamedSharding(m, P()))  # gathered rows replicated

        return apply("sharded_embedding", impl, w, ids)

    def state_dict(self, *a, **k):
        sd = super().state_dict(*a, **k)
        return sd


@jax.jit
def _sparse_adam(t, mm, vv, idx, g, lr, beta1, beta2, eps, step):
    # segment-sum duplicate ids into dense per-row grads via scatter-add
    dense_g = jnp.zeros_like(t).at[idx].add(g)
    touched = jnp.zeros((t.shape[0], 1), t.dtype).at[idx].set(1.0)
    new_m = jnp.where(touched > 0, beta1 * mm + (1 - beta1) * dense_g, mm)
    new_v = jnp.where(touched > 0,
                      beta2 * vv + (1 - beta2) * dense_g * dense_g, vv)
    mhat = new_m / (1 - beta1 ** step)
    vhat = new_v / (1 - beta2 ** step)
    new_t = jnp.where(touched > 0,
                      t - lr * mhat / (jnp.sqrt(vhat) + eps), t)
    return new_t, new_m, new_v


def sparse_row_update(table, m_state, v_state, ids, grad_rows, *, lr=1e-3,
                      beta1=0.9, beta2=0.999, eps=1e-8, step=1):
    """Row-sparse Adam update against a (sharded) table — the semantics of
    the reference's CommonSparseTable push (common_sparse_table.h:112):
    duplicate ids are segment-summed, only touched rows update their Adam
    moments. One fused XLA program; GSPMD partitions the scatters the same
    way as the table.

    All of ``table``/``m_state``/``v_state`` are [V, D] arrays (Tensors or
    raw); ``ids`` [N] int, ``grad_rows`` [N, D]. Returns the updated
    (table, m, v) — functional, caller rebinds.
    """
    t_raw = table._data if isinstance(table, Tensor) else table
    m_raw = m_state._data if isinstance(m_state, Tensor) else m_state
    v_raw = v_state._data if isinstance(v_state, Tensor) else v_state
    ids_raw = ids._data if isinstance(ids, Tensor) else jnp.asarray(ids)
    g_raw = (grad_rows._data if isinstance(grad_rows, Tensor)
             else jnp.asarray(grad_rows))

    # hyperparams traced (module-level jit: ONE compile per table shape,
    # not one per call/step value)
    new_t, new_m, new_v = _sparse_adam(
        t_raw, m_raw, v_raw, ids_raw, g_raw,
        jnp.asarray(lr, t_raw.dtype), jnp.asarray(beta1, t_raw.dtype),
        jnp.asarray(beta2, t_raw.dtype), jnp.asarray(eps, t_raw.dtype),
        jnp.asarray(step, jnp.float32))
    if isinstance(table, Tensor):
        return Tensor(new_t), Tensor(new_m), Tensor(new_v)
    return new_t, new_m, new_v


def make_row_state(table, mesh=None):
    """Adam moment tensors sharded exactly like the table (the PS servers'
    per-row optimizer state, here just same-spec arrays)."""
    raw = table._data if isinstance(table, Tensor) else table
    zeros = jnp.zeros_like(raw)
    sh = getattr(raw, "sharding", None)
    if sh is not None:
        zeros = jax.device_put(zeros, sh)
    return zeros, jnp.zeros_like(zeros)
