"""Compiled GPipe engine: rotating microbatch schedule over the "pp" axis.

TPU-native equivalent of the reference's pipeline runtime
(reference: python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py:107 train_batch — the host loop issuing per-microbatch
forward/backward with send_v2/recv_v2 between stage processes;
framework/section_worker.cc:99 SectionWorker::TrainFiles).

Here the whole schedule is ONE compiled SPMD program ("pipelined scan",
the standard TPU formulation): every pp rank holds one stage's parameters
(stacked pytree sharded over "pp"), a lax.scan ticks M + S - 1 times, each
tick computes one stage on every rank simultaneously and rotates
activations with ppermute — warm-up/drain bubbles fall out of the tick
index arithmetic, and reverse-mode AD through scan+ppermute yields the
pipelined backward automatically (no hand-written p2p grad schedule).
"""
from __future__ import annotations

from typing import Callable

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import mesh as _mesh


def stack_stage_params(param_trees):
    """Stack S structurally-identical per-stage param pytrees along a new
    leading axis (to be sharded over "pp")."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *param_trees)


def gpipe_apply(block_fn: Callable, stacked_params, mb_x, mesh=None,
                axis="pp"):
    """Apply S pipeline stages to M microbatches.

    block_fn(params, x) -> y must be shape-preserving (x and y same shape —
    the transformer-block case). For heterogeneous stages (embedding →
    blocks → head, different shapes per stage) use ``gpipe_blocks`` /
    ``gpipe_stages`` below instead. ``stacked_params``: pytree with leading
    dim S on every leaf. ``mb_x``: [M, ...] microbatched input (replicated).
    Returns [M, ...] outputs. Differentiable end-to-end.
    """
    m = mesh or _mesh.ensure_mesh()
    S = int(m.shape[axis])
    M = int(mb_x.shape[0])
    T = M + S - 1

    def per_rank(params_shard, xs):
        # params_shard leaves: [1, ...] (this rank's stage)
        params_local = jax.tree_util.tree_map(lambda a: a[0], params_shard)
        rank = lax.axis_index(axis)

        # mark the carries device-varying for shard_map's vma type system
        state0 = lax.pcast(jnp.zeros_like(xs[0]), (axis,), to="varying")
        outbuf0 = lax.pcast(jnp.zeros_like(xs), (axis,), to="varying")

        def tick(carry, t):
            state, outbuf = carry
            x_t = xs[jnp.clip(t, 0, M - 1)]
            inp = jnp.where(rank == 0, x_t, state)
            y = block_fn(params_local, inp)
            # last rank collects microbatch t-(S-1) once the pipe is full
            oi = jnp.clip(t - (S - 1), 0, M - 1)
            write = jnp.logical_and(rank == S - 1, t >= S - 1)
            cur = lax.dynamic_index_in_dim(outbuf, oi, 0, keepdims=False)
            outbuf = lax.dynamic_update_index_in_dim(
                outbuf, jnp.where(write, y, cur), oi, 0)
            # rotate activations one stage forward
            nxt = lax.ppermute(y, axis, perm=[(i, i + 1) for i in range(S - 1)])
            return (nxt, outbuf), None

        (_, outbuf), _ = lax.scan(tick, (state0, outbuf0), jnp.arange(T))
        # replicate the collected outputs from the last rank
        contrib = jnp.where(rank == S - 1, outbuf, jnp.zeros_like(outbuf))
        return lax.psum(contrib, axis)

    spec_axes_only = P(axis)
    in_specs = (jax.tree_util.tree_map(lambda _: spec_axes_only,
                                       stacked_params), P())
    return jax.shard_map(per_rank, mesh=m, in_specs=in_specs,
                         out_specs=P())(stacked_params, mb_x)


def split_microbatches(x, num_micro):
    """[B, ...] -> [M, B/M, ...] (reference: pipeline micro_batch_size)."""
    b = x.shape[0]
    if b % num_micro != 0:
        raise ValueError(f"batch {b} not divisible by {num_micro} microbatches")
    return x.reshape((num_micro, b // num_micro) + x.shape[1:])


# -- heterogeneous stages -----------------------------------------------------
#
# The reference pipeline exchanges activations of arbitrary per-stage shape
# with a runtime shape handshake (pipeline_parallel.py:272 _send_meta). On
# TPU all signatures must be static at trace time, so they are *declared /
# inferred at build time* with jax.eval_shape and validated once:
#   x_sig --embed--> carry_sig --block--> carry_sig ... --head--> out_sig
# Only the inter-stage carry rides the rotating ppermute buffer; the first
# stage reads microbatch inputs directly and the last stage writes to a
# separate output buffer, so the pipe's entry/exit types are unconstrained.


def _sig_of(tree):
    return jax.tree_util.tree_map(
        lambda a: (tuple(a.shape), str(a.dtype)), tree)


def _vary_tree(t, axes):
    """Mark every leaf device-varying on the given axis/axes for
    shard_map's vma type system (idempotent — axes already varying on a
    leaf are skipped)."""
    if isinstance(axes, str):
        axes = (axes,)

    def one(a):
        vma = getattr(jax.typeof(a), "vma", None)
        if vma is None:      # jax < 0.6: no vma system, nothing to mark
            return a
        missing = tuple(ax for ax in axes if ax not in vma)
        if not missing:
            return a
        return lax.pcast(a, missing, to="varying")
    return jax.tree_util.tree_map(one, t)


def _rotating_schedule(axis, vary_axes, S, M, carry_aval, out_aval,
                       xs_local, compute):
    """The shared GPipe rotating-scan core: tick over M + S - 1 steps,
    feed stage 0 from the microbatch stream, collect the last rank's
    outputs at the pipe-depth lag, rotate carries with ppermute, and shed
    varying axes at the end. ``compute(rank, state, x_t, x_last, vary)``
    -> (carry_out, out_t) supplies the per-engine stage dispatch."""
    rank = lax.axis_index(axis)

    def vary(t):
        return _vary_tree(t, vary_axes)

    state0 = vary(jax.tree_util.tree_map(
        lambda av: jnp.zeros(av.shape, av.dtype), carry_aval))
    outbuf0 = vary(jax.tree_util.tree_map(
        lambda av: jnp.zeros((M,) + tuple(av.shape), av.dtype), out_aval))
    T = M + S - 1

    def tick(carry, t):
        state, outbuf = carry
        x_t = jax.tree_util.tree_map(
            lambda a: a[jnp.clip(t, 0, M - 1)], xs_local)
        # the microbatch the LAST stage is processing lags the pipe depth
        x_last = jax.tree_util.tree_map(
            lambda a: a[jnp.clip(t - (S - 1), 0, M - 1)], xs_local)
        c, out_t = compute(rank, state, x_t, x_last, vary)
        oi = jnp.clip(t - (S - 1), 0, M - 1)
        write = jnp.logical_and(rank == S - 1, t >= S - 1)

        def upd(buf, o):
            cur = lax.dynamic_index_in_dim(buf, oi, 0, keepdims=False)
            return lax.dynamic_update_index_in_dim(
                buf, jnp.where(write, o, cur), oi, 0)
        outbuf = jax.tree_util.tree_map(upd, outbuf, out_t)
        nxt = jax.tree_util.tree_map(
            lambda a: lax.ppermute(
                a, axis, perm=[(i, i + 1) for i in range(S - 1)]), c)
        return (nxt, outbuf), None

    (_, outbuf), _ = lax.scan(tick, (state0, outbuf0), jnp.arange(T))

    # replicate the collected outputs from the last rank, then shed any
    # remaining varying axes (dp contributions are averaged; other axes,
    # e.g. "mp" after an in-head all_gather, hold identical values so
    # pmean is an identity that satisfies out_specs=P())
    def finalize(b):
        b = lax.psum(jnp.where(rank == S - 1, b, jnp.zeros_like(b)), axis)
        vma = getattr(jax.typeof(b), "vma", None)
        # jax < 0.6 cannot report which axes still vary: pmean over all of
        # them — identity for the already-replicated ones (see above), the
        # real dp average otherwise, and it satisfies the old rep checker
        rest = tuple(ax for ax in vary_axes
                     if vma is None or ax in vma)
        return lax.pmean(b, rest) if rest else b
    return jax.tree_util.tree_map(finalize, outbuf)


def infer_pipeline_signatures(embed_fn, block_fn, head_fn, embed_params,
                              block_params_one_stage, head_params, x_mb,
                              head_takes_input=False):
    """Abstract-eval the stage chain; returns (carry_aval, out_aval).
    Raises if the block does not preserve the carry signature (the static
    equivalent of a _send_meta mismatch)."""
    carry = jax.eval_shape(embed_fn, embed_params, x_mb)
    carry2 = jax.eval_shape(block_fn, block_params_one_stage, carry)
    if _sig_of(carry) != _sig_of(carry2):
        raise ValueError(
            f"pipeline block must preserve the inter-stage signature: "
            f"got {_sig_of(carry)} -> {_sig_of(carry2)}")
    if head_takes_input:
        out = jax.eval_shape(head_fn, head_params, carry, x_mb)
    else:
        out = jax.eval_shape(head_fn, head_params, carry)
    return carry, out


def gpipe_blocks(embed_fn, block_fn, head_fn, embed_params,
                 stacked_block_params, head_params, xs, mesh=None,
                 axis="pp", carry_sig=None, out_sig=None,
                 head_takes_input=False, batch_axis=None,
                 embed_specs=None, block_specs=None, head_specs=None):
    """Pipeline a full model — embed → S×blocks → head — in ONE compiled
    rotating-scan program (heterogeneous first/last stages).

    - ``embed_fn(embed_params, x_mb) -> carry`` runs as stage 0's preamble
      (e.g. token+position embedding; ``x_mb`` may be int ids).
    - ``block_fn(stage_params, carry) -> carry`` is the uniform stage body;
      ``stacked_block_params`` leaves are [S, ...] and are sharded over the
      ``axis`` mesh axis — block (the bulk) memory scales 1/S per rank.
    - ``head_fn(head_params, carry) -> out`` runs as the last stage's
      postamble (final norm + logits, or a per-microbatch loss). With
      ``head_takes_input=True`` it is called as
      ``head_fn(head_params, carry, x_mb)`` where ``x_mb`` is the
      microbatch the carry belongs to (for in-pipe loss: labels ride xs).
    - ``embed_params``/``head_params`` are replicated on every rank (for
      GPT they are the tied embedding table, needed on both ends anyway).

    ``xs``: [M, ...] microbatched inputs. Returns [M, *out.shape].
    Differentiable end-to-end (AD through scan + ppermute + cond).

    ``batch_axis``: name of a data-parallel mesh axis — each dp slice runs
    the pipe on its shard of the microbatch dim 1 and the collected outputs
    are pmean'd over it (dp×pp hybrid in one program).

    ``embed_specs``/``block_specs``/``head_specs``: PartitionSpec pytrees
    overriding the default placement (embed/head replicated, blocks
    P(axis) on dim 0) — used for tensor-parallel hybrids where block
    weights are additionally sharded over "mp" and the stage fns contain
    the matching TP collectives (declare carry_sig/out_sig then).
    """
    m = mesh or _mesh.ensure_mesh()
    S = int(m.shape[axis])
    M = int(jax.tree_util.tree_leaves(xs)[0].shape[0])

    if carry_sig is not None and out_sig is not None:
        # declared signatures (needed when stage fns contain collectives
        # that can't abstract-eval outside the mesh trace, e.g. TP psum)
        carry_aval, out_aval = carry_sig, out_sig
    else:
        block_one = jax.tree_util.tree_map(
            lambda a: a[0], stacked_block_params)
        # signatures are LOCAL (per-device) shapes: dp shards dim 1
        bs = int(m.shape[batch_axis]) if batch_axis else 1  # noqa: PTA001 -- mesh axis size is a static host int, never a tracer
        x_aval = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(
                (a.shape[1] // bs,) + tuple(a.shape[2:]), a.dtype), xs)
        carry_aval, out_aval = infer_pipeline_signatures(
            embed_fn, block_fn, head_fn, embed_params, block_one,
            head_params, x_aval, head_takes_input=head_takes_input)

    # branches joined by cond/where must agree on varying axes, so mark
    # values varying on EVERY mesh axis; the finalize step sheds them
    vary_axes = tuple(m.axis_names)

    def per_rank(emb_p, blocks_shard, head_p, xs_local):
        block_local = jax.tree_util.tree_map(lambda a: a[0], blocks_shard)
        # Replicated inputs used inside rank-divergent cond branches must be
        # varying BEFORE the branch: the transpose of an unvarying->varying
        # use is a psum, and a psum inside a divergent branch deadlocks.
        # Varying them here moves that psum to the (uniform) shard_map
        # boundary.
        emb_p = _vary_tree(emb_p, vary_axes)
        head_p = _vary_tree(head_p, vary_axes)
        xs_local = _vary_tree(xs_local, vary_axes)

        def compute(rank, state, x_t, x_last, vary):
            # stage-0 preamble: embed this tick's microbatch; other ranks
            # use the rotated-in activation (cond executes one branch, so
            # embedding FLOPs happen on rank 0 only)
            inp = lax.cond(rank == 0,
                           lambda: vary(embed_fn(emb_p, x_t)),
                           lambda: state)
            y = block_fn(block_local, inp)
            # last-rank postamble once the pipe is full
            apply_head = ((lambda: vary(head_fn(head_p, y, x_last)))
                          if head_takes_input
                          else (lambda: vary(head_fn(head_p, y))))
            out_t = lax.cond(rank == S - 1,
                             apply_head,
                             lambda: vary(jax.tree_util.tree_map(
                                 lambda av: jnp.zeros(av.shape, av.dtype),
                                 out_aval)))
            return y, out_t

        return _rotating_schedule(axis, vary_axes, S, M, carry_aval,
                                  out_aval, xs_local, compute)

    xs_spec = P() if batch_axis is None else P(None, batch_axis)
    in_specs = (embed_specs if embed_specs is not None else
                jax.tree_util.tree_map(lambda _: P(), embed_params),
                block_specs if block_specs is not None else
                jax.tree_util.tree_map(lambda _: P(axis),
                                       stacked_block_params),
                head_specs if head_specs is not None else
                jax.tree_util.tree_map(lambda _: P(), head_params),
                jax.tree_util.tree_map(lambda _: xs_spec, xs))
    return jax.shard_map(per_rank, mesh=m, in_specs=in_specs,
                         out_specs=P())(embed_params, stacked_block_params,
                                        head_params, xs)


def gpipe_stages(stage_fns, stage_params, xs, mesh=None, axis="pp",
                 last_takes_input=False, carry_sig=None, out_sig=None):
    """Pipeline an arbitrary list of per-stage functions (the compiled path
    for heterogeneous ``PipelineLayer`` stage lists).

    ``stage_fns[s](stage_params[s], inp) -> out``; stage 0 consumes the
    microbatch input, later stages consume the previous stage's output, and
    all inter-stage signatures must agree (validated by abstract eval — the
    build-time _send_meta). Stage dispatch is ``lax.switch`` on the rank, so
    each rank computes only its own stage; params are replicated across
    ranks (arbitrary per-stage structures can't be mesh-stacked — use
    :func:`gpipe_blocks` when the bulk of the model is a uniform block
    stack and memory scaling matters).

    ``last_takes_input=True`` gives the last stage the *microbatch input*
    too — ``stage_fns[-1](params, (carry, x_mb))`` with ``x_mb`` aligned to
    the microbatch the carry belongs to (for in-pipe loss against labels
    carried in ``xs``). ``carry_sig``/``out_sig`` declare signatures when
    stage fns contain collectives that can't abstract-eval here.

    Returns [M, *out.shape] from the last stage. Differentiable.
    """
    m = mesh or _mesh.ensure_mesh()
    S = int(m.shape[axis])  # noqa: PTA001 -- mesh axis size is a static host int, never a tracer
    if len(stage_fns) != S:
        raise ValueError(f"{len(stage_fns)} stage fns for {axis}={S} mesh")
    M = int(jax.tree_util.tree_leaves(xs)[0].shape[0])  # noqa: PTA001 -- array shape is concrete at trace time

    if carry_sig is not None and out_sig is not None:
        carry_aval, out_aval = carry_sig, out_sig
    else:
        x_aval = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape[1:], a.dtype), xs)
        sig = jax.eval_shape(stage_fns[0], stage_params[0], x_aval)
        carry_aval = sig
        for s in range(1, S):
            arg = (sig, x_aval) if (last_takes_input and s == S - 1) else sig
            nxt_sig = jax.eval_shape(stage_fns[s], stage_params[s], arg)
            if s < S - 1 and _sig_of(nxt_sig) != _sig_of(sig):
                raise ValueError(
                    f"stage {s} changes the inter-stage signature "
                    f"{_sig_of(sig)} -> {_sig_of(nxt_sig)}; only the last "
                    f"stage may (declare signatures so every middle stage "
                    f"preserves them)")
            sig = nxt_sig
        out_aval = sig

    vary_axes = tuple(m.axis_names)

    def per_rank(params_all, xs_local):
        # see gpipe_blocks: vary replicated inputs before divergent branches
        params_all = _vary_tree(params_all, vary_axes)
        xs_local = _vary_tree(xs_local, vary_axes)

        def zeros_of(aval_tree):
            return jax.tree_util.tree_map(
                lambda av: jnp.zeros(av.shape, av.dtype), aval_tree)

        def compute(rank, state, x_t, x_last, vary):
            def make_branch(s):
                def branch(operand):
                    x_in, x_tail, st = operand
                    if s == 0:
                        inp = x_in
                    elif s == S - 1 and last_takes_input:
                        inp = (st, x_tail)
                    else:
                        inp = st
                    o = stage_fns[s](params_all[s], inp)
                    # uniform return type: (carry-typed, out-typed)
                    c = o if s < S - 1 else zeros_of(carry_aval)
                    y = o if s == S - 1 else zeros_of(out_aval)
                    return vary(c), vary(y)
                return branch

            return lax.switch(rank, [make_branch(s) for s in range(S)],
                              (x_t, x_last, state))

        return _rotating_schedule(axis, vary_axes, S, M, carry_aval,
                                  out_aval, xs_local, compute)

    in_specs = (jax.tree_util.tree_map(lambda _: P(), list(stage_params)),
                P())
    return jax.shard_map(per_rank, mesh=m, in_specs=in_specs,
                         out_specs=P())(list(stage_params), xs)
