"""Compiled GPipe engine: rotating microbatch schedule over the "pp" axis.

TPU-native equivalent of the reference's pipeline runtime
(reference: python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py:107 train_batch — the host loop issuing per-microbatch
forward/backward with send_v2/recv_v2 between stage processes;
framework/section_worker.cc:99 SectionWorker::TrainFiles).

Here the whole schedule is ONE compiled SPMD program ("pipelined scan",
the standard TPU formulation): every pp rank holds one stage's parameters
(stacked pytree sharded over "pp"), a lax.scan ticks M + S - 1 times, each
tick computes one stage on every rank simultaneously and rotates
activations with ppermute — warm-up/drain bubbles fall out of the tick
index arithmetic, and reverse-mode AD through scan+ppermute yields the
pipelined backward automatically (no hand-written p2p grad schedule).
"""
from __future__ import annotations

from typing import Callable

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import mesh as _mesh


def stack_stage_params(param_trees):
    """Stack S structurally-identical per-stage param pytrees along a new
    leading axis (to be sharded over "pp")."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *param_trees)


def gpipe_apply(block_fn: Callable, stacked_params, mb_x, mesh=None,
                axis="pp"):
    """Apply S pipeline stages to M microbatches.

    block_fn(params, x) -> y must be shape-preserving (x and y same shape —
    the transformer-block case). ``stacked_params``: pytree with leading dim
    S on every leaf. ``mb_x``: [M, ...] microbatched input (replicated).
    Returns [M, ...] outputs. Differentiable end-to-end.
    """
    m = mesh or _mesh.ensure_mesh()
    S = int(m.shape[axis])
    M = int(mb_x.shape[0])
    T = M + S - 1

    def per_rank(params_shard, xs):
        # params_shard leaves: [1, ...] (this rank's stage)
        params_local = jax.tree_util.tree_map(lambda a: a[0], params_shard)
        rank = lax.axis_index(axis)

        # mark the carries device-varying for shard_map's vma type system
        state0 = lax.pcast(jnp.zeros_like(xs[0]), (axis,), to="varying")
        outbuf0 = lax.pcast(jnp.zeros_like(xs), (axis,), to="varying")

        def tick(carry, t):
            state, outbuf = carry
            x_t = xs[jnp.clip(t, 0, M - 1)]
            inp = jnp.where(rank == 0, x_t, state)
            y = block_fn(params_local, inp)
            # last rank collects microbatch t-(S-1) once the pipe is full
            oi = jnp.clip(t - (S - 1), 0, M - 1)
            write = jnp.logical_and(rank == S - 1, t >= S - 1)
            cur = lax.dynamic_index_in_dim(outbuf, oi, 0, keepdims=False)
            outbuf = lax.dynamic_update_index_in_dim(
                outbuf, jnp.where(write, y, cur), oi, 0)
            # rotate activations one stage forward
            nxt = lax.ppermute(y, axis, perm=[(i, i + 1) for i in range(S - 1)])
            return (nxt, outbuf), None

        (_, outbuf), _ = lax.scan(tick, (state0, outbuf0), jnp.arange(T))
        # replicate the collected outputs from the last rank
        contrib = jnp.where(rank == S - 1, outbuf, jnp.zeros_like(outbuf))
        return lax.psum(contrib, axis)

    spec_axes_only = P(axis)
    in_specs = (jax.tree_util.tree_map(lambda _: spec_axes_only,
                                       stacked_params), P())
    return jax.shard_map(per_rank, mesh=m, in_specs=in_specs,
                         out_specs=P())(stacked_params, mb_x)


def split_microbatches(x, num_micro):
    """[B, ...] -> [M, B/M, ...] (reference: pipeline micro_batch_size)."""
    b = x.shape[0]
    if b % num_micro != 0:
        raise ValueError(f"batch {b} not divisible by {num_micro} microbatches")
    return x.reshape((num_micro, b // num_micro) + x.shape[1:])
