"""paddle.distributed.cloud_utils (reference: distributed/cloud_utils.py
— PaddleCloud environment discovery). Thin env readers over the same
PADDLE_* contract the launcher writes."""
import os


def get_cluster_and_pod(args=None):
    raise RuntimeError(
        "cloud_utils.get_cluster_and_pod targets PaddleCloud's scheduler "
        "env; this build launches with distributed.launch / spawn over "
        "the PADDLE_* contract (distributed/launch.py)")


def get_trainers_num():
    return int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
