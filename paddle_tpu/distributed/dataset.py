"""Industrial bulk-ingestion datasets: InMemoryDataset / QueueDataset.

Reference: python/paddle/distributed/fleet/dataset/dataset.py:253
(InMemoryDataset — load_into_memory :680, local_shuffle :785,
global_shuffle :817) over the C++ runtime in
paddle/fluid/framework/data_set.h:43 + data_feed.h:120 (MultiSlotDataFeed:
trainer threads pull parsed instances from file-sharded channels; global
shuffle rehashes instances across trainers over brpc).

TPU-native shape of the same capability:

- ingestion is host-side numpy (the accelerator never touches raw text);
  files are read by a thread pool (``thread_num``), each line parsed by a
  pluggable ``parse_fn`` (default: whitespace-separated floats, the
  degenerate MultiSlot form).
- ``global_shuffle`` redistributes instances across *processes* by a
  seeded hash of the instance id (the reference hashes by line id through
  its ShuffleChannel) using the jax.distributed transport already
  bootstrapped by the launcher — no brpc.
- training consumes ``batch_iterator()`` — plain [B, ...] numpy batches
  that feed ``Model.train_batch`` / DataLoader-style loops; the
  train_from_dataset Executor entanglement of the reference collapses
  into "iterate and call the step", per SURVEY's executor mapping.
"""
from __future__ import annotations

import concurrent.futures as _fut
import hashlib
import os
from typing import Callable, List, Optional, Sequence

import numpy as np
import jax

__all__ = ["DatasetBase", "InMemoryDataset", "QueueDataset"]


def _default_parse_fn(line: str):
    parts = line.split()
    if not parts:
        return None
    return np.asarray([float(p) for p in parts], np.float32)


class DatasetBase:
    """reference: dataset.py:24 DatasetBase (init/_set_* surface)."""

    def __init__(self):
        self._filelist: List[str] = []
        self._batch_size = 1
        self._thread_num = 1
        self._use_var: List[str] = []
        self._parse_fn: Callable = _default_parse_fn
        self._drop_last = False

    def init(self, batch_size=1, thread_num=1, use_var=None, parse_fn=None,
             drop_last=False, **kwargs):
        self._batch_size = int(batch_size)
        self._thread_num = int(thread_num)
        self._use_var = list(use_var or [])
        if parse_fn is not None:
            self._parse_fn = parse_fn
        self._drop_last = bool(drop_last)
        return self

    def set_filelist(self, filelist: Sequence[str]):
        missing = [f for f in filelist if not os.path.exists(f)]
        if missing:
            raise FileNotFoundError(f"set_filelist: {missing}")
        self._filelist = list(filelist)

    def set_batch_size(self, b):
        self._batch_size = int(b)

    def set_thread(self, n):
        self._thread_num = int(n)

    def set_parse_fn(self, fn):
        self._parse_fn = fn

    # -- helpers --------------------------------------------------------------
    def _my_files(self):
        """File-level sharding across processes (the reference assigns
        whole files to trainers the same way)."""
        rank, world = jax.process_index(), jax.process_count()
        return self._filelist[rank::world] if world > 1 else self._filelist

    def _read_file(self, path):
        out = []
        with open(path, "r") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                s = self._parse_fn(line)
                if s is not None:
                    out.append(s)
        return out

    def _batches_from(self, samples):
        B = self._batch_size
        n = len(samples)
        end = (n // B) * B if self._drop_last else n
        for i in range(0, end, B):
            chunk = samples[i:i + B]
            if not chunk:
                return
            if isinstance(chunk[0], (tuple, list)):
                yield tuple(np.stack([c[j] for c in chunk])
                            for j in range(len(chunk[0])))
            else:
                yield np.stack(chunk)


class InMemoryDataset(DatasetBase):
    """reference: dataset.py:253 — bulk load, shuffle, iterate."""

    def __init__(self):
        super().__init__()
        self._samples: list = []
        self._loaded = False

    # -- ingestion ------------------------------------------------------------
    def load_into_memory(self):
        """reference :680 — parallel file-sharded ingestion."""
        files = self._my_files()
        self._samples = []
        if not files:
            self._loaded = True
            return
        with _fut.ThreadPoolExecutor(max_workers=max(self._thread_num, 1)) \
                as pool:
            for chunk in pool.map(self._read_file, files):
                self._samples.extend(chunk)
        self._loaded = True

    preload_into_memory = load_into_memory

    def wait_preload_done(self):
        return None

    def release_memory(self):
        """reference :884."""
        self._samples = []
        self._loaded = False

    def get_memory_data_size(self, fleet=None):
        """reference :906 — global instance count before shuffle."""
        return self._global_size(len(self._samples))

    def get_shuffle_data_size(self, fleet=None):
        """reference :940 — this process's post-shuffle count (summed
        globally like the reference when fleet is passed)."""
        return self._global_size(len(self._samples))

    def _global_size(self, local_n):
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            import jax.numpy as jnp
            total = multihost_utils.process_allgather(
                jnp.asarray([local_n]))
            return int(np.asarray(total).sum())
        return local_n

    # -- shuffles -------------------------------------------------------------
    def local_shuffle(self, seed: Optional[int] = None):
        """reference :785 — in-process permutation."""
        rng = np.random.RandomState(seed)
        rng.shuffle(self._samples)

    def global_shuffle(self, fleet=None, thread_num=12,
                       seed: Optional[int] = None):
        """reference :817 — redistribute instances ACROSS processes.

        Every instance is routed to hash(instance_bytes, seed) % world —
        the reference's ShuffleChannel semantics (brpc send to the owning
        trainer) over the jax.distributed transport: each process gathers
        every shard destined for it via one all-gather of the per-
        destination buckets, then shuffles locally. Single-process this
        degenerates to local_shuffle (like the reference without a fleet).
        """
        world = jax.process_count()
        if world <= 1:
            self.local_shuffle(seed)
            return
        from .collective import all_gather_object
        buckets: List[list] = [[] for _ in range(world)]
        salt = str(seed if seed is not None else 0).encode()
        for s in self._samples:
            h = hashlib.md5(salt + np.asarray(s).tobytes()).digest()
            buckets[int.from_bytes(h[:4], "little") % world].append(s)
        # exchange: gather everyone's buckets, keep the ones addressed here
        gathered: list = []
        all_gather_object(gathered, buckets)
        rank = jax.process_index()
        self._samples = [s for proc_buckets in gathered
                         for s in proc_buckets[rank]]
        self.local_shuffle(seed)

    # -- consumption ----------------------------------------------------------
    def batch_iterator(self):
        if not self._loaded:
            raise RuntimeError("call load_into_memory() first")
        return self._batches_from(self._samples)

    def __iter__(self):
        return self.batch_iterator()

    def __len__(self):
        B = self._batch_size
        n = len(self._samples)
        return n // B if self._drop_last else -(-n // B)


class QueueDataset(DatasetBase):
    """reference: dataset.py QueueDataset — streaming (one pass, no
    memory residency, no global shuffle; the reference raises on
    shuffle too)."""

    def local_shuffle(self):
        raise RuntimeError(
            "QueueDataset streams from files; use InMemoryDataset for "
            "shuffling (reference raises the same)")

    global_shuffle = local_shuffle

    def batch_iterator(self):
        def gen():
            pending: list = []
            for path in self._my_files():
                pending.extend(self._read_file(path))
                B = self._batch_size
                while len(pending) >= B:
                    chunk, pending = pending[:B], pending[B:]
                    yield from self._batches_from(chunk)
            if pending and not self._drop_last:
                yield from self._batches_from(pending)
        return gen()

    def __iter__(self):
        return self.batch_iterator()
