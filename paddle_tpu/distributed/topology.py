"""Hybrid-parallel process topology over mesh axes.

TPU-native equivalent of the reference's N-D cartesian topology
(reference: python/paddle/distributed/fleet/base/topology.py:35
CommunicateTopology, :111 HybridCommunicateGroup). The reference builds one
NCCL ring per axis-slice; here each parallel dimension IS a mesh axis of the
global jax.sharding.Mesh, and a "comm group" is a Group keyed by that axis —
collectives over it automatically reduce within the slice defined by the
other axes (no per-slice ring enumeration needed).
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence

import numpy as np

from . import mesh as _mesh
from .collective import Group, new_group


class CommunicateTopology:
    """reference: fleet/base/topology.py:35."""

    def __init__(self, hybrid_group_names: Sequence[str] = ("data", "pipe", "model"),
                 dims: Sequence[int] = (1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(int(d) for d in dims)
        self.coordinate = list(itertools.product(*[range(d) for d in self._dims]))
        self._rank2coord = {r: c for r, c in enumerate(self.coordinate)}
        self._coord2rank = {c: r for r, c in enumerate(self.coordinate)}

    def get_hybrid_group_names(self) -> List[str]:
        return self._parallel_names

    def get_dim(self, axis_name: str) -> int:
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self) -> int:
        return int(np.prod(self._dims))

    def get_rank(self, **kwargs) -> int:
        coord = tuple(kwargs[n] for n in self._parallel_names)
        return self._coord2rank[coord]

    def get_coord(self, rank: int):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        """All ranks whose coordinate on ``axis_name`` equals ``index``."""
        axis = self._parallel_names.index(axis_name)
        return sorted(r for r, c in self._rank2coord.items() if c[axis] == index)

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        """Rank lists of every group that communicates along ``axis_name``
        (reference: topology.py get_comm_list)."""
        axis = self._parallel_names.index(axis_name)
        others = [self._parallel_names[i] for i in range(len(self._dims))
                  if i != axis]
        comm = []
        for combo in itertools.product(
                *[range(self.get_dim(o)) for o in others]):
            ranks = []
            for k in range(self.get_dim(axis_name)):
                kw = dict(zip(others, combo))
                kw[axis_name] = k
                ranks.append(self.get_rank(**kw))
            comm.append(ranks)
        return comm


# paddle axis name -> mesh axis name
_AXIS_MAP = {"data": "dp", "pipe": "pp", "sharding": "sharding",
             "sep": "sp", "model": "mp"}


class HybridCommunicateGroup:
    """reference: fleet/base/topology.py:111. Built from the hybrid dims; also
    installs the matching global Mesh so collectives and sharding agree."""

    def __init__(self, topology: Optional[CommunicateTopology] = None,
                 dp_degree: int = 1, mp_degree: int = 1, pp_degree: int = 1,
                 sharding_degree: int = 1, sep_degree: int = 1,
                 rank: Optional[int] = None, devices=None):
        if topology is not None:
            dims = {n: topology.get_dim(n)
                    for n in topology.get_hybrid_group_names()}
            dp_degree = dims.get("data", 1)
            pp_degree = dims.get("pipe", 1)
            mp_degree = dims.get("model", 1)
            sharding_degree = dims.get("sharding", 1)
            sep_degree = dims.get("sep", 1)
        self._dp_degree = dp_degree
        self._mp_degree = mp_degree
        self._pp_degree = pp_degree
        self._sharding_degree = sharding_degree
        self._sep_degree = sep_degree

        names, dims = [], []
        for n, d in (("data", dp_degree), ("pipe", pp_degree),
                     ("sharding", sharding_degree), ("sep", sep_degree),
                     ("model", mp_degree)):
            names.append(n)
            dims.append(d)
        self._topo = CommunicateTopology(names, dims)

        from .env import get_rank
        self.global_rank = rank if rank is not None else get_rank()
        self.nranks = self._topo.world_size()

        # install the global mesh (only axes with degree > 1, in hybrid order)
        axes = {}
        for n, d in zip(names, dims):
            if d > 1:
                axes[_AXIS_MAP[n]] = d
        import jax
        devs = devices if devices is not None else jax.devices()
        need = int(np.prod(list(axes.values()) or [1]))
        existing = _mesh.get_mesh()
        if need == len(devs):
            _mesh.set_mesh(_mesh.build_mesh(axes or None, devs))
        elif existing is not None and all(
                existing.shape.get(a) == d for a, d in axes.items()):
            pass  # a user-installed mesh (possibly on a device subset)
            # already provides the requested axes — keep it
        elif need > 1:
            # A silently-skipped mesh would turn every dp/mp/pp collective
            # into an identity no-op; fail loudly instead.
            raise ValueError(
                f"hybrid degrees {axes} need {need} devices but "
                f"{len(devs)} are visible; fix hybrid_configs, pass "
                f"devices= explicitly, or pre-install a matching mesh via "
                f"distributed.set_mesh")

        self._dp_group = new_group(axis="dp")
        self._mp_group = new_group(axis="mp")
        self._pp_group = new_group(axis="pp")
        self._sharding_group = new_group(axis="sharding")
        self._sep_group = new_group(axis="sp")
        # check group: dp×sharding (reference: topology.py _check_comm_group)
        self._check_group = new_group(axis=("dp", "sharding"))

    def get_parallel_mode(self):
        if self._pp_degree > 1:
            return "pipeline"
        if self._mp_degree > 1:
            return "model"
        if self._sharding_degree > 1:
            return "sharding"
        return "data"

    def _coord(self):
        return self._topo.get_coord(self.global_rank)

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    # data parallel
    def get_data_parallel_rank(self):
        return self._coord()[0]

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._dp_group

    def get_data_parallel_group_src_rank(self):
        return self._topo.get_axis_list("data", 0)[0]

    # model (tensor) parallel
    def get_model_parallel_rank(self):
        return self._coord()[4]

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._mp_group

    def get_model_parallel_group_src_rank(self):
        return 0

    # pipeline parallel
    def get_stage_id(self):
        return self._coord()[1]

    def get_pipe_parallel_rank(self):
        return self._coord()[1]

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._pp_group

    @property
    def is_first_stage(self):
        return self.get_stage_id() == 0

    @property
    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    # sharding
    def get_sharding_parallel_rank(self):
        return self._coord()[2]

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_sharding_parallel_group_src_rank(self):
        return 0

    def get_check_parallel_group(self):
        return self._check_group

    def get_rank_from_stage(self, stage_id, **kwargs):
        c = list(self._coord())
        c[1] = stage_id
        return self._topo.get_rank(data=c[0], pipe=c[1], sharding=c[2],
                                   sep=c[3], model=c[4])


_HCG = [None]


def set_hybrid_communicate_group(hcg):
    _HCG[0] = hcg


def get_hybrid_communicate_group():
    return _HCG[0]
