"""paddle.distributed.utils (reference: distributed/utils.py — launcher
helper functions; the real machinery lives in distributed/launch.py)."""


def get_host_name_ip():
    import socket
    host = socket.gethostname()
    try:
        ip = socket.gethostbyname(socket.getfqdn(host))
    except OSError:
        ip = "127.0.0.1"
    return host, ip


def get_logger(log_level=20, name="root"):
    import logging
    logger = logging.getLogger(name)
    logger.setLevel(log_level)
    return logger
