"""paddle_tpu.distributed: collectives, data parallel, topology, launch.

Public surface mirrors `paddle.distributed` (reference:
python/paddle/distributed/__init__.py): functional collectives, ParallelEnv /
init_parallel_env, DataParallel, new_group, spawn, launch; plus the TPU-native
mesh utilities that replace ring ids (see mesh.py docstring).
"""
# `from . import env` (not only `from .env import ...`): when paddle_tpu's
# pre-backend bootstrap loaded env.py standalone into sys.modules, this
# also binds it as a package attribute so `paddle_tpu.distributed.env`
# attribute access keeps working.
from . import env  # noqa: F401
from .env import (  # noqa: F401
    ParallelEnv, init_parallel_env, bootstrap_pre_backend, is_initialized,
    device_count,
)
# group-aware rank/world-size (fall back to env for the global group)
from .collective import get_rank, get_world_size  # noqa: F401
from .mesh import (  # noqa: F401
    build_mesh, set_mesh, get_mesh, ensure_mesh, shard_tensor,
    replicate_tensor, constrain, sharding_for,
)
from .collective import (  # noqa: F401
    ReduceOp, Group, new_group, get_group, destroy_process_group,
    all_reduce, reduce, broadcast, all_gather, all_gather_object, scatter,
    reduce_scatter, alltoall, send, recv, p2p_exchange, barrier, wait,
    compressed_all_reduce, compressed_grad_sync,
    compressed_allreduce_wire_bytes, dense_allreduce_wire_bytes,
)
from .parallel import (  # noqa: F401
    DataParallel, sync_params_buffers, shard_batch, build_global_batch,
)
from .elastic import (  # noqa: F401
    PreemptionGuard, PREEMPTION_EXIT_CODE, HOST_LOST_EXIT_CODE,
    under_elastic_supervisor, RestartBudget,
)
from . import elastic_runtime  # noqa: F401
from .elastic_runtime import (  # noqa: F401
    StepWatchdog, HeartbeatPlane, CohortSupervisor,
)
from .topology import (  # noqa: F401
    CommunicateTopology, HybridCommunicateGroup,
    set_hybrid_communicate_group, get_hybrid_communicate_group,
)
from . import fleet  # noqa: F401
from . import sharding  # noqa: F401
from .dataset import (DatasetBase, InMemoryDataset,  # noqa: F401
                      QueueDataset)
from .sharding import group_sharded_parallel  # noqa: F401


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """reference: distributed/spawn.py:333 — multiprocessing launch of
    ``func(*args)`` per process with the PADDLE_* env contract set."""
    from .spawn_impl import spawn as _spawn
    return _spawn(func, args=args, nprocs=nprocs, join=join, daemon=daemon,
                  **options)

from .fleet.mp_layers import split  # noqa: E402,F401

# -- reference distributed/__init__.py export tail ---------------------------
from .fleet import BoxPSDataset  # noqa: E402,F401


class ProbabilityEntry:
    """reference: entry_attr.py — sparse-feature admission by probability
    (a PS accessor config string). Config-object parity only: the brpc
    PS accessor that consumed it is ADR'd out (docs/adr/0001), so
    nothing reads attr() here."""

    def __init__(self, probability):
        if not 0.0 <= probability <= 1.0:
            raise ValueError("probability must be in [0, 1]")
        self.probability = float(probability)

    def attr(self):
        return f"probability_entry:{self.probability}"


class CountFilterEntry:
    """reference: entry_attr.py — sparse-feature admission by minimum
    occurrence count."""

    def __init__(self, count_filter):
        if count_filter < 0:
            raise ValueError("count_filter must be >= 0")
        self.count_filter = int(count_filter)

    def attr(self):
        return f"count_filter_entry:{self.count_filter}"


from . import utils  # noqa: E402,F401
from . import cloud_utils  # noqa: E402,F401
