"""dist.spawn: in-Python multi-process launch.

TPU-native equivalent of reference spawn
(reference: python/paddle/distributed/spawn.py:333 spawn — multiprocessing
with the PADDLE_* env handshake per child; :230 _func_wrapper).
"""
from __future__ import annotations

import multiprocessing
import os
import sys
import traceback
from typing import Optional


def _worker(func, args, rank, nprocs, endpoints, error_queue, env_updates):
    try:
        os.environ.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
            "PADDLE_TRAINERS_NUM": str(nprocs),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
        })
        if env_updates:
            os.environ.update(env_updates)
        func(*args)
    except KeyboardInterrupt:
        pass
    except Exception:
        error_queue.put(traceback.format_exc())
        sys.exit(1)


class MultiprocessContext:
    """reference: spawn.py MultiprocessContext (join + error surfacing)."""

    def __init__(self, processes, error_queues):
        self.processes = processes
        self.error_queues = error_queues

    def join(self, timeout=None):
        for p in self.processes:
            p.join(timeout)
        for rank, (p, q) in enumerate(zip(self.processes,
                                          self.error_queues)):
            if p.exitcode not in (0, None):
                msg = q.get() if not q.empty() else f"exitcode {p.exitcode}"
                for other in self.processes:
                    if other.is_alive():
                        other.terminate()
                raise RuntimeError(
                    f"spawned rank {rank} failed:\n{msg}")
        return all(p.exitcode == 0 for p in self.processes)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """reference: distributed/spawn.py:333."""
    if nprocs == -1:
        nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        if nprocs <= 1:
            nprocs = 1
    start_port = int(options.get("start_port",
                                 os.environ.get("FLAGS_START_PORT", "6170")))
    ips = options.get("ips", "127.0.0.1")
    endpoints = [f"{ips}:{start_port + i}" for i in range(nprocs)]
    env_updates = options.get("env", None)

    ctx = multiprocessing.get_context("spawn")
    processes, queues = [], []
    for rank in range(nprocs):
        q = ctx.SimpleQueue()
        p = ctx.Process(target=_worker,
                        args=(func, args, rank, nprocs, endpoints, q,
                              env_updates),
                        daemon=daemon)
        p.start()
        processes.append(p)
        queues.append(q)
    mp_ctx = MultiprocessContext(processes, queues)
    if join:
        mp_ctx.join()
    return mp_ctx
