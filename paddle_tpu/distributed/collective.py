"""Collective functional API over mesh axes.

TPU-native equivalent of the reference's collective surface
(reference: python/paddle/distributed/collective.py — all_reduce :410,
broadcast :343, all_gather :585, reduce :491, scatter :663, alltoall :1315,
send/recv :1386/:1436, new_group :205, barrier :165; backed by the C++ comm
ops in operators/collective/ and NCCLCommContext ring registry).

Design (SURVEY §5.8 TPU mapping): a communicator ring becomes a *mesh axis*.
Three execution contexts:

1. **Inside a mapped trace** (shard_map/pjit body — the perf path): lowers to
   ``lax.psum``/``all_gather``/``psum_scatter``/``all_to_all``/``ppermute``
   on the group's axis; XLA schedules them on ICI. Calls go through the op
   funnel, so they are tape-recorded and differentiable (psum's transpose
   is the same allreduce the reference's grad ops insert).
2. **Eager, single process**: the group spans only this process ⇒ identity
   (matches a world_size-1 reference run). Intra-host multi-device work is
   expressed by sharding, not by eager collectives.
3. **Eager, multi-process** (one process per host via launcher +
   jax.distributed): implemented with a host-local all-gather
   (``multihost_utils.process_allgather``) + local reduction — the paddle
   process-level semantics, with ICI/DCN transport picked by XLA.

Groups: a group that IS a mesh axis (dp/mp/pp from the hybrid topology) needs
no rank masks — ``psum(x, axis)`` already reduces within each slice of the
other axes. ``new_group(ranks)`` over arbitrary ranks uses masked full-axis
collectives (members contribute, non-members pass through), because this JAX
version does not support ``axis_index_groups`` under shard_map.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor
from ..ops.dispatch import apply
from . import mesh as _mesh


class ReduceOp:
    """reference: distributed/collective.py:38 ReduceOp."""
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class Group:
    """A communication group = a mesh-axis subset or an explicit rank list
    (reference: collective.py:76 Group; ring_id ≈ axis name here)."""

    _next_id = [1]

    def __init__(self, ranks: Optional[Sequence[int]] = None,
                 axis: Union[str, Tuple[str, ...], None] = None,
                 gid: Optional[int] = None, name: Optional[str] = None):
        self.ranks = list(ranks) if ranks is not None else None
        self.axis = axis
        if gid is None:
            gid = Group._next_id[0]
            Group._next_id[0] += 1
        self.id = gid
        self.name = name or f"group_{gid}"

    @property
    def nranks(self):
        if self.ranks is not None:
            return len(self.ranks)
        axes = _resolve_axes(self)
        if axes:
            return _mesh.mesh_axis_size(axes)
        return jax.process_count()

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        if self.ranks is None:
            return rank
        return self.ranks.index(rank) if rank in self.ranks else -1

    def is_member(self):
        return True

    def __repr__(self):
        return f"Group(id={self.id}, axis={self.axis}, ranks={self.ranks})"


_GLOBAL_GROUP = Group(gid=0, name="global")
_GROUP_MAP = {0: _GLOBAL_GROUP}


def _get_group(group) -> Group:
    if group is None:
        return _GLOBAL_GROUP
    if isinstance(group, Group):
        return group
    return _GROUP_MAP[int(group)]


def new_group(ranks: Optional[Sequence[int]] = None, backend=None,
              timeout=None, axis=None) -> Group:
    """reference: collective.py:205 new_group. ``axis=`` creates a mesh-axis
    group (the hybrid-topology fast path); ``ranks=`` an arbitrary subset."""
    g = Group(ranks=ranks, axis=axis)
    _GROUP_MAP[g.id] = g
    return g


def get_group(gid: int) -> Group:
    return _GROUP_MAP[gid]


def is_initialized() -> bool:
    from .env import is_initialized as _i
    return _i()


def destroy_process_group(group=None):
    if group is not None:
        _GROUP_MAP.pop(_get_group(group).id, None)


# -- mapped-context detection -------------------------------------------------

def _axis_bound(name: str) -> bool:
    """Is the mesh axis bound in the current (shard_map) trace?"""
    try:
        lax.axis_index(name)
        return True
    except NameError:
        return False  # jax's signal for an unbound axis name
    except Exception:
        return False  # anything else equally means "not usable here"


def _axes_in_scope() -> Tuple[str, ...]:
    """Mesh axes bound in the current (shard_map) trace."""
    m = _mesh.get_mesh()
    if m is None:
        return ()
    return tuple(name for name in m.axis_names if _axis_bound(name))


def _resolve_axes(group: Group) -> Tuple[str, ...]:
    scope = _axes_in_scope()
    if group.axis is not None:
        want = (group.axis,) if isinstance(group.axis, str) else tuple(group.axis)
        return tuple(a for a in want if a in scope)
    return scope


def _linear_index(axes: Tuple[str, ...]):
    """Flat rank index over the given axes (row-major in axis order)."""
    m = _mesh.get_mesh()
    idx = jnp.zeros((), jnp.int32)
    for a in axes:
        idx = idx * m.shape[a] + lax.axis_index(a)
    return idx


def _member_mask(group: Group, axes: Tuple[str, ...]):
    if group.ranks is None:
        return None
    idx = _linear_index(axes)
    return jnp.isin(idx, jnp.asarray(np.array(group.ranks, np.int32)))  # noqa: PTA002 -- group.ranks is a host-side python list (trace-time constant), no device value involved


# -- raw implementations (jax arrays; usable inside shard_map directly) -------

_REDUCERS = {
    ReduceOp.SUM: (lax.psum, jnp.zeros_like),
    ReduceOp.AVG: (lax.pmean, jnp.zeros_like),
    ReduceOp.MAX: (lax.pmax, lambda x: jnp.full_like(x, -jnp.inf)
                   if jnp.issubdtype(x.dtype, jnp.floating)
                   else jnp.full_like(x, jnp.iinfo(x.dtype).min)),
    ReduceOp.MIN: (lax.pmin, lambda x: jnp.full_like(x, jnp.inf)
                   if jnp.issubdtype(x.dtype, jnp.floating)
                   else jnp.full_like(x, jnp.iinfo(x.dtype).max)),
}


def _raw_allreduce(x, op, group: Group, axes: Tuple[str, ...]):
    mask = _member_mask(group, axes)
    if op == ReduceOp.PROD:
        # no pprod primitive: psum of logs would lose sign — use
        # exp(psum(log|x|)) * sign product via psum of sign bits
        contrib = x if mask is None else jnp.where(mask, x, jnp.ones_like(x))
        neg = (contrib < 0).astype(jnp.int32)
        total_neg = lax.psum(neg, axes)
        mag = lax.psum(jnp.log(jnp.abs(contrib) + 1e-30), axes)
        out = jnp.exp(mag) * jnp.where(total_neg % 2 == 1, -1.0, 1.0).astype(x.dtype)
        return out if mask is None else jnp.where(mask, out, x)
    fn, neutral = _REDUCERS[op]
    if mask is None:
        return fn(x, axes)
    contrib = jnp.where(mask, x, neutral(x))
    if op == ReduceOp.AVG:
        total = lax.psum(contrib, axes)
        out = total / float(len(group.ranks))
    else:
        out = fn(contrib, axes)
    return jnp.where(mask, out, x)


def _raw_broadcast(x, src_in_group, group: Group, axes: Tuple[str, ...]):
    idx = _linear_index(axes)
    if group.ranks is not None:
        src_global = group.ranks[src_in_group]
        mask = _member_mask(group, axes)
    else:
        src_global = src_in_group
        mask = None
    contrib = jnp.where(idx == src_global, x, jnp.zeros_like(x))
    out = lax.psum(contrib, axes)
    if mask is not None:
        return jnp.where(mask, out, x)
    return out


def _raw_allgather(x, group: Group, axes: Tuple[str, ...]):
    if len(axes) == 1:
        full = lax.all_gather(x, axes[0])         # [axis_size, ...]
    else:
        full = x
        for a in reversed(axes):
            full = lax.all_gather(full, a)
        full = full.reshape((-1,) + x.shape)
    if group.ranks is not None:
        full = full[jnp.asarray(np.array(group.ranks, np.int32))]  # noqa: PTA002 -- group.ranks is a host-side python list (trace-time constant), no device value involved
    return full


def _raw_reduce_scatter(x, op, group: Group, axes: Tuple[str, ...]):
    if group.ranks is not None:
        raise NotImplementedError(
            "reduce_scatter over an arbitrary rank group; use a mesh-axis "
            "group")
    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise NotImplementedError("reduce_scatter supports SUM/AVG")
    out = lax.psum_scatter(x, axes, tiled=True)
    if op == ReduceOp.AVG:
        out = out / _mesh.mesh_axis_size(axes)
    return out


def _raw_alltoall(x, group: Group, axes: Tuple[str, ...]):
    if group.ranks is not None:
        raise NotImplementedError(
            "alltoall over an arbitrary rank group; use a mesh-axis group")
    if len(axes) != 1:
        raise NotImplementedError("alltoall needs a single mesh axis")
    return lax.all_to_all(x, axes[0], split_axis=0, concat_axis=0, tiled=True)


def _raw_p2p(x, src, dst, axes: Tuple[str, ...]):
    """Move ``x`` from rank src to rank dst (others keep their value)."""
    if len(axes) != 1:
        raise NotImplementedError("send/recv needs a single mesh axis")
    moved = lax.ppermute(x, axes[0], perm=[(src, dst)])
    idx = lax.axis_index(axes[0])
    return jnp.where(idx == dst, moved, x)


# -- compressed (quantized) allreduce ----------------------------------------
# EQuARX-style (PAPERS.md): express the allreduce as reduce-scatter +
# all-gather and quantize both wire phases to int8 (block-scaled) or bf16,
# keeping quantize/exchange/dequantize one fused XLA program — no host
# transfers (the PTA009 entrypoint below audits exactly that). int8 with
# the default 256-element blocks cuts bytes-on-wire ~3.9x vs f32; the
# two quantization passes bound the elementwise error by
# (n+1) * absmax / 127 (each contribution loses <= its block absmax/254
# per pass), which is noise against SGD gradient variance — the
# convergence test in tests/test_compressed_allreduce.py holds the
# training loss to the dense path's budget.

DEFAULT_COMPRESS_BLOCK = 256
_WIRE_DTYPES = ("int8", "bf16")


def _compress_block_for(nelems: int, wire_dtype: str) -> int:
    """Block size for the quantize stage: tuner winner if one is known
    (tools/autotune.py --compress), else the 256 default."""
    try:
        from ..tuner import get_compress_block
    except ImportError:      # tuner unavailable mid-bootstrap
        return DEFAULT_COMPRESS_BLOCK
    blk = get_compress_block(nelems, wire_dtype)
    return int(blk) if blk else DEFAULT_COMPRESS_BLOCK


def _block_quantize_int8(blocks):
    """``[..., block]`` f32 -> (int8 codes, f32 per-block absmax scales)."""
    absmax = jnp.max(jnp.abs(blocks), axis=-1)
    scale = jnp.where(absmax > 0, absmax, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(blocks / scale[..., None] * 127.0),
                 -127.0, 127.0).astype(jnp.int8)
    return q, scale


def _block_dequantize_int8(q, scale):
    return q.astype(jnp.float32) * (scale[..., None] / 127.0)


def _raw_compressed_allreduce(x, axes: Tuple[str, ...], wire_dtype="int8",
                              block: Optional[int] = None, mean=False,
                              mesh=None):
    """The in-trace compressed allreduce (shard_map body).

    Phase 1 (reduce-scatter): block-quantize the local value, all_to_all
    the codes+scales so rank j holds every rank's j-th shard, dequantize
    and sum locally. Phase 2 (all-gather): re-quantize the reduced shard,
    all_gather, dequantize. Every rank dequantizes identical codes, so the
    replicas stay bitwise identical — the same guarantee the dense psum
    gives, which is what keeps replicated parameters in lockstep.
    """
    if wire_dtype not in _WIRE_DTYPES:
        raise ValueError(
            f"compressed allreduce wire dtype must be one of "
            f"{_WIRE_DTYPES}, got {wire_dtype!r}")
    if len(axes) != 1:
        raise NotImplementedError(
            "compressed allreduce needs a single mesh-axis group (dp)")
    axis = axes[0]
    # size from the explicit mesh when given: inside a hand-built
    # shard_map there may be no ambient global mesh, and the n==1
    # fallback would silently turn the sync into an identity
    n = _mesh.mesh_axis_size(axes, mesh)
    orig_dtype = x.dtype
    if n == 1:
        return x
    blk = int(block or _compress_block_for(x.size, wire_dtype))  # noqa: PTA001 -- x.size and the tuner block are trace-time python ints, not traced values
    flat = x.astype(jnp.float32).reshape(-1)
    per = -(-flat.size // (n * blk)) * blk      # shard length, blk-multiple
    flat = jnp.pad(flat, (0, n * per - flat.size))
    shards = flat.reshape(n, per)
    if wire_dtype == "bf16":
        got = lax.all_to_all(shards.astype(jnp.bfloat16), axis, 0, 0)
        local = jnp.sum(got.astype(jnp.float32), axis=0)         # [per]
        full = lax.all_gather(local.astype(jnp.bfloat16), axis)  # [n, per]
        out = full.astype(jnp.float32).reshape(-1)
    else:
        q, s = _block_quantize_int8(shards.reshape(n, per // blk, blk))
        gq = lax.all_to_all(q, axis, 0, 0)      # [n, per//blk, blk]
        gs = lax.all_to_all(s, axis, 0, 0)      # [n, per//blk]
        local = jnp.sum(_block_dequantize_int8(gq, gs), axis=0)
        q2, s2 = _block_quantize_int8(local)    # reduced shard, requantized
        fq = lax.all_gather(q2, axis)           # [n, per//blk, blk]
        fs = lax.all_gather(s2, axis)           # [n, per//blk]
        out = _block_dequantize_int8(fq, fs).reshape(-1)
    out = out[: x.size].reshape(x.shape)
    if mean:
        out = out / n
    return out.astype(orig_dtype)


def compressed_allreduce_wire_bytes(nelems: int, world: int,
                                    wire_dtype="int8",
                                    block: Optional[int] = None) -> int:
    """Analytic per-device bytes-on-wire of the two-phase compressed
    exchange: (world-1) quantized shards sent in each phase. The scale
    sidecar (4 bytes per block) is charged to the int8 wire."""
    if world <= 1:
        return 0
    blk = int(block or DEFAULT_COMPRESS_BLOCK)
    per = -(-int(nelems) // (world * blk)) * blk
    if wire_dtype == "bf16":
        payload = per * 2
    elif wire_dtype == "int8":
        payload = per + (per // blk) * 4
    else:
        raise ValueError(f"unknown wire dtype {wire_dtype!r}")
    return 2 * (world - 1) * payload


def dense_allreduce_wire_bytes(nelems: int, world: int,
                               itemsize: int = 4) -> int:
    """Per-device bytes of the dense ring/two-phase allreduce — the
    baseline the >=3x acceptance bar is measured against."""
    if world <= 1:
        return 0
    per = -(-int(nelems) // world)
    return 2 * (world - 1) * per * itemsize


def compressed_grad_sync(grads, axis: str = "dp", wire_dtype: str = "int8",
                         block: Optional[int] = None, mean: bool = True,
                         mesh=None):
    """Compressed gradient mean over a mesh axis, for hand-written
    shard_map train steps (the DataParallel SPMD path inserts the dense
    psum implicitly via sharding; an explicit step opts into compression
    by calling this on its gradient pytree instead of ``lax.pmean``).
    Pass ``mesh`` when the enclosing shard_map's mesh is not the ambient
    global one (``set_mesh``) — axis sizing falls back to the global
    mesh otherwise."""
    return jax.tree_util.tree_map(
        lambda g: _raw_compressed_allreduce(g, (axis,), wire_dtype,
                                            block, mean, mesh=mesh), grads)


# -- public functional API ----------------------------------------------------

def _run(name, tensor, raw_fn, inplace=True):
    """Dispatch a collective through the op funnel (differentiable, visible
    to AMP/nan-check), honoring paddle's mutate-in-place convention."""
    if isinstance(tensor, Tensor):
        out = apply(name, raw_fn, tensor)
        if inplace:
            tensor._swap_payload(out)
            return tensor
        return out
    return raw_fn(tensor)


def _eager_multiprocess_reduce(arr, op):
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(arr)  # [nproc, ...]
    if op == ReduceOp.SUM:
        return gathered.sum(axis=0)
    if op == ReduceOp.AVG:
        return gathered.mean(axis=0)
    if op == ReduceOp.MAX:
        return gathered.max(axis=0)
    if op == ReduceOp.MIN:
        return gathered.min(axis=0)
    if op == ReduceOp.PROD:
        return gathered.prod(axis=0)
    raise ValueError(f"bad ReduceOp {op}")


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True,
               use_calc_stream=None):
    """reference: distributed/collective.py:410 (c_allreduce_* kernels,
    c_allreduce_op.h:253)."""
    g = _get_group(group)
    axes = _resolve_axes(g)
    if axes:
        return _run("c_allreduce", tensor,
                    lambda x: _raw_allreduce(x, op, g, axes))
    if jax.process_count() > 1:
        # host-level path (see broadcast): keep multihost_utils outside
        # the op funnel's jit
        raw = tensor._data if isinstance(tensor, Tensor) else tensor
        out = _eager_multiprocess_reduce(raw, op)
        if isinstance(tensor, Tensor):
            # see broadcast: untaped host-level mutation -> version bump
            tensor._swap_payload(Tensor(jnp.asarray(out)))
            tensor._inplace_version += 1
            return tensor
        return out
    return tensor  # world of one


def _eager_compressed_reduce(arr, op, wire_dtype, block):
    """Host-level compressed reduce (one process per host): quantize the
    local value, process_allgather the int8 codes + scales (the actual
    DCN payload), dequantize and sum. Every process dequantizes identical
    gathered rows, so replicas stay bitwise identical."""
    from jax.experimental import multihost_utils
    x = jnp.asarray(arr)
    blk = int(block or _compress_block_for(x.size, wire_dtype))
    flat = x.astype(jnp.float32).reshape(-1)
    pad = -(-flat.size // blk) * blk - flat.size
    blocks = jnp.pad(flat, (0, pad)).reshape(-1, blk)
    if wire_dtype == "bf16":
        rows = multihost_utils.process_allgather(
            blocks.astype(jnp.bfloat16))
        total = jnp.asarray(rows).astype(jnp.float32).sum(axis=0)
    else:
        q, s = _block_quantize_int8(blocks)
        gq = multihost_utils.process_allgather(q)
        gs = multihost_utils.process_allgather(s)
        total = _block_dequantize_int8(jnp.asarray(gq),
                                       jnp.asarray(gs)).sum(axis=0)
    out = total.reshape(-1)[: flat.size].reshape(x.shape)
    if op == ReduceOp.AVG:
        out = out / jax.process_count()
    return out.astype(x.dtype)


def compressed_all_reduce(tensor, op=ReduceOp.SUM, group=None,
                          wire_dtype: str = "int8",
                          block: Optional[int] = None):
    """Quantized allreduce (EQuARX, PAPERS.md): same contract as
    :func:`all_reduce` but the wire payload is block-scaled int8 (or
    bf16) instead of the input dtype. SUM/AVG only — quantization
    commutes with addition up to the bounded rounding error, not with
    max/min/prod. Enabled fleet-wide via
    ``DistributedStrategy.compressed_allreduce`` (docs/quantization.md).
    """
    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise NotImplementedError(
            "compressed_all_reduce supports SUM/AVG only")
    if wire_dtype not in _WIRE_DTYPES:
        raise ValueError(
            f"compressed allreduce wire dtype must be one of "
            f"{_WIRE_DTYPES}, got {wire_dtype!r}")
    g = _get_group(group)
    if g.ranks is not None:
        raise NotImplementedError(
            "compressed_all_reduce over an arbitrary rank group; use a "
            "mesh-axis group (dp)")
    axes = _resolve_axes(g)
    if axes:
        return _run("c_compressed_allreduce", tensor,
                    lambda x: _raw_compressed_allreduce(
                        x, axes, wire_dtype, block,
                        mean=(op == ReduceOp.AVG)))
    if jax.process_count() > 1:
        # host-level path (see all_reduce): multihost_utils stays outside
        # the op funnel's jit
        raw = tensor._data if isinstance(tensor, Tensor) else tensor
        out = _eager_compressed_reduce(raw, op, wire_dtype, block)
        if isinstance(tensor, Tensor):
            tensor._swap_payload(Tensor(jnp.asarray(out)))
            tensor._inplace_version += 1
            return tensor
        return out
    return tensor  # world of one


def reduce(tensor, dst, op=ReduceOp.SUM, group=None, sync_op=True):
    """reference: collective.py:491. SPMD form: every rank computes the
    reduction, only dst keeps it (others keep their input)."""
    g = _get_group(group)
    axes = _resolve_axes(g)
    if not axes:
        return all_reduce(tensor, op, group, sync_op)

    def impl(x):
        red = _raw_allreduce(x, op, g, axes)
        idx = _linear_index(axes)
        dst_global = g.ranks[dst] if g.ranks is not None else dst
        return jnp.where(idx == dst_global, red, x)
    return _run("c_reduce", tensor, impl)


def broadcast(tensor, src, group=None, sync_op=True):
    """reference: collective.py:343 (c_broadcast op)."""
    g = _get_group(group)
    axes = _resolve_axes(g)
    if axes:
        src_in_group = g.get_group_rank(src) if g.ranks is not None else src
        return _run("c_broadcast", tensor,
                    lambda x: _raw_broadcast(x, src_in_group, g, axes))
    if jax.process_count() > 1:
        # host-level collective: multihost_utils drives its own pjit and
        # must NOT run inside the eager op funnel's jit (a traced input
        # would hit TracerArrayConversionError)
        from jax.experimental import multihost_utils
        raw = tensor._data if isinstance(tensor, Tensor) else tensor
        out = multihost_utils.broadcast_one_to_all(
            raw, is_source=jax.process_index() == int(src))
        if isinstance(tensor, Tensor):
            # raw, untaped replacement (the host collective cannot be
            # tape-recorded): bump the version so stale-grad guards fire
            tensor._swap_payload(Tensor(jnp.asarray(out)))
            tensor._inplace_version += 1
            return tensor
        return out
    return tensor


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    """reference: collective.py:585. Fills ``tensor_list`` with every rank's
    tensor; also returns the stacked result."""
    g = _get_group(group)
    axes = _resolve_axes(g)
    if axes:
        stacked = _run("c_allgather", tensor,
                       lambda x: _raw_allgather(x, g, axes), inplace=False)
    elif jax.process_count() > 1:
        from jax.experimental import multihost_utils
        raw = tensor._data if isinstance(tensor, Tensor) else tensor
        out = multihost_utils.process_allgather(raw)
        stacked = Tensor(out) if isinstance(tensor, Tensor) else out
    else:
        stacked = (Tensor(tensor._data[None]) if isinstance(tensor, Tensor)
                   else tensor[None])
    if tensor_list is not None:
        n = stacked.shape[0]
        for i in range(int(n)):
            tensor_list.append(stacked[i])
    return stacked


def all_gather_object(object_list, obj, group=None):
    """reference: collective.py all_gather_object (pickle transport)."""
    import pickle
    data = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        # pad to a common max size
        n = int(multihost_utils.process_allgather(
            jnp.asarray([data.size])).max())
        buf = np.zeros(n + 8, np.uint8)
        buf[:8] = np.frombuffer(np.int64(data.size).tobytes(), np.uint8)
        buf[8:8 + data.size] = data
        rows = multihost_utils.process_allgather(jnp.asarray(buf))
        for row in np.asarray(rows):  # noqa: PTA002 -- object gather is a host-side pickle exchange by contract; the fetch IS the operation
            size = int(np.frombuffer(row[:8].tobytes(), np.int64)[0])
            object_list.append(pickle.loads(row[8:8 + size].tobytes()))
    else:
        object_list.append(obj)
    return object_list


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    """reference: collective.py:663 — src holds a list of per-rank tensors;
    each rank receives its slice."""
    g = _get_group(group)
    axes = _resolve_axes(g)
    if not axes:
        if tensor_list:
            rank = g.get_group_rank(get_rank()) if g.ranks is not None else get_rank()
            if rank < 0:  # not a member of this group: keep input
                return tensor
            pick = tensor_list[rank]
            if isinstance(tensor, Tensor):
                tensor._swap_payload(pick if isinstance(pick, Tensor)
                                     else Tensor(pick))
                return tensor
            return pick
        return tensor

    def impl(x, stack):
        idx = _linear_index(axes)
        src_in_group = g.get_group_rank(src) if g.ranks is not None else src
        if src_in_group < 0:  # reference collective.py:663 asserts gsrc >= 0
            raise ValueError(
                f"scatter src={src} is not a member of group ranks "
                f"{g.ranks}")
        full = _raw_broadcast(stack, src_in_group, g, axes)
        if g.ranks is not None:
            # each member picks its slot by *group* rank; non-members keep x
            ranks = jnp.asarray(np.array(g.ranks, np.int32))  # noqa: PTA002 -- g.ranks is a host-side python list (trace-time constant), no device value involved
            matches = ranks == idx
            my = jnp.take(full, jnp.argmax(matches), axis=0)
            return jnp.where(matches.any(), my, x)
        return jnp.take(full, idx, axis=0)
    stack_raw = jnp.stack([t._data if isinstance(t, Tensor) else jnp.asarray(t)
                           for t in (tensor_list or [])])
    if isinstance(tensor, Tensor):
        out = apply("c_scatter", impl, tensor, Tensor(stack_raw))
        tensor._swap_payload(out)
        return tensor
    return impl(tensor, stack_raw)


def reduce_scatter(tensor, tensor_or_tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    """reference: operators/collective/c_reducescatter_op.cc."""
    g = _get_group(group)
    axes = _resolve_axes(g)
    src = tensor_or_tensor_list
    if isinstance(src, (list, tuple)):
        src = concat_tensors(src)
    if not axes:
        if isinstance(tensor, Tensor) and isinstance(src, Tensor):
            tensor._swap_payload(src)
            return tensor
        return src
    if isinstance(src, Tensor):
        out = apply("c_reducescatter",
                    lambda x: _raw_reduce_scatter(x, op, g, axes), src)
        if isinstance(tensor, Tensor):
            tensor._swap_payload(out)
            return tensor
        return out
    return _raw_reduce_scatter(src, op, g, axes)


def concat_tensors(ts):
    from ..ops import concat as _concat
    return _concat(list(ts), axis=0)


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    """reference: collective.py:1315 (alltoall op)."""
    g = _get_group(group)
    axes = _resolve_axes(g)
    xs = in_tensor_list
    single = not isinstance(xs, (list, tuple))
    stacked = xs if single else concat_tensors(
        [x.unsqueeze(0) if isinstance(x, Tensor) else x[None] for x in xs])
    if not axes:
        result = stacked
    else:
        result = _run("alltoall", stacked,
                      lambda x: _raw_alltoall(x, g, axes), inplace=False)
    if out_tensor_list is not None and not single:
        for i in range(result.shape[0]):
            out_tensor_list.append(result[i])
    return result


def send(tensor, dst=0, group=None, sync_op=True, src=None):
    """reference: collective.py:1386 (send_v2).

    In an SPMD trace every rank runs the same program, so the sending rank
    cannot be inferred from "who called send" the way the reference's
    per-process send_v2 kernel does — it must be stated. Pass ``src=``
    (or use :func:`p2p_exchange`) to name the sender; otherwise this
    raises rather than silently routing from rank 0.
    """
    g = _get_group(group)
    axes = _resolve_axes(g)
    if not axes:
        return tensor
    if src is None:
        raise NotImplementedError(
            "send() inside an SPMD trace cannot infer the sending rank; "
            "pass src= explicitly or use p2p_exchange(tensor, src, dst)")
    return p2p_exchange(tensor, src, dst, group)


def recv(tensor, src=0, group=None, sync_op=True, dst=None):
    """reference: collective.py:1436 (recv_v2). See :func:`send` — the
    receiving rank must be stated (``dst=``) inside an SPMD trace."""
    g = _get_group(group)
    axes = _resolve_axes(g)
    if not axes:
        return tensor
    if dst is None:
        raise NotImplementedError(
            "recv() inside an SPMD trace cannot infer the receiving rank; "
            "pass dst= explicitly or use p2p_exchange(tensor, src, dst)")
    return p2p_exchange(tensor, src, dst, group)


def p2p_exchange(tensor, src, dst, group=None):
    """Explicit SPMD point-to-point: value of rank ``src`` lands on rank
    ``dst``; every other rank keeps its own (the shard_map-native form of
    send_v2/recv_v2 used by the pipeline schedule)."""
    g = _get_group(group)
    axes = _resolve_axes(g)
    if not axes:
        return tensor
    return _run("p2p", tensor, lambda x: _raw_p2p(x, src, dst, axes))


def barrier(group=None):
    """reference: collective.py:165 (barrier op). Eager multi-process: a tiny
    allreduce is the barrier; in SPMD traces XLA orders collectives, no-op."""
    if jax.process_count() > 1 and not _axes_in_scope():
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("paddle_tpu_barrier")
    return None


def wait(tensor, group=None, use_calc_stream=True):
    """reference: collective.py:276. XLA owns stream ordering; block the host
    until the value is ready (the closest observable semantics)."""
    if isinstance(tensor, Tensor):
        tensor.block_until_ready()  # noqa: PTA002 -- wait()'s documented contract IS the host-side sync (reference collective.py:276)
    return tensor


def get_rank(group=None):
    from .env import get_rank as _r
    g = _get_group(group)
    r = _r()
    if g.ranks is not None:
        return g.get_group_rank(r)
    return r


def get_world_size(group=None):
    g = _get_group(group)
    if g is _GLOBAL_GROUP:
        from .env import get_world_size as _w
        return _w()
    return g.nranks


# -- trace-audit entrypoint ---------------------------------------------------

def build_compressed_train_step(mesh, axis: str = "dp",
                                wire_dtype: str = "int8",
                                block: Optional[int] = None,
                                lr: float = 0.1):
    """A dp train step whose gradient sync is the compressed allreduce:
    linear regression, per-shard grads, :func:`compressed_grad_sync`
    instead of ``lax.pmean``, SGD update. Small on purpose — the PTA009
    audit checks the *collective*: quantize → all_to_all/all_gather →
    dequantize must stay one fused device program with zero host
    transfers, and the replicated parameters must come back bit-identical
    across ranks (out_specs=P() asserts replication)."""
    from jax.sharding import PartitionSpec as P

    def _shard_fn(w, b, x, y):
        err = x @ w + b - y
        n_local = x.shape[0]
        gw = x.T @ err * (2.0 / n_local)
        gb = jnp.mean(err, axis=0) * 2.0
        gw, gb = compressed_grad_sync((gw, gb), axis=axis,
                                      wire_dtype=wire_dtype, block=block,
                                      mesh=mesh)
        loss = lax.pmean(jnp.mean(err * err), axis)
        return w - lr * gw, b - lr * gb, loss

    # check_vma=False: the all_gather phase replicates the result by
    # construction, but the checker cannot infer that statically
    return jax.shard_map(_shard_fn, mesh=mesh,
                         in_specs=(P(), P(), P(axis), P(axis)),
                         out_specs=(P(), P(), P()),
                         check_vma=False)


def _audit_compressed_allreduce_spec():
    from ..core import audit
    devices = np.array(jax.devices())  # noqa: PTA002 -- host-side device-list layout at audit registration, not a step path
    mesh = jax.sharding.Mesh(devices, ("dp",))
    n, feat, out, per_rank = devices.size, 32, 4, 4

    def make_args(variant):
        rng = np.random.default_rng(77 + variant)
        w = jnp.asarray(rng.standard_normal((feat, out)) * 0.1, jnp.float32)
        b = jnp.zeros((out,), jnp.float32)
        x = jnp.asarray(rng.standard_normal((n * per_rank, feat)),
                        jnp.float32)
        y = jnp.asarray(rng.standard_normal((n * per_rank, out)),
                        jnp.float32)
        return (w, b, x, y)

    # fresh w/b per call (make_args), so the updated params can consume
    # their input buffers — same donation contract as the bench steps
    return audit.AuditSpec(fn=build_compressed_train_step(mesh, block=64),
                           make_args=make_args,
                           jit_kwargs={"donate_argnums": (0, 1)})


def _register_audit_entrypoints():
    from ..core import audit
    audit.register_entrypoint("compressed_allreduce_train_step",
                              _audit_compressed_allreduce_spec,
                              tags=("train", "collective", "bench"))


_register_audit_entrypoints()
