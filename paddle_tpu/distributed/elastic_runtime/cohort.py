"""Cohort re-formation: the supervisor half of surviving host loss.

:class:`~paddle_tpu.distributed.launch.ElasticSupervisor` (PR 1) respawns
*individual* ranks — correct for a single-host job, wrong for a multi-host
SPMD world: once any peer dies, every survivor's collectives are wedged and
the ``jax.distributed`` runtime cannot admit a lone replacement into a
half-dead world. Recovery is all-or-nothing: tear down every local worker,
bump the cohort generation, and re-run ``jax.distributed.initialize`` for
a *new* world.

:class:`CohortSupervisor` is that extension (``launch --elastic`` builds it).
On a cohort event — a child exiting
:data:`~paddle_tpu.distributed.elastic.HOST_LOST_EXIT_CODE` (its watchdog
caught a hung collective), any fatal child exit in a multi-rank world, or a
heartbeat-declared host death — it:

1. records a ``distributed.cohort_reform`` flight event (after the health
   plane's own ``distributed.host_lost`` event, before any teardown),
2. SIGTERM→SIGKILLs all surviving local workers,
3. consumes ONE restart-budget unit for the whole re-formation (preemption
   cascades are free, like single-rank preemption always was),
4. computes the next world: a dead endpoint is replaced from
   ``spare_endpoints`` when one is available, dropped when
   ``shrink_on_loss`` is set or the endpoint is an unreachable remote,
   kept when it is local (a respawnable process, not a lost machine),
5. bumps the generation (``PADDLE_TPU_COHORT_GEN``), updates the PADDLE_*
   env contract to the new world, and respawns every local rank.

The respawned trainers re-run ``jax.distributed.initialize`` through the
normal pre-backend bootstrap (env.py) and restore from the newest committed
multi-host checkpoint via the PR 10 manifest; when the world shrank,
``load_sharded``'s re-shard path reassembles the full arrays from all
hosts' shard files and lays them out over the smaller mesh (dp degree is
whatever the trainer recomputes from ``PADDLE_TRAINERS_NUM``).

Exit-code taxonomy (docs/fault_tolerance.md): 0 done · 117 preemption
(free) · 119 divergence (never restarted) · 121 host lost (cohort reform,
budgeted) · other fatal (cohort reform in a multi-rank world, per-rank
respawn in a single-rank one — the PR 1 semantics, unchanged).
"""
from __future__ import annotations

import signal
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence

from ..elastic import (DIVERGENCE_EXIT_CODE, HOST_LOST_EXIT_CODE,
                       PREEMPTION_EXIT_CODE)
from ..launch import (ElasticSupervisor, _spawn_rank, _tail_log,
                      terminate_local_procs)
from .heartbeat import (COHORT_GEN_VAR, HEARTBEAT_ADDR_VAR,
                        HeartbeatConfig, HeartbeatCoordinator)
from .watchdog import STEP_DEADLINE_VAR


class CohortSupervisor(ElasticSupervisor):
    """Supervise a cohort of ranks as one unit (see module docstring)."""

    def __init__(self, endpoints, script, script_args,
                 step_deadline: Optional[float] = None,
                 heartbeat: bool = False,
                 heartbeat_port: int = 0,
                 heartbeat_interval: Optional[float] = None,
                 heartbeat_miss: Optional[int] = None,
                 shrink_on_loss: bool = False,
                 spare_endpoints: Sequence[str] = (),
                 reform_on_crash: Optional[bool] = None,
                 settle_s: float = 1.0,
                 **kw):
        super().__init__(endpoints, script, script_args, **kw)
        self.generation = 0
        self.world: List[str] = list(endpoints)
        self.shrink_on_loss = bool(shrink_on_loss)
        self.spares: List[str] = list(spare_endpoints)
        # single-rank worlds keep PR 1's per-rank respawn; any multi-rank
        # world must re-form as a unit (a lone respawn can't rejoin a
        # wedged jax.distributed world)
        self.reform_on_crash = (len(endpoints) > 1 if reform_on_crash is None
                                else bool(reform_on_crash))
        self.settle_s = float(settle_s)
        self.reforms = 0
        # the endpoints this supervisor is responsible for spawning: its
        # node's slice of the initial world (ips decide locality after a
        # shrink/replace reshuffles ranks)
        base = self.node_rank * self.nproc_per_node
        local = endpoints[base:base + self.nproc_per_node]
        self._local_ips = {ep.rsplit(":", 1)[0] for ep in local}
        self._procs: List = []
        self._death_lock = threading.Lock()
        self._remote_deaths: List[Dict] = []
        self._coordinator: Optional[HeartbeatCoordinator] = None
        self._hb_config = None
        if heartbeat:
            self._hb_config = HeartbeatConfig(
                interval_s=heartbeat_interval, miss_threshold=heartbeat_miss)
            self._hb_port = int(heartbeat_port)
        if step_deadline and float(step_deadline) > 0:
            self.extra_env[STEP_DEADLINE_VAR] = str(float(step_deadline))
        self.extra_env.setdefault(COHORT_GEN_VAR, "0")
        if self.log_dir:
            # watchdog flight dumps should land next to the workerlogs
            self.extra_env.setdefault("PADDLE_TPU_FLIGHT_DIR", self.log_dir)

    # -- spawning -----------------------------------------------------------
    def _local_rank_slots(self):
        """(global_rank, local_rank) pairs this supervisor owns in the
        *current* world — locality by endpoint ip, because a shrink or a
        spare substitution renumbers global ranks."""
        slots = []
        for i, ep in enumerate(self.world):
            if ep.rsplit(":", 1)[0] in self._local_ips:
                slots.append((i, len(slots)))
        return slots

    def _spawn_cohort(self) -> List:
        procs = []
        for rank, local_rank in self._local_rank_slots():
            n = self._restart_counts.get(rank, 0)
            procs.append(_spawn_rank(
                rank, local_rank, self.world, self.script, self.script_args,
                self.log_dir, self.extra_env, restart_num=n))
        self._procs = procs
        return procs

    # -- heartbeat-declared deaths ------------------------------------------
    def _note_death(self, rank: int, info: Dict):
        # coordinator-thread callback: queue only (the run loop owns all
        # process/teardown state); the health plane already recorded the
        # distributed.host_lost flight event before calling us
        with self._death_lock:
            self._remote_deaths.append(dict(info))

    def _pop_remote_deaths(self) -> List[Dict]:
        with self._death_lock:
            out, self._remote_deaths = self._remote_deaths, []
            return out

    # -- the supervise loop -------------------------------------------------
    def run(self) -> int:
        if self._hb_config is not None:
            self._coordinator = HeartbeatCoordinator(
                port=self._hb_port, config=self._hb_config,
                on_death=self._note_death)
            self._coordinator.start()
            self.extra_env[HEARTBEAT_ADDR_VAR] = self._coordinator.address
        alive = self._spawn_cohort()
        prev = {}
        for sig in (signal.SIGTERM, signal.SIGINT):
            prev[sig] = signal.signal(sig, self.request_drain)
        try:
            while alive:
                if self._drain:
                    sys.stderr.write(
                        f"cohort supervisor: draining {len(alive)} rank(s) "
                        f"(grace {self.grace_period}s)\n")
                    terminate_local_procs(alive, self.grace_period)
                    return 1
                self._sleep(self.poll_interval)
                deaths = self._pop_remote_deaths()
                if deaths:
                    rc = self._reform(alive, fatals={}, declared=deaths)
                    if rc is not None:
                        return rc
                    alive = self._procs
                    continue
                fatals: Dict[int, int] = {}
                for p in list(alive):
                    ret = p.poll()
                    if ret is None:
                        continue
                    alive.remove(p)
                    f = getattr(p, "_log_file", None)
                    if f:
                        f.close()
                    if ret == 0:
                        continue
                    tail = _tail_log(p._log_path)
                    if tail:
                        sys.stderr.write(
                            f"----- workerlog.{p._rank} (tail) -----\n"
                            f"{tail}\n"
                            f"----- end workerlog.{p._rank} -----\n")
                    if ret == DIVERGENCE_EXIT_CODE:
                        sys.stderr.write(
                            f"rank {p._rank} halted on numerical divergence "
                            f"(exit {ret}); not restarting — terminating "
                            f"the job\n")
                        terminate_local_procs(alive, self.grace_period)
                        return ret
                    if not self._cohort_event(ret):
                        rc = self._respawn_single(alive, p, ret)
                        if rc is not None:
                            return rc
                        continue
                    fatals[p._rank] = ret
                if fatals:
                    # settle briefly so near-simultaneous peer exits (the
                    # SIGKILLed host AND the 121 messengers) are all
                    # attributed to this round before the shrink decision
                    self._collect_fatals(alive, fatals)
                    rc = self._reform(alive, fatals)
                    if rc is not None:
                        return rc
                    alive = self._procs
            return 0
        finally:
            for sig, h in prev.items():
                signal.signal(sig, h)
            terminate_local_procs(alive, self.grace_period)
            if self._coordinator is not None:
                self._coordinator.stop()

    def _cohort_event(self, ret: int) -> bool:
        if ret == HOST_LOST_EXIT_CODE:
            return True
        return self.reform_on_crash

    def _respawn_single(self, alive, p, ret) -> Optional[int]:
        """PR 1 per-rank semantics for single-rank worlds: 117 free respawn,
        crash respawn under budget. Returns an exit code to propagate or
        None to continue supervising."""
        if ret == PREEMPTION_EXIT_CODE:
            sys.stderr.write(
                f"rank {p._rank} drained after preemption (exit {ret}); "
                f"restarting (free — budget "
                f"{self.max_restarts - self.restarts_used} left)\n")
            alive.append(self._respawn(p))
            return None
        if not self.budget.try_consume():
            sys.stderr.write(
                f"rank {p._rank} exited with code {ret}; restart budget "
                f"({self.max_restarts}) exhausted — terminating the job\n")
            terminate_local_procs(alive, self.grace_period)
            return ret
        pause = self.budget.pause()
        sys.stderr.write(
            f"rank {p._rank} exited with code {ret}; restarting in "
            f"{pause:.2f}s ({self.restarts_used}/{self.max_restarts} "
            f"restarts used)\n")
        self._sleep(pause)
        if not self._drain:
            alive.append(self._respawn(p))
        return None

    def _collect_fatals(self, alive: List, fatals: Dict[int, int]):
        """Poll survivors for up to ``settle_s`` more, folding any further
        fatal exits into this round (the watchdog messengers and the
        actually-dead rank race each other to the supervisor)."""
        deadline = time.monotonic() + self.settle_s
        while alive and time.monotonic() < deadline:
            self._sleep(min(self.poll_interval, 0.05))
            for p in list(alive):
                ret = p.poll()
                if ret is None:
                    continue
                alive.remove(p)
                f = getattr(p, "_log_file", None)
                if f:
                    f.close()
                if ret != 0:
                    fatals[p._rank] = ret

    # -- re-formation -------------------------------------------------------
    def _reform(self, alive: List, fatals: Dict[int, int],
                declared: Sequence[Dict] = ()) -> Optional[int]:
        """Tear down, recompute the world, respawn at generation+1.
        Returns an exit code to propagate, or None when the new cohort is
        up."""
        from ...observability import flight as _flight
        next_gen = self.generation + 1
        # ranks whose HOST is gone: fatal exits other than the watchdog
        # messengers (121) / preemption drains (117), plus every
        # heartbeat-declared death
        dead_ranks = sorted(
            {r for r, c in fatals.items()
             if c not in (HOST_LOST_EXIT_CODE, PREEMPTION_EXIT_CODE)}
            | {int(d["rank"]) for d in declared})
        free = (bool(fatals) and not declared
                and set(fatals.values()) == {PREEMPTION_EXIT_CODE})
        _flight.record_event(
            "distributed.cohort_reform",
            {"gen": self.generation, "next_gen": next_gen,
             "fatals": {str(r): c for r, c in fatals.items()},
             "declared_dead": dead_ranks, "free": free})
        sys.stderr.write(
            f"cohort supervisor: generation {self.generation} lost "
            f"rank(s) {dead_ranks or sorted(fatals)} "
            f"(exits {fatals}, heartbeat-declared "
            f"{[d['rank'] for d in declared]}); tearing down "
            f"{len(alive)} survivor(s) and re-forming\n")
        terminate_local_procs(alive, self.grace_period)
        del alive[:]
        if not free and not self.budget.try_consume():
            code = next((c for c in fatals.values()
                         if c != PREEMPTION_EXIT_CODE), 1)
            sys.stderr.write(
                f"cohort supervisor: restart budget ({self.max_restarts}) "
                f"exhausted — terminating the job (exit {code})\n")
            return code

        dead_eps = {self.world[r] for r in dead_ranks
                    if 0 <= r < len(self.world)}
        new_world: List[str] = []
        for ep in self.world:
            if ep not in dead_eps:
                new_world.append(ep)
            elif self.spares:
                sub = self.spares.pop(0)
                sys.stderr.write(
                    f"cohort supervisor: replacing lost {ep} with spare "
                    f"{sub}\n")
                new_world.append(sub)
            elif self.shrink_on_loss:
                sys.stderr.write(
                    f"cohort supervisor: dropping lost {ep} "
                    f"(shrink-to-fit)\n")
            elif ep.rsplit(":", 1)[0] in self._local_ips:
                new_world.append(ep)  # local process, machine still here
            else:
                sys.stderr.write(
                    f"cohort supervisor: dropping unreachable {ep} "
                    f"(no spare available)\n")
        if not new_world or not any(
                ep.rsplit(":", 1)[0] in self._local_ips
                for ep in new_world):
            sys.stderr.write(
                "cohort supervisor: no local ranks left after "
                "re-formation — terminating\n")
            return 1

        self.generation = next_gen
        self.world = new_world
        self.endpoints = new_world  # keeps inherited _respawn coherent
        self.extra_env[COHORT_GEN_VAR] = str(self.generation)
        if self._coordinator is not None:
            self._coordinator.set_generation(self.generation)
        for rank, _lr in self._local_rank_slots():
            self._restart_counts[rank] = self._restart_counts.get(rank, 0) + 1
        pause = self.budget.pause() if not free else 0.0
        if pause:
            self._sleep(pause)
        if self._drain:
            return 1
        self._spawn_cohort()
        self.reforms += 1
        sys.stderr.write(
            f"cohort supervisor: generation {self.generation} up — world "
            f"size {len(new_world)}, {len(self._procs)} local rank(s), "
            f"budget {self.max_restarts - self.restarts_used} left\n")
        return None
