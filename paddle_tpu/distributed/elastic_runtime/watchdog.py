"""StepWatchdog: convert a hung collective into a bounded-time exit 121.

The dominant real-world multi-host failure is not a crash — it is a stall.
When a peer host is SIGKILLed mid-allreduce, the surviving hosts' XLA
collectives simply never complete; the job wedges forever with no exception
to catch. The watchdog is the bound on that: the training loop *arms* it
at the start of every guarded step and *disarms* it at the end; a deadline
thread notices an armed step that overstayed ``deadline_s`` and turns the
stall into :data:`~paddle_tpu.distributed.elastic.HOST_LOST_EXIT_CODE`
(121) — after writing a flight record (last events + spans, the hung step
number, the cohort generation) so the post-mortem shows *where* the world
wedged. The cohort supervisor (elastic_runtime.cohort) treats 121 as "a
peer is gone" and re-forms the whole cohort.

Step-path cost is two monotonic-clock reads and two short lock sections
per step (``arm`` + ``disarm``) — no device work, no host syncs, no
allocation. The ≤2% overhead budget is enforced by
``tools/bench_elastic.py --check``.

The firing path runs on the watchdog thread (NOT a signal handler — no
async-signal-safety constraints), but keeps the same flag-only discipline:
``arm``/``disarm`` touch shared state only under ``_lock`` and the thread
calls out (flight dump, exit) only after dropping it.

Fault sites fired inside :meth:`StepWatchdog.arm` (the start of a guarded
step — see docs/fault_tolerance.md):

* ``host_kill:N:crash`` — hard ``os._exit`` on the Nth guarded step: the
  in-process analog of SIGKILLing this host mid-step.
* ``collective_hang:N:hang`` — the Nth guarded step blocks for
  ``PADDLE_TPU_FAULT_HANG_S`` (default 3600) seconds *inside the armed
  window*, simulating the survivor side of a peer death mid-allreduce;
  the watchdog converts it to exit 121 at the deadline.
"""
from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from typing import Callable, Optional

from ..elastic import HOST_LOST_EXIT_CODE  # noqa: F401  (re-exported)
from ...utils.resilience import fault_injector
from .heartbeat import cohort_generation

#: env var the cohort supervisor sets in every child: guarded-step deadline
#: in seconds; presence auto-arms a process-wide StepWatchdog (see
#: maybe_auto_watchdog). 0/unset = watchdog off.
STEP_DEADLINE_VAR = "PADDLE_TPU_STEP_DEADLINE_S"

HANG_SECONDS = float(os.environ.get("PADDLE_TPU_FAULT_HANG_S", "3600"))


class StepWatchdog:
    """Deadline thread around guarded train steps.

    ::

        wd = StepWatchdog(deadline_s=60)
        for step, batch in enumerate(loader):
            with wd.guard(step):
                loss = train_step(batch)   # hangs forever? exit 121 at 60s

    ``on_timeout`` (tests) replaces the terminal dump+exit; ``exit_fn`` is
    injectable for the same reason. ``heartbeat`` is an optional
    :class:`~.heartbeat.BeaconSender` that gets ``notify_step`` with each
    disarmed step's wall-time, so the health plane's straggler detector
    sees real step times without separate wiring.
    """

    def __init__(self, deadline_s: float,
                 on_timeout: Optional[Callable[[Optional[int], float],
                                               None]] = None,
                 exit_fn: Callable[[int], None] = os._exit,
                 heartbeat=None, clock=time.monotonic,
                 poll_s: Optional[float] = None):
        self.deadline_s = float(deadline_s)
        if self.deadline_s <= 0:
            raise ValueError(
                f"StepWatchdog deadline must be positive, got {deadline_s}"
                f" (omit the watchdog instead of arming a zero deadline)")
        self._on_timeout = on_timeout
        self._exit_fn = exit_fn
        self.heartbeat = heartbeat
        self._clock = clock
        self._poll_s = (max(0.005, min(0.25, self.deadline_s / 8.0))
                        if poll_s is None else float(poll_s))
        self._lock = threading.Lock()
        self._armed_at: Optional[float] = None
        self._step: Optional[int] = None
        self._fired = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- step-path API ------------------------------------------------------
    def arm(self, step: Optional[int] = None):
        """Start the deadline for one guarded step. Fires the ``host_kill``
        and ``collective_hang`` chaos sites (the latter *after* arming, so
        an injected hang is covered by the deadline it exists to test)."""
        # fire the chaos sites only when some spec is armed at all — fire()
        # itself is cheap, but arm() runs once per train step and the
        # common case (no injection) should cost one bool check
        inj = fault_injector()
        chaos = inj.armed()
        if chaos:
            inj.fire("host_kill")
        with self._lock:
            self._armed_at = self._clock()
            self._step = step
        self._ensure_thread()
        if chaos and inj.fire("collective_hang") == "hang":
            time.sleep(HANG_SECONDS)

    def disarm(self) -> Optional[float]:
        """End the guarded step; returns its wall-time (None if unarmed)."""
        with self._lock:
            if self._armed_at is None:
                return None
            elapsed = self._clock() - self._armed_at
            self._armed_at = None
            step = self._step
        if self.heartbeat is not None and step is not None:
            self.heartbeat.notify_step(step, elapsed)
        return elapsed

    @contextmanager
    def guard(self, step: Optional[int] = None):
        self.arm(step)
        try:
            yield self
        finally:
            self.disarm()

    @property
    def armed(self) -> bool:
        with self._lock:
            return self._armed_at is not None

    @property
    def fired(self) -> bool:
        with self._lock:
            return self._fired

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- deadline thread ----------------------------------------------------
    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._watch, name="step-watchdog", daemon=True)
            self._thread.start()

    def _watch(self):
        while not self._stop.wait(self._poll_s):
            with self._lock:
                armed_at = self._armed_at
                step = self._step
                if armed_at is None:
                    continue
                elapsed = self._clock() - armed_at
                if elapsed <= self.deadline_s:
                    continue
                self._fired = True
                self._armed_at = None
            self._fire(step, elapsed)
            return

    def _fire(self, step: Optional[int], elapsed: float):
        """Deadline blown: the step wedged (peer death mid-collective is
        the expected cause). Record + dump the flight timeline, then exit
        with the reserved host-lost code so the cohort supervisor re-forms
        the world instead of respawning just this rank."""
        from ...observability import flight as _flight
        gen = cohort_generation()
        _flight.record_event(
            "distributed.watchdog_fired",
            {"step": step, "gen": gen, "elapsed_s": round(elapsed, 3),
             "deadline_s": self.deadline_s})
        if self._on_timeout is not None:
            self._on_timeout(step, elapsed)
            return
        # unconditional dump (not dump_if_armed): the process is about to
        # exit 121 and this file is the only record of where it wedged —
        # last events, last spans, the hung step, the cohort generation
        _flight.dump(f"host_lost_watchdog_step_{step}_gen_{gen}")
        self._exit_fn(HOST_LOST_EXIT_CODE)


_AUTO_WATCHDOG: list = []


def maybe_auto_watchdog(watchdog: Optional[StepWatchdog] = None
                        ) -> Optional[StepWatchdog]:
    """Return ``watchdog``, or the process-wide auto-armed one when the
    cohort supervisor set :data:`STEP_DEADLINE_VAR` (>0), else None — the
    same wire-through-env pattern as
    :func:`~paddle_tpu.distributed.elastic.maybe_auto_guard`."""
    if watchdog is not None:
        return watchdog
    if _AUTO_WATCHDOG:
        return _AUTO_WATCHDOG[0]
    try:
        deadline = float(os.environ.get(STEP_DEADLINE_VAR, "0") or "0")
    except ValueError:
        return None
    if deadline <= 0:
        return None
    from .heartbeat import maybe_auto_sender
    wd = StepWatchdog(deadline, heartbeat=maybe_auto_sender())
    _AUTO_WATCHDOG.append(wd)
    return wd


def _reset_auto_watchdog_for_tests():
    while _AUTO_WATCHDOG:
        _AUTO_WATCHDOG.pop().stop()
