"""Heartbeat health plane: liveness + straggler side-channel over stdlib TCP.

The SPMD data plane (XLA collectives) has no failure detector — when a peer
host dies mid-allreduce the survivors stall, they don't crash. This module
is the out-of-band control plane that notices: the coordinator (by
convention the host of ``PADDLE_TRAINER_ENDPOINTS[0]``, i.e. the same host
that runs the ``jax.distributed`` coordinator service) runs a
:class:`HeartbeatCoordinator`, and every worker runs a :class:`BeaconSender`
thread that POSTs one JSON beacon per interval carrying
``(rank, cohort generation, step number, last step wall-time)``.

Declarations the coordinator makes from the beacon stream:

* **host death** — ``miss_threshold`` consecutive intervals without a
  beacon. A ``distributed.host_lost`` flight event is recorded *before*
  the ``on_death`` callback runs (the callback is what triggers cohort
  teardown, and the acceptance contract is "every declared death produces
  a flight event before any teardown").
* **straggler** — a host whose reported step wall-time sits more than
  ``straggler_z`` standard deviations above the cohort mean (computed over
  the hosts' latest step times; needs ``straggler_min_peers`` reporting
  hosts for the z-score to mean anything). Emits a ``distributed.straggler``
  flight event on the rising edge and a labeled gauge either way.

Per-host liveness/step/step-time/lag/straggler state is published as
labeled gauges on the default :class:`~paddle_tpu.core.monitor.StatRegistry`
so ``/metricsz`` (observability/metrics.py) renders one sample per rank.

Partition tolerance is symmetric: the sender counts consecutive beacon
*send* failures and declares the coordinator dead past the same threshold
(``distributed.coordinator_lost`` flight event + ``on_coordinator_lost``
callback) — a worker isolated from the control plane knows it, instead of
training headless forever.

Transport is one short-lived TCP connection per beacon (connect, one JSON
line, read one JSON reply, close). At 1 Hz per host that is noise, and it
keeps the protocol stateless: a half-open connection from a dead host can't
wedge the accept loop. The reply carries the coordinator's current cohort
view (``generation`` + declared-dead ranks) so workers learn verdicts
without a second channel.

Fault sites (``PADDLE_TPU_FAULT_SPEC``, docs/fault_tolerance.md):

* ``heartbeat_partition:N:drop`` — the Nth beacon *latches* a simulated
  network partition: that beacon and every later one is silently dropped
  (real partitions don't heal after one packet), so the coordinator
  declares this host dead after ``miss_threshold`` intervals.
* ``slow_link:N:delay`` — the Nth beacon is delayed by
  ``PADDLE_TPU_FAULT_SLOW_LINK_S`` (default 2.0) seconds before sending —
  a transient, per-occurrence slow link.

Threads hold ``_lock`` only around state mutation and never call out
under it (PTA006); sockets are owned by the thread that created them.
"""
from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Callable, Dict, Optional

from ...utils.resilience import fault_injector

#: env var the cohort supervisor sets in every child: "host:port" of the
#: HeartbeatCoordinator; presence auto-starts a BeaconSender (see
#: maybe_auto_sender).
HEARTBEAT_ADDR_VAR = "PADDLE_TPU_HEARTBEAT_ADDR"

#: env var carrying the cohort generation (bumped by the supervisor on every
#: re-formation; generation 0 is the initial world).
COHORT_GEN_VAR = "PADDLE_TPU_COHORT_GEN"

SLOW_LINK_SECONDS = float(os.environ.get("PADDLE_TPU_FAULT_SLOW_LINK_S",
                                         "2.0"))


def cohort_generation() -> int:
    """This process's cohort generation (0 outside a cohort supervisor)."""
    try:
        return int(os.environ.get(COHORT_GEN_VAR, "0"))
    except ValueError:
        return 0


class HeartbeatConfig:
    """Tuning knobs shared by both halves of the plane."""

    def __init__(self, interval_s: Optional[float] = None,
                 miss_threshold: Optional[int] = None,
                 straggler_z: float = 3.0,
                 straggler_min_peers: int = 3,
                 connect_timeout_s: float = 2.0):
        if interval_s is None:
            interval_s = float(os.environ.get(
                "PADDLE_TPU_HEARTBEAT_INTERVAL", "1.0"))
        if miss_threshold is None:
            miss_threshold = int(os.environ.get(
                "PADDLE_TPU_HEARTBEAT_MISS", "3"))
        self.interval_s = max(0.01, float(interval_s))
        self.miss_threshold = max(1, int(miss_threshold))
        self.straggler_z = float(straggler_z)
        self.straggler_min_peers = max(2, int(straggler_min_peers))
        self.connect_timeout_s = float(connect_timeout_s)

    @property
    def death_after_s(self) -> float:
        return self.interval_s * self.miss_threshold


class _Peer:
    __slots__ = ("rank", "gen", "step", "step_s", "host", "pid",
                 "last_seen", "straggler")

    def __init__(self, rank: int):
        self.rank = rank
        self.gen = 0
        self.step = -1
        self.step_s: Optional[float] = None
        self.host = ""
        self.pid = 0
        self.last_seen = 0.0
        self.straggler = False


class HeartbeatCoordinator:
    """Accept beacons, track per-host liveness, declare deaths/stragglers.

    One daemon thread runs both the accept loop and the sweep (beacon rates
    are ~1/s/host; a dedicated sweeper would be ceremony). ``on_death`` is
    called once per declared rank, after the flight event and gauge flip.
    """

    def __init__(self, bind: str = "127.0.0.1", port: int = 0,
                 config: Optional[HeartbeatConfig] = None,
                 on_death: Optional[Callable[[int, Dict], None]] = None,
                 registry=None, clock=time.monotonic):
        self.config = config or HeartbeatConfig()
        self._on_death = on_death
        self._clock = clock
        self._registry = registry
        self._lock = threading.Lock()
        self._peers: Dict[int, _Peer] = {}
        self._dead: Dict[int, Dict] = {}
        self.generation = 0
        self._stop = threading.Event()
        self._srv = socket.create_server((bind, port))
        self._srv.settimeout(min(0.2, self.config.interval_s / 2.0))
        self.port = self._srv.getsockname()[1]
        self.address = f"{bind}:{self.port}"
        self._thread: Optional[threading.Thread] = None

    # -- registry plumbing (lazy: the default registry lives in core) -------
    def _reg(self):
        if self._registry is None:
            from ...core import monitor as _monitor
            self._registry = _monitor.default_registry()
        return self._registry

    def _gauge(self, name: str, rank: int, value: float):
        self._reg().set_labeled(name, {"rank": str(rank)}, value)

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._serve, name="heartbeat-coordinator",
                daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        try:
            self._srv.close()
        except OSError:
            pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def set_generation(self, gen: int):
        """New cohort generation: prior declarations are stale (the dead
        rank's endpoint was replaced or dropped), so the slate is wiped."""
        with self._lock:
            self.generation = int(gen)
            self._peers.clear()
            self._dead.clear()

    # -- views --------------------------------------------------------------
    def declared_dead(self) -> Dict[int, Dict]:
        with self._lock:
            return dict(self._dead)

    def snapshot(self) -> Dict[int, Dict]:
        """Per-rank view for /healthz-style introspection and tests."""
        now = self._clock()
        with self._lock:
            return {r: {"rank": r, "gen": p.gen, "step": p.step,
                        "step_s": p.step_s, "host": p.host, "pid": p.pid,
                        "age_s": now - p.last_seen,
                        "straggler": p.straggler,
                        "dead": r in self._dead}
                    for r, p in self._peers.items()}

    # -- serve loop ---------------------------------------------------------
    def _serve(self):
        while not self._stop.is_set():
            try:
                conn, _addr = self._srv.accept()
            except socket.timeout:
                pass
            except OSError:
                return  # socket closed under us: stop() won the race
            else:
                try:
                    self._handle(conn)
                finally:
                    conn.close()
            self._sweep()

    def _handle(self, conn: socket.socket):
        conn.settimeout(self.config.connect_timeout_s)
        try:
            raw = conn.makefile("rb").readline()
            beacon = json.loads(raw.decode("utf-8"))
        except (OSError, ValueError):
            return  # torn beacon: the sender will retry next interval
        if not isinstance(beacon, dict) or "rank" not in beacon:
            return
        rank = int(beacon["rank"])
        now = self._clock()
        with self._lock:
            peer = self._peers.get(rank)
            if peer is None:
                peer = self._peers[rank] = _Peer(rank)
            peer.gen = int(beacon.get("gen", 0))
            peer.step = int(beacon.get("step", -1))
            step_s = beacon.get("step_s")
            peer.step_s = float(step_s) if step_s is not None else None
            peer.host = str(beacon.get("host", ""))
            peer.pid = int(beacon.get("pid", 0))
            peer.last_seen = now
            was_dead = self._dead.pop(rank, None)
            gen = self.generation
            dead = sorted(self._dead)
        if was_dead is not None:
            # a declared-dead rank beaconing again means the declaration
            # was a partition, not a death — record the recovery
            from ...observability import flight as _flight
            _flight.record_event("distributed.host_recovered",
                                 {"rank": rank, "gen": gen})
        self._gauge("distributed.host_up", rank, 1.0)
        self._gauge("distributed.host_step", rank, float(peer.step))
        if peer.step_s is not None:
            self._gauge("distributed.host_step_ms", rank,
                        peer.step_s * 1000.0)
        self._reg().add("distributed.heartbeats", 1)
        try:
            conn.sendall((json.dumps(
                {"ok": True, "gen": gen, "dead": dead}) + "\n")
                .encode("utf-8"))
        except OSError:
            pass  # sender vanished mid-reply; its own retry loop copes

    def _sweep(self):
        now = self._clock()
        newly_dead = []
        with self._lock:
            alive = [p for r, p in self._peers.items() if r not in self._dead]
            for p in alive:
                if now - p.last_seen > self.config.death_after_s:
                    info = {"rank": p.rank, "gen": p.gen, "step": p.step,
                            "host": p.host, "pid": p.pid,
                            "silent_s": now - p.last_seen}
                    self._dead[p.rank] = info
                    newly_dead.append(info)
            alive = [p for p in alive if p.rank not in self._dead]
            straggler_events, straggler_rows = \
                self._update_stragglers_locked()
            max_step = max((p.step for p in alive), default=-1)
            lag_rows = [(p.rank, max_step - p.step) for p in alive
                        if p.step >= 0]
        for ev in straggler_events:
            from ...observability import flight as _flight
            _flight.record_event("distributed.straggler", ev)
        for rank, flag in straggler_rows:
            self._gauge("distributed.straggler", rank, 1.0 if flag else 0.0)
        for rank, lag in lag_rows:
            self._gauge("distributed.host_step_lag", rank, float(lag))
        for info in newly_dead:
            # contract: the flight event lands BEFORE any teardown the
            # on_death callback may trigger
            from ...observability import flight as _flight
            _flight.record_event("distributed.host_lost", dict(info))
            self._gauge("distributed.host_up", info["rank"], 0.0)
            self._reg().add("distributed.deaths_declared", 1)
            if self._on_death is not None:
                self._on_death(info["rank"], info)

    def _update_stragglers_locked(self):
        """z-score each live host's latest step time against the cohort.
        Caller holds ``_lock``; returns ``(rising_edge_events, rows)`` so
        flight/gauge emission happens after the lock is dropped."""
        live = [p for r, p in self._peers.items()  # noqa: PTA006 -- _locked suffix contract: sole caller (_sweep) holds _lock
                if r not in self._dead and p.step_s is not None]  # noqa: PTA006 -- _locked suffix contract: sole caller (_sweep) holds _lock
        events = []
        if len(live) >= self.config.straggler_min_peers:
            times = [p.step_s for p in live]
            mean = sum(times) / len(times)
            var = sum((t - mean) ** 2 for t in times) / len(times)
            std = var ** 0.5
            for p in live:
                z = (p.step_s - mean) / std if std > 1e-12 else 0.0
                is_straggler = z > self.config.straggler_z
                if is_straggler and not p.straggler:
                    events.append({"rank": p.rank, "step": p.step,
                                   "step_s": p.step_s, "z": round(z, 3),
                                   "cohort_mean_s": mean})
                p.straggler = is_straggler
        return events, [(p.rank, p.straggler) for p in live]


class BeaconSender:
    """Worker half: one daemon thread beaconing this host's liveness.

    The train loop (StepWatchdog.disarm, TrainEpochRange, hapi callbacks)
    calls :meth:`notify_step` with the latest completed step and its
    wall-time; the beacon thread snapshots that under the lock. Zero work
    on the step path beyond two float stores.
    """

    def __init__(self, address: str, rank: int, gen: Optional[int] = None,
                 config: Optional[HeartbeatConfig] = None,
                 on_coordinator_lost: Optional[Callable[[], None]] = None,
                 clock=time.monotonic):
        host, _, port = address.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.rank = int(rank)
        self.gen = cohort_generation() if gen is None else int(gen)
        self.config = config or HeartbeatConfig()
        self._on_coordinator_lost = on_coordinator_lost
        self._clock = clock
        self._lock = threading.Lock()
        self._step = -1
        self._step_s: Optional[float] = None
        self._consec_fail = 0
        self._coordinator_lost = False
        self._partitioned = False
        self.peer_dead: frozenset = frozenset()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def notify_step(self, step: int, step_s: Optional[float] = None):
        with self._lock:
            self._step = int(step)
            if step_s is not None:
                self._step_s = float(step_s)

    @property
    def coordinator_lost(self) -> bool:
        with self._lock:
            return self._coordinator_lost

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name=f"heartbeat-sender-{self.rank}",
                daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- beacon loop --------------------------------------------------------
    def _loop(self):
        # first beacon immediately: the coordinator should see the host as
        # alive before the first full interval elapses
        while True:
            self._beat()
            if self._stop.wait(self.config.interval_s):
                return

    def _beat(self):
        inj = fault_injector()
        if inj.fire("heartbeat_partition") == "drop":
            self._partitioned = True  # partitions latch; they don't heal
        if self._partitioned:
            return
        if inj.fire("slow_link") == "delay":
            time.sleep(min(SLOW_LINK_SECONDS, self.config.death_after_s))
        with self._lock:
            payload = {"rank": self.rank, "gen": self.gen,
                       "step": self._step, "step_s": self._step_s,
                       "host": socket.gethostname(), "pid": os.getpid()}
        try:
            with socket.create_connection(
                    (self.host, self.port),
                    timeout=self.config.connect_timeout_s) as conn:
                conn.sendall((json.dumps(payload) + "\n").encode("utf-8"))
                reply = json.loads(
                    conn.makefile("rb").readline().decode("utf-8"))
        except (OSError, ValueError):
            self._on_send_failure()
            return
        with self._lock:
            self._consec_fail = 0
            if isinstance(reply, dict):
                self.peer_dead = frozenset(reply.get("dead", ()))

    def _on_send_failure(self):
        with self._lock:
            self._consec_fail += 1
            crossed = (self._consec_fail >= self.config.miss_threshold
                       and not self._coordinator_lost)
            if crossed:
                self._coordinator_lost = True
            fails = self._consec_fail
        if crossed:
            # the symmetric half of partition tolerance: a worker cut off
            # from the control plane knows it (and can choose to stop
            # training into the void)
            from ...observability import flight as _flight
            _flight.record_event("distributed.coordinator_lost",
                                 {"rank": self.rank, "gen": self.gen,
                                  "consecutive_failures": fails})
            if self._on_coordinator_lost is not None:
                self._on_coordinator_lost()


class HeartbeatPlane:
    """Facade tying the two halves together (the name the docs use).

    ``HeartbeatPlane.coordinator(...)`` / ``HeartbeatPlane.sender(...)``
    construct the respective halves; :func:`maybe_auto_sender` is the
    env-contract entry the training wiring uses.
    """

    coordinator = HeartbeatCoordinator
    sender = BeaconSender


_AUTO_SENDER: list = []


def maybe_auto_sender() -> Optional[BeaconSender]:
    """Process-wide BeaconSender when the cohort supervisor armed the env
    contract (HEARTBEAT_ADDR_VAR), else None. Idempotent."""
    if _AUTO_SENDER:
        return _AUTO_SENDER[0]
    addr = os.environ.get(HEARTBEAT_ADDR_VAR, "")
    if not addr:
        return None
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    sender = BeaconSender(addr, rank).start()
    _AUTO_SENDER.append(sender)
    return sender


def _reset_auto_sender_for_tests():
    while _AUTO_SENDER:
        _AUTO_SENDER.pop().stop()
