"""Multi-host elastic fault-tolerance control plane (docs/fault_tolerance.md,
"Surviving host loss").

Three layers turn peer death from an indefinite stall into bounded-time
recovery:

* :mod:`~paddle_tpu.distributed.elastic_runtime.heartbeat` — the
  out-of-band health plane: per-host TCP beacons, missed-beat death
  declaration, straggler z-scores, labeled ``/metricsz`` gauges.
* :mod:`~paddle_tpu.distributed.elastic_runtime.watchdog` — the
  in-process collective watchdog: a deadline thread around every guarded
  train step that converts a hung collective into exit
  :data:`~paddle_tpu.distributed.elastic.HOST_LOST_EXIT_CODE` (121).
* :mod:`~paddle_tpu.distributed.elastic_runtime.cohort` — the supervisor:
  on exit-121 or a declared death, tear down, bump the cohort generation,
  re-form the world (spare host / shrink-to-fit), restore from the newest
  committed multi-host checkpoint.
"""
from ..elastic import HOST_LOST_EXIT_CODE  # noqa: F401
from .heartbeat import (  # noqa: F401
    COHORT_GEN_VAR, HEARTBEAT_ADDR_VAR, BeaconSender, HeartbeatConfig,
    HeartbeatCoordinator, HeartbeatPlane, cohort_generation,
    maybe_auto_sender,
)
from .watchdog import (  # noqa: F401
    STEP_DEADLINE_VAR, StepWatchdog, maybe_auto_watchdog,
)
from .cohort import CohortSupervisor  # noqa: F401
