"""Process/device environment for distributed training.

TPU-native replacement for the reference's env-var handshake + NCCL bootstrap
(reference: python/paddle/distributed/parallel.py:60 init_parallel_env →
imperative/nccl_context.cc:53 NCCLParallelContext::Init — TCP-broadcast of
ncclUniqueId + ncclCommInitRank; platform/gen_comm_id_helper.cc).

On TPU the transport is XLA's ICI/DCN: `jax.distributed.initialize`
(coordinator address ≈ PADDLE_TRAINER_ENDPOINTS[0]) wires every host into one
global runtime; there are no ring ids or comm streams to manage. The
reference's env contract (PADDLE_TRAINER_ID, PADDLE_TRAINERS_NUM,
PADDLE_TRAINER_ENDPOINTS) is honored so launcher scripts port unchanged.
"""
from __future__ import annotations

import os
from typing import Optional

import jax


# paddle_tpu/__init__ performs the pre-backend bootstrap and leaves this
# sentinel (see there); pick it up so init_parallel_env is a no-op after it
_INITIALIZED = [bool(os.environ.get("_PADDLE_TPU_DIST_INITIALIZED"))]


class ParallelEnv:
    """reference: fluid/dygraph/parallel.py:70 ParallelEnv."""

    def __init__(self):
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self._endpoints = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
        self._current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
        self._device_id = int(os.environ.get("FLAGS_selected_devices",
                                             os.environ.get("FLAGS_selected_gpus", "0"))
                              .split(",")[0] or 0)

    @property
    def rank(self):
        if _INITIALIZED[0]:
            return jax.process_index()
        return self._rank

    local_rank = rank

    @property
    def world_size(self):
        if _INITIALIZED[0]:
            return jax.process_count()
        return self._world_size

    nranks = world_size

    @property
    def device_id(self):
        return self._device_id

    @property
    def dev_id(self):
        return self._device_id

    @property
    def current_endpoint(self):
        return self._current_endpoint

    @property
    def trainer_endpoints(self):
        return self._endpoints


def _initialize_distributed_with_retry(coordinator, num_processes,
                                       process_id):
    """``jax.distributed.initialize`` with backoff — workers racing the
    coordinator at job start must wait for it, not fail fast. Total budget
    from PADDLE_TPU_INIT_TIMEOUT (seconds, default 300)."""
    from ..utils.resilience import Deadline, RetryError, retry_call

    deadline = Deadline.from_env("PADDLE_TPU_INIT_TIMEOUT", 300.0)

    def _attempt():
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id)

    try:
        retry_call(_attempt, max_attempts=1000, backoff=1.0, max_backoff=15.0,
                   deadline=deadline)
    except RetryError as e:
        raise RuntimeError(
            f"jax.distributed.initialize(coordinator={coordinator}, "
            f"num_processes={num_processes}, process_id={process_id}) did "
            f"not come up within PADDLE_TPU_INIT_TIMEOUT="
            f"{deadline.seconds}s") from (e.__cause__ or e)


def init_parallel_env():
    """reference: distributed/parallel.py:60. Multi-host: initialize the JAX
    distributed runtime from the PADDLE_* env contract (normally already
    done by the pre-backend bootstrap in paddle_tpu/__init__ — jax requires
    initialize() before the first backend touch, the same
    before-any-kernel constraint as the reference's
    NCCLParallelContext::Init, nccl_context.cc:53). Single-host: no-op."""
    env = ParallelEnv()
    if _INITIALIZED[0]:
        return env
    if env._world_size > 1:
        coordinator = env._endpoints[0] if env._endpoints[0] else None
        _initialize_distributed_with_retry(
            coordinator, env._world_size, env._rank)
    _INITIALIZED[0] = True
    return env


def get_rank(group=None):
    if _INITIALIZED[0] or int(os.environ.get("PADDLE_TRAINERS_NUM", "1")) > 1:
        return ParallelEnv().rank
    return 0


def get_world_size(group=None):
    if _INITIALIZED[0] or int(os.environ.get("PADDLE_TRAINERS_NUM", "1")) > 1:
        return ParallelEnv().world_size
    return 1


def is_initialized():
    return _INITIALIZED[0]


def device_count():
    return len(jax.devices())
