"""Process/device environment for distributed training.

TPU-native replacement for the reference's env-var handshake + NCCL bootstrap
(reference: python/paddle/distributed/parallel.py:60 init_parallel_env →
imperative/nccl_context.cc:53 NCCLParallelContext::Init — TCP-broadcast of
ncclUniqueId + ncclCommInitRank; platform/gen_comm_id_helper.cc).

On TPU the transport is XLA's ICI/DCN: `jax.distributed.initialize`
(coordinator address ≈ PADDLE_TRAINER_ENDPOINTS[0]) wires every host into one
global runtime; there are no ring ids or comm streams to manage. The
reference's env contract (PADDLE_TRAINER_ID, PADDLE_TRAINERS_NUM,
PADDLE_TRAINER_ENDPOINTS) is honored so launcher scripts port unchanged.
"""
from __future__ import annotations

import logging
import os
from typing import Optional

import jax

_LOG = logging.getLogger(__name__)

# paddle_tpu/__init__ performs the pre-backend bootstrap (by calling
# bootstrap_pre_backend below on a standalone load of this module) and
# leaves this sentinel; pick it up so init_parallel_env is a no-op after it
_INITIALIZED = [bool(os.environ.get("_PADDLE_TPU_DIST_INITIALIZED"))]


class ParallelEnv:
    """reference: fluid/dygraph/parallel.py:70 ParallelEnv."""

    def __init__(self):
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self._endpoints = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
        self._current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
        self._device_id = int(os.environ.get("FLAGS_selected_devices",
                                             os.environ.get("FLAGS_selected_gpus", "0"))
                              .split(",")[0] or 0)

    @property
    def rank(self):
        if _INITIALIZED[0]:
            return jax.process_index()
        return self._rank

    local_rank = rank

    @property
    def world_size(self):
        if _INITIALIZED[0]:
            return jax.process_count()
        return self._world_size

    nranks = world_size

    @property
    def device_id(self):
        return self._device_id

    @property
    def dev_id(self):
        return self._device_id

    @property
    def current_endpoint(self):
        return self._current_endpoint

    @property
    def trainer_endpoints(self):
        return self._endpoints


def _resilience():
    """``paddle_tpu.utils.resilience`` WITHOUT importing the
    ``paddle_tpu.utils`` package — its ``__init__`` pulls vision/nn, which
    run backend-touching computations at import, and this module's callers
    include the pre-backend bootstrap where the backend must not exist yet.
    resilience.py itself is stdlib-only, so load it standalone under its
    canonical dotted name; the later package import finds this sys.modules
    entry and reuses it (one module object, one FaultInjector singleton)."""
    import sys
    name = "paddle_tpu.utils.resilience"
    mod = sys.modules.get(name)
    if mod is None:
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            name, os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "utils", "resilience.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
    return mod


def _initialize_distributed_with_retry(coordinator, num_processes,
                                       process_id):
    """``jax.distributed.initialize`` with backoff — workers racing the
    coordinator at job start must wait for it, not fail fast. Total budget
    from PADDLE_TPU_INIT_TIMEOUT (seconds, default 300); each retry logs
    the attempt count and coordinator address so a wedged bootstrap is
    diagnosable from the worker log alone."""
    res = _resilience()
    Deadline, RetryError, retry_call = (res.Deadline, res.RetryError,
                                        res.retry_call)

    deadline = Deadline.from_env("PADDLE_TPU_INIT_TIMEOUT", 300.0)

    def _attempt():
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id)

    def _log_retry(attempt, exc, pause):
        _LOG.warning(
            "jax.distributed.initialize attempt %d against coordinator %s "
            "failed (%s); retrying in %.1fs "
            "(budget PADDLE_TPU_INIT_TIMEOUT=%ss)",
            attempt, coordinator, exc, pause, deadline.seconds)

    try:
        retry_call(_attempt, max_attempts=1000, backoff=1.0, max_backoff=15.0,
                   deadline=deadline, on_retry=_log_retry)
    except RetryError as e:
        raise RuntimeError(
            f"jax.distributed.initialize(coordinator={coordinator}, "
            f"num_processes={num_processes}, process_id={process_id}) did "
            f"not come up within PADDLE_TPU_INIT_TIMEOUT="
            f"{deadline.seconds}s") from (e.__cause__ or e)


def bootstrap_pre_backend():
    """The guarded multi-host bootstrap, shared by ``paddle_tpu/__init__``
    and :func:`init_parallel_env` — the single home of the initialize-retry
    loop. Under a launcher (PADDLE_TRAINERS_NUM > 1, sentinel unset) brings
    up the JAX distributed runtime against coordinator
    ``PADDLE_TRAINER_ENDPOINTS[0]`` with retry/backoff; no-op otherwise.

    ``paddle_tpu/__init__`` calls this on a *standalone* importlib load of
    this module (registered under the canonical ``paddle_tpu.distributed.env``
    name, so the package import later reuses it) because importing the
    ``paddle_tpu.distributed`` package pulls in backend-touching modules,
    and jax requires initialize() before the first backend touch — the same
    before-any-kernel constraint as the reference's
    NCCLParallelContext::Init (nccl_context.cc:53).
    """
    if _INITIALIZED[0] or os.environ.get("_PADDLE_TPU_DIST_INITIALIZED"):
        _INITIALIZED[0] = True
        return
    if int(os.environ.get("PADDLE_TRAINERS_NUM", "1")) <= 1:
        return
    endpoints = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
    coordinator = (endpoints[0] or None) if endpoints else None
    num_processes = int(os.environ["PADDLE_TRAINERS_NUM"])
    process_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    try:
        # the CPU backend refuses multiprocess computations unless a CPU
        # collectives transport is selected, and the choice must land
        # before initialize(); TPU/GPU runs are unaffected (their
        # collectives ride ICI/NCCL, and any CPU-backend side computation
        # gets a working transport instead of INVALID_ARGUMENT)
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):  # older jax: flag absent
        pass
    _initialize_distributed_with_retry(coordinator, num_processes, process_id)
    _LOG.info(
        "jax.distributed initialized: coordinator=%s process_id=%d "
        "num_processes=%d cohort_generation=%s",
        coordinator, process_id, num_processes,
        os.environ.get("PADDLE_TPU_COHORT_GEN", "0"))
    # env-var sentinel (not just module state): a re-exec or a second load
    # of this module in the same process must see the runtime as up
    os.environ["_PADDLE_TPU_DIST_INITIALIZED"] = "1"
    _INITIALIZED[0] = True


def init_parallel_env():
    """reference: distributed/parallel.py:60. Multi-host: initialize the JAX
    distributed runtime from the PADDLE_* env contract (normally already
    done by the pre-backend bootstrap in paddle_tpu/__init__, which routes
    through the same :func:`bootstrap_pre_backend`). Single-host: no-op."""
    bootstrap_pre_backend()
    _INITIALIZED[0] = True
    return ParallelEnv()


def get_rank(group=None):
    if _INITIALIZED[0] or int(os.environ.get("PADDLE_TRAINERS_NUM", "1")) > 1:
        return ParallelEnv().rank
    return 0


def get_world_size(group=None):
    if _INITIALIZED[0] or int(os.environ.get("PADDLE_TRAINERS_NUM", "1")) > 1:
        return ParallelEnv().world_size
    return 1


def is_initialized():
    return _INITIALIZED[0]


def device_count():
    return len(jax.devices())
