"""Process/device environment for distributed training.

TPU-native replacement for the reference's env-var handshake + NCCL bootstrap
(reference: python/paddle/distributed/parallel.py:60 init_parallel_env →
imperative/nccl_context.cc:53 NCCLParallelContext::Init — TCP-broadcast of
ncclUniqueId + ncclCommInitRank; platform/gen_comm_id_helper.cc).

On TPU the transport is XLA's ICI/DCN: `jax.distributed.initialize`
(coordinator address ≈ PADDLE_TRAINER_ENDPOINTS[0]) wires every host into one
global runtime; there are no ring ids or comm streams to manage. The
reference's env contract (PADDLE_TRAINER_ID, PADDLE_TRAINERS_NUM,
PADDLE_TRAINER_ENDPOINTS) is honored so launcher scripts port unchanged.
"""
from __future__ import annotations

import os
from typing import Optional

import jax


# paddle_tpu/__init__ performs the pre-backend bootstrap and leaves this
# sentinel (see there); pick it up so init_parallel_env is a no-op after it
_INITIALIZED = [bool(os.environ.get("_PADDLE_TPU_DIST_INITIALIZED"))]


class ParallelEnv:
    """reference: fluid/dygraph/parallel.py:70 ParallelEnv."""

    def __init__(self):
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self._endpoints = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
        self._current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
        self._device_id = int(os.environ.get("FLAGS_selected_devices",
                                             os.environ.get("FLAGS_selected_gpus", "0"))
                              .split(",")[0] or 0)

    @property
    def rank(self):
        if _INITIALIZED[0]:
            return jax.process_index()
        return self._rank

    local_rank = rank

    @property
    def world_size(self):
        if _INITIALIZED[0]:
            return jax.process_count()
        return self._world_size

    nranks = world_size

    @property
    def device_id(self):
        return self._device_id

    @property
    def dev_id(self):
        return self._device_id

    @property
    def current_endpoint(self):
        return self._current_endpoint

    @property
    def trainer_endpoints(self):
        return self._endpoints


def init_parallel_env():
    """reference: distributed/parallel.py:60. Multi-host: initialize the JAX
    distributed runtime from the PADDLE_* env contract (normally already
    done by the pre-backend bootstrap in paddle_tpu/__init__ — jax requires
    initialize() before the first backend touch, the same
    before-any-kernel constraint as the reference's
    NCCLParallelContext::Init, nccl_context.cc:53). Single-host: no-op."""
    env = ParallelEnv()
    if _INITIALIZED[0]:
        return env
    if env._world_size > 1:
        coordinator = env._endpoints[0] if env._endpoints[0] else None
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=env._world_size,
            process_id=env._rank)
    _INITIALIZED[0] = True
    return env


def get_rank(group=None):
    if _INITIALIZED[0] or int(os.environ.get("PADDLE_TRAINERS_NUM", "1")) > 1:
        return ParallelEnv().rank
    return 0


def get_world_size(group=None):
    if _INITIALIZED[0] or int(os.environ.get("PADDLE_TRAINERS_NUM", "1")) > 1:
        return ParallelEnv().world_size
    return 1


def is_initialized():
    return _INITIALIZED[0]


def device_count():
    return len(jax.devices())
