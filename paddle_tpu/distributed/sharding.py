"""Parameter/optimizer-state sharding (ZeRO stages) as mesh annotations.

TPU-native equivalent of the reference's sharding meta-optimizer
(reference: python/paddle/distributed/fleet/meta_optimizers/
sharding_optimizer.py:43 — a 1.4k-LoC program rewriter inserting
broadcast/reduce-scatter ops and pruning per-rank weights). Here each ZeRO
stage is a set of PartitionSpecs:

- stage 1 ("os"): optimizer states sharded over the data axis;
- stage 2 ("os_g"): + gradients reduced into the sharded layout
  (XLA turns the grad allreduce into reduce-scatter where the consumer is
  sharded);
- stage 3 ("p_g_os"): + parameters sharded (FSDP — the partitioner inserts
  the all-gathers right before use and frees afterwards).

The dygraph entry point mirrors paddle.distributed.sharding
.group_sharded_parallel (python/paddle/distributed/sharding/group_sharded.py).
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor
from . import mesh as _mesh


def _axis_size(axis, mesh):
    return int(mesh.shape[axis]) if axis in mesh.axis_names else 1


def _spec_for(shape, axis, mesh) -> Optional[P]:
    """Shard dim 0 over ``axis`` when divisible; else replicate."""
    n = _axis_size(axis, mesh)
    if n <= 1 or not shape or shape[0] % n != 0:
        return None
    return P(*((axis,) + (None,) * (len(shape) - 1)))


def shard_optimizer_states(optimizer, mesh=None, axis="dp"):
    """ZeRO-1: every optimizer moment/accumulator is laid out sharded over
    the data axis. The fused update consumes grads where the state lives, so
    XLA lowers grad-allreduce + update into reduce-scatter + local update +
    (lazy) all-gather — the reference's sharding stage-1 comm pattern."""
    m = mesh or _mesh.ensure_mesh()
    orig_init = optimizer._init_state

    def sharded_init(p):
        st = orig_init(p)
        out = {}
        for k, v in st.items():
            spec = _spec_for(v.shape, axis, m)
            out[k] = _mesh.constrain(v, spec, m) if spec is not None else v
        return out

    optimizer._init_state = sharded_init
    # re-shard any states that already exist
    for pid, st in list(optimizer._state.items()):
        for k, v in list(st.items()):
            spec = _spec_for(getattr(v, "shape", ()), axis, m)
            if spec is not None:
                st[k] = _mesh.constrain(v, spec, m)
    return optimizer


def shard_parameters(model, mesh=None, axis="dp"):
    """ZeRO-3/FSDP: parameters live sharded over the data axis; XLA
    all-gathers them at use sites (reference stage-3 prunes per-rank
    weights and broadcasts on demand)."""
    m = mesh or _mesh.ensure_mesh()
    for _, p in model.named_parameters():
        spec = _spec_for(p.shape, axis, m)
        if spec is not None:
            _mesh.shard_tensor(p, spec, m)
    return model


def group_sharded_parallel(model, optimizer, level="os", scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False):
    """reference: python/paddle/distributed/sharding/group_sharded.py
    group_sharded_parallel(level in {"os", "os_g", "p_g_os"})."""
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError(f"unknown sharding level {level!r}")
    if offload:
        raise NotImplementedError(
            "offload=True (host-memory optimizer states) is not supported; "
            "use more data-axis shards instead")
    shard_optimizer_states(optimizer)
    if level == "p_g_os":
        shard_parameters(model)
    return model, optimizer, scaler
