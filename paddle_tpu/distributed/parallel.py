"""Data-parallel training.

TPU-native equivalent of the reference's dygraph DataParallel + C++ Reducer
(reference: python/paddle/fluid/dygraph/parallel.py:380 DataParallel,
paddle/fluid/imperative/reducer.cc:289/:624/:798 — gradient bucketing with
overlapped fused NCCL allreduce).

Design: the Reducer exists because the reference runs one process per GPU and
must merge replica gradients by hand, overlapping comm with the backward
walk. On TPU the same math is expressed as SPMD sharding: the *global* batch
is sharded over the mesh's "dp" axis, parameters are replicated, and XLA
inserts the gradient all-reduce (and overlaps it with compute) when it
partitions the backward pass. So:

- forward: pin inputs to PartitionSpec("dp", ...) and parameters to
  replicated — the entire Reducer machinery (buckets, comm streams, unused
  -variable scan: reducer.cc:527 PrepareForBackward) has no residue.
- ``loss.backward()`` then yields gradients that are already the global
  (sum over shards) gradients of the global-mean loss == the reference's
  allreduce-averaged replica gradients.
- multi-process launches (one process per host) additionally broadcast the
  initial parameters from rank 0 (reference: parallel.py sync_params_buffers)
  and expose ``apply_collective_grads`` as the eager fallback path.
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from ..ops.dispatch import apply
from . import mesh as _mesh
from . import collective as C
from .env import ParallelEnv, init_parallel_env, get_rank, get_world_size


def _dp_axis_size() -> int:
    m = _mesh.get_mesh()
    if m is None or "dp" not in m.axis_names:
        return 1
    return int(m.shape["dp"])


def sync_params_buffers(model: Layer, comm_group=None, src_rank=0,
                        is_model_parallel=False):
    """reference: fluid/dygraph/parallel.py sync_params_buffers — broadcast
    params+buffers from src so every replica starts identical."""
    if jax.process_count() <= 1:
        return
    for _, p in model.named_parameters():
        C.broadcast(p, src_rank, group=comm_group)
    for _, b in model.named_buffers():
        C.broadcast(b, src_rank, group=comm_group)


class DataParallel(Layer):
    """reference: fluid/dygraph/parallel.py:380.

    ``comm_buffer_size``/``last_comm_buffer_size`` are accepted for API
    parity; bucketing is XLA's job here. ``find_unused_parameters`` is
    likewise moot: there is one global computation, so no replica can
    disagree about which parameters were used (the hazard reducer.cc:860
    ProcessUnusedDenseVars guards against)."""

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None, bf16_allreduce=False,
                 compressed_allreduce=False,
                 compressed_allreduce_dtype="int8"):
        super().__init__()
        self._layers = layers
        self._group = group
        # strategy.fp16_allreduce analog (reference: fp16_allreduce_
        # optimizer.py:20 — halve cross-process gradient bytes; bf16 is
        # the TPU-native half-width format)
        self._bf16_allreduce = bool(bf16_allreduce)
        # strategy.compressed_allreduce: block-scaled quantized gradient
        # exchange (collective.compressed_all_reduce, docs/quantization.md)
        if compressed_allreduce_dtype not in ("int8", "bf16"):
            raise ValueError(
                "compressed_allreduce_dtype must be 'int8' or 'bf16', "
                f"got {compressed_allreduce_dtype!r}")
        self._compressed_allreduce = bool(compressed_allreduce)
        self._compressed_dtype = str(compressed_allreduce_dtype)
        self._mesh = _mesh.ensure_mesh()
        self.find_unused_parameters = find_unused_parameters
        # replicate parameters/buffers across the mesh (BCastParamsToDevices,
        # parallel_executor.cc:687) and sync across processes
        for _, p in layers.named_parameters():
            _mesh.replicate_tensor(p, self._mesh)
        for _, b in layers.named_buffers():
            _mesh.replicate_tensor(b, self._mesh)
        sync_params_buffers(layers, comm_group=group)

    def _shard_input(self, x):
        if not isinstance(x, Tensor) or x.ndim == 0:
            return x
        n = _dp_axis_size()
        if n <= 1 or x.shape[0] % n != 0:
            return x
        spec = P(*(("dp",) + (None,) * (x.ndim - 1)))
        return apply("shard_batch",
                     lambda r: _mesh.constrain(r, spec, self._mesh), x)

    def forward(self, *inputs, **kwargs):
        inputs = tuple(self._shard_input(x) for x in inputs)
        kwargs = {k: self._shard_input(v) for k, v in kwargs.items()}
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        """reference: parallel.py:586 — identity in sync mode (the global
        mean over the sharded batch already carries the 1/nranks)."""
        return loss

    def apply_collective_grads(self):
        """Eager multi-process fallback (reference: parallel.py:595): average
        gradients across processes."""
        if jax.process_count() <= 1:
            return
        for p in self._layers.parameters():
            if p._grad is None:
                continue
            raw = p._grad
            if (self._compressed_allreduce
                    and jnp.issubdtype(raw.dtype, jnp.floating)):
                g = Tensor(raw)
                C.compressed_all_reduce(g, op=C.ReduceOp.AVG,
                                        group=self._group,
                                        wire_dtype=self._compressed_dtype)
                p._grad = g._data
            elif self._bf16_allreduce and raw.dtype == jnp.float32:
                g = Tensor(raw.astype(jnp.bfloat16))
                C.all_reduce(g, op=C.ReduceOp.AVG, group=self._group)
                p._grad = g._data.astype(jnp.float32)
            else:
                g = Tensor(raw)
                C.all_reduce(g, op=C.ReduceOp.AVG, group=self._group)
                p._grad = g._data

    # delegate everything stateful to the wrapped layer
    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    load_dict = set_state_dict

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, *args, **kwargs):
        return self._layers.named_parameters(*args, **kwargs)


def shard_batch(tensor, mesh=None, axis="dp"):
    """Pin a global-batch tensor onto the dp axis (helper for hand-written
    training loops; DataParallel.forward does this automatically)."""
    m = mesh or _mesh.ensure_mesh()
    if axis not in m.axis_names:
        return tensor
    nd = tensor.ndim if isinstance(tensor, Tensor) else np.ndim(tensor)
    spec = P(*((axis,) + (None,) * (nd - 1)))
    return _mesh.shard_tensor(tensor, spec, m)


def build_global_batch(local_np, mesh=None, axis="dp"):
    """Multi-process: assemble each process's local batch into one global
    sharded array (reference analog: each trainer feeds its own shard).
    Single-process: just shard the given array."""
    m = mesh or _mesh.ensure_mesh()
    arr = np.asarray(local_np)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        spec = P(*((axis,) + (None,) * (arr.ndim - 1)))
        global_arr = multihost_utils.host_local_array_to_global_array(
            arr, m, spec)
        return Tensor(global_arr)
    return shard_batch(Tensor(arr), m, axis)
