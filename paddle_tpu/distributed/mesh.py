"""Device-mesh management: the TPU-native replacement for ring ids.

Where the reference keys every communicator by an integer ``ring_id``
(reference: paddle/fluid/platform/collective_helper.h:68 NCCLCommContext —
ring_id → NCCLComm; rings built by c_comm_init ops), the TPU design names
communication *axes* of one global ``jax.sharding.Mesh``. A "ring" becomes a
mesh axis; a hybrid dp×mp×pp topology (reference: fleet/base/topology.py:111
HybridCommunicateGroup) becomes a 3-axis mesh, and every collective rides the
ICI links of its axis — XLA plans the routing, no ring bookkeeping.

A process-global default mesh is kept here; ``init_parallel_env`` installs a
1-D "dp" mesh over all visible devices, ``fleet.init`` with a hybrid strategy
installs a multi-axis one.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_GLOBAL_MESH: list = [None]

# canonical axis order for hybrid parallelism (reference topology order
# fleet/base/topology.py hybrid_configs: dp, pp, sharding, mp — here:
# dp outermost/DCN-most, then pp, then sp, then mp innermost/ICI-most so
# tensor-parallel collectives ride the fastest links)
HYBRID_AXES = ("dp", "pp", "sharding", "sp", "mp")


def build_mesh(axes: Optional[Dict[str, int]] = None,
               devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh from an {axis_name: size} dict (order preserved).

    ``axes=None`` gives a 1-D data-parallel mesh over all devices — the
    equivalent of the reference's single global NCCL ring (ring_id 0).
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    if not axes:
        axes = {"dp": len(devs)}
    names = tuple(axes.keys())
    sizes = tuple(int(s) for s in axes.values())
    total = int(np.prod(sizes))
    if total != len(devs):
        raise ValueError(
            f"mesh {dict(axes)} needs {total} devices, have {len(devs)}")
    return Mesh(np.array(devs).reshape(sizes), names)


def set_mesh(mesh: Optional[Mesh]):
    _GLOBAL_MESH[0] = mesh


def get_mesh() -> Optional[Mesh]:
    return _GLOBAL_MESH[0]


def ensure_mesh() -> Mesh:
    """Return the global mesh, creating the default 1-D dp mesh on first use."""
    if _GLOBAL_MESH[0] is None:
        _GLOBAL_MESH[0] = build_mesh()
    return _GLOBAL_MESH[0]


def mesh_axis_size(axis, mesh: Optional[Mesh] = None) -> int:
    m = mesh or get_mesh()
    if m is None:
        return 1
    if isinstance(axis, (tuple, list)):
        return int(np.prod([m.shape[a] for a in axis]))  # noqa: PTA001 -- mesh axis sizes are host python ints (trace-time constants)
    return int(m.shape[axis])  # noqa: PTA001 -- mesh axis sizes are host python ints (trace-time constants)


def sharding_for(spec: PartitionSpec, mesh: Optional[Mesh] = None):
    return NamedSharding(mesh or ensure_mesh(), spec)


def constrain(raw, spec: PartitionSpec, mesh: Optional[Mesh] = None):
    """Attach a sharding to a raw array: ``with_sharding_constraint`` under a
    trace, ``device_put`` (a real reshard) in eager mode. This is the analog
    of the reference inserting c_split/c_identity ops around TP blocks."""
    sh = sharding_for(spec, mesh)
    if isinstance(raw, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(raw, sh)
    return jax.device_put(raw, sh)


def shard_tensor(tensor, spec: PartitionSpec, mesh: Optional[Mesh] = None):
    """Reshard a Tensor in place onto ``spec`` (eager) and remember the spec
    so jitted paths can re-apply it."""
    from ..core.tensor import Tensor
    if isinstance(tensor, Tensor):
        tensor._data = constrain(tensor._data, spec, mesh)
        tensor._sharding_spec = spec
        tensor.is_distributed = True
        return tensor
    return constrain(tensor, spec, mesh)


def replicate_tensor(tensor, mesh: Optional[Mesh] = None):
    return shard_tensor(tensor, PartitionSpec(), mesh)
