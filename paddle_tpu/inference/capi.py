"""Build helper for the C inference API (csrc/capi_shim.cpp).

The reference ships a prebuilt C library (inference/capi_exp); here the
shim builds on first use with the system toolchain, like the shm ring
(core/shm_ring.py). ``build_capi()`` returns the path to
``libpaddle_tpu_capi.so`` (and the header lives at csrc/paddle_tpu_capi.h
for callers to #include).
"""
from __future__ import annotations

import os
import subprocess
import sysconfig

_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "csrc")


def _python_link_flags():
    inc = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR") or "/usr/local/lib"
    ver = sysconfig.get_config_var("LDVERSION") or sysconfig.get_config_var(
        "VERSION")
    return [f"-I{inc}", f"-L{libdir}", f"-lpython{ver}",
            f"-Wl,-rpath,{libdir}"]


def build_capi(build_dir: str | None = None) -> str:
    """Compile (if stale) and return the path of libpaddle_tpu_capi.so."""
    build_dir = build_dir or os.path.join(_CSRC, "build")
    os.makedirs(build_dir, exist_ok=True)
    src = os.path.join(_CSRC, "capi_shim.cpp")
    out = os.path.join(build_dir, "libpaddle_tpu_capi.so")
    if (os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(src)):
        return out
    cmd = (["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
            f"-I{_CSRC}", "-o", out, src] + _python_link_flags())
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    return out


def header_path() -> str:
    return os.path.join(_CSRC, "paddle_tpu_capi.h")
