"""paddle.inference: standalone predictor over exported artifacts.

Reference: paddle/fluid/inference/api/analysis_predictor.h:82
(AnalysisPredictor: Config → create_predictor → input handles →
ZeroCopyRun :165) and paddle_infer Python API.

TPU design: the deployable artifact is the serialized StableHLO program
jit.save writes (*.pdmodel = jax.export payload, *.pdiparams = pickled
params) — the predictor deserializes and executes it WITHOUT the model's
Python code, the role AnalysisPredictor's ProgramDesc loading served. The
analysis pass pipeline (fusions, TRT subgraphs) has no equivalent here by
design: XLA compiles the whole program at load.
"""
from __future__ import annotations

import os
import pickle
from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp


class Config:
    """reference: paddle_infer.Config (api/paddle_analysis_config.h)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self._prefix = prog_file
        self._params_file = params_file
        self._enable_memory_optim = True

    def set_prog_file(self, path):
        self._prefix = path[:-len(".pdmodel")] if path.endswith(".pdmodel") \
            else path

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    # accepted-and-ignored GPU-era knobs (kept for ported deploy scripts)
    def enable_use_gpu(self, *a, **k):
        pass

    def disable_gpu(self):
        pass

    def switch_ir_optim(self, flag=True):
        pass

    def enable_memory_optim(self, flag=True):
        self._enable_memory_optim = flag

    def enable_tensorrt_engine(self, *a, **k):
        raise NotImplementedError(
            "TensorRT subgraphs are CUDA-era; XLA compiles the whole "
            "program on TPU")


class _IOHandle:
    """Zero-copy-style tensor handle (reference: ZeroCopyTensor)."""

    def __init__(self):
        self._value = None

    def copy_from_cpu(self, arr):
        self._value = jnp.asarray(arr)

    def reshape(self, shape):
        if self._value is not None:
            self._value = self._value.reshape(shape)

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def shape(self):
        return list(self._value.shape) if self._value is not None else None


class Predictor:
    """reference: api/analysis_predictor.h:82 (Run :120 / ZeroCopyRun
    :165)."""

    def __init__(self, config: Config):
        prefix = config._prefix
        from jax import export as jax_export
        from ..serving.cache import default_cache
        with open(prefix + ".pdmodel", "rb") as f:
            self._exported = jax_export.deserialize(f.read())
        # compiled-callable cache keyed on (artifact, input shapes/dtypes):
        # batch-size churn stops recompiling — each signature costs one XLA
        # compile, shared across Predictors over the same artifact
        self._model_key = os.path.abspath(prefix)
        self._exec_cache = default_cache()
        with open(config._params_file or prefix + ".pdiparams", "rb") as f:
            blob = pickle.load(f)
        self._params = [jnp.asarray(p) for p in blob["params"]]
        self._n_out = blob.get("n_out")
        # in_avals flattens the params list + the real inputs
        n_in = blob.get("n_in")
        if n_in is None:
            n_in = len(self._exported.in_avals) - len(self._params)
        self._input_names = [f"x{i}" for i in range(max(n_in, 0))]
        self._inputs: Dict[str, _IOHandle] = {
            n: _IOHandle() for n in self._input_names}
        self._outputs: List[_IOHandle] = []

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name) -> _IOHandle:
        return self._inputs[name]

    def get_output_names(self) -> List[str]:
        return [f"out{i}" for i in range(len(self._outputs))]

    def get_output_handle(self, name) -> _IOHandle:
        return self._outputs[int(name.replace("out", ""))]

    def run(self, inputs=None):
        """Either positional (returns numpy list, reference Run) or via the
        input handles (reference ZeroCopyRun)."""
        if inputs is not None:
            xs = [jnp.asarray(a) for a in inputs]
        else:
            xs = [self._inputs[n]._value for n in self._input_names]
        outs = self._call_cached(xs)
        if self._n_out is not None:
            outs = outs[:self._n_out]
        self._outputs = []
        for o in outs:
            h = _IOHandle()
            h._value = o
            self._outputs.append(h)
        return [np.asarray(o) for o in outs]

    def _call_cached(self, xs):
        """Execute through the shape-keyed ExecutableCache: a jax.jit
        wrapper per input signature means one XLA compile per signature
        (shape-polymorphic artifacts re-lower per shape otherwise)."""
        from ..serving.cache import signature_of
        sig = signature_of(xs)
        exported = self._exported

        def _compile():
            return jax.jit(lambda params, *xargs: exported.call(
                params, *xargs))

        fn = self._exec_cache.get_or_compile((self._model_key, sig),
                                             _compile)
        outs = fn(self._params, *xs)
        return list(outs) if isinstance(outs, (list, tuple)) else [outs]


def create_predictor(config: Config) -> Predictor:
    """reference: paddle_infer.create_predictor."""
    return Predictor(config)
