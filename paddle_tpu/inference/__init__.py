"""paddle.inference: standalone predictor over exported artifacts.

Reference: paddle/fluid/inference/api/analysis_predictor.h:82
(AnalysisPredictor: Config → create_predictor → input handles →
ZeroCopyRun :165) and paddle_infer Python API.

TPU design: the deployable artifact is the serialized StableHLO program
jit.save writes (*.pdmodel = jax.export payload, *.pdiparams = pickled
params) — the predictor deserializes and executes it WITHOUT the model's
Python code, the role AnalysisPredictor's ProgramDesc loading served. The
analysis pass pipeline (fusions, TRT subgraphs) has no equivalent here by
design: XLA compiles the whole program at load.
"""
from __future__ import annotations

import os
import pickle
from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp


class Config:
    """reference: paddle_infer.Config (api/paddle_analysis_config.h)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self._prefix = prog_file
        self._params_file = params_file
        self._enable_memory_optim = True
        # "auto": honor a .pdsharding.json sidecar when one exists;
        # None: force replicated; dict: an explicit enable_sharding request
        self._sharding_request = "auto"

    def enable_sharding(self, mesh=None, mesh_axes=None, input_specs=None,
                        param_specs=None, devices=None):
        """Request GSPMD-partitioned execution (the TPU-era analog of the
        multi-device knobs this Config otherwise stubs out).

        Any argument left None is filled from the artifact's
        ``.pdsharding.json`` sidecar at load; an explicit ``mesh`` wins
        over ``mesh_axes`` + ``devices`` (which build a sub-mesh over the
        first ``prod(sizes)`` of ``devices``). Mismatches between the spec
        and the visible devices warn and fall back to replicated — see
        :mod:`paddle_tpu.serving.sharding`."""
        self._sharding_request = {
            "mesh": mesh, "mesh_axes": mesh_axes,
            "input_specs": input_specs, "param_specs": param_specs,
            "devices": devices,
        }
        return self

    def disable_sharding(self):
        """Force replicated single-device execution, ignoring any
        ``.pdsharding.json`` sidecar."""
        self._sharding_request = None
        return self

    def set_prog_file(self, path):
        self._prefix = path[:-len(".pdmodel")] if path.endswith(".pdmodel") \
            else path

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    # accepted-and-ignored GPU-era knobs (kept for ported deploy scripts)
    def enable_use_gpu(self, *a, **k):
        pass

    def disable_gpu(self):
        pass

    def switch_ir_optim(self, flag=True):
        pass

    def enable_memory_optim(self, flag=True):
        self._enable_memory_optim = flag

    def enable_tensorrt_engine(self, *a, **k):
        raise NotImplementedError(
            "TensorRT subgraphs are CUDA-era; XLA compiles the whole "
            "program on TPU")


class _IOHandle:
    """Zero-copy-style tensor handle (reference: ZeroCopyTensor)."""

    def __init__(self):
        self._value = None

    def copy_from_cpu(self, arr):
        self._value = jnp.asarray(arr)

    def reshape(self, shape):
        if self._value is not None:
            self._value = self._value.reshape(shape)

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def shape(self):
        return list(self._value.shape) if self._value is not None else None


class Predictor:
    """reference: api/analysis_predictor.h:82 (Run :120 / ZeroCopyRun
    :165)."""

    def __init__(self, config: Config):
        prefix = config._prefix
        from jax import export as jax_export
        from ..serving.cache import default_cache, persistent_root
        # activate env-configured persistent compilation BEFORE the first
        # compile this predictor triggers, so even parameter-upload utility
        # programs land in (and later load from) the fleet-wide cache
        persistent_root()
        with open(prefix + ".pdmodel", "rb") as f:
            self._exported = jax_export.deserialize(f.read())
        # compiled-callable cache keyed on (artifact, input shapes/dtypes):
        # batch-size churn stops recompiling — each signature costs one XLA
        # compile, shared across Predictors over the same artifact
        self._model_key = os.path.abspath(prefix)
        self._exec_cache = default_cache()
        with open(config._params_file or prefix + ".pdiparams", "rb") as f:
            blob = pickle.load(f)
        self._params = [jnp.asarray(p) for p in blob["params"]]
        self._n_out = blob.get("n_out")
        # in_avals flattens the params list + the real inputs
        n_in = blob.get("n_in")
        if n_in is None:
            n_in = len(self._exported.in_avals) - len(self._params)
        self._input_names = [f"x{i}" for i in range(max(n_in, 0))]
        self._inputs: Dict[str, _IOHandle] = {
            n: _IOHandle() for n in self._input_names}
        self._outputs: List[_IOHandle] = []
        # GSPMD partitioning: resolve the config request / sidecar into
        # per-input + per-param NamedShardings (None -> replicated path)
        self._sharding = self._resolve_sharding(config, prefix,
                                                max(n_in, 0))
        if self._sharding is not None:
            self._params = [jax.device_put(p, s) for p, s in
                            zip(self._params,
                                self._sharding.param_shardings)]

    def _resolve_sharding(self, config: Config, prefix: str, n_in: int):
        """Bind the Config's sharding request (or the artifact sidecar)
        to devices; warns and returns None on any mismatch so the
        predictor falls back to replicated execution."""
        from ..serving import sharding as _sh
        req = getattr(config, "_sharding_request", "auto")
        if req is None:
            return None
        side = _sh.load_sidecar(prefix)
        if req == "auto":
            if side is None:
                return None
            return _sh.resolve(side, n_inputs=n_in,
                               n_params=len(self._params))
        mesh = req.get("mesh")
        mesh_axes = req.get("mesh_axes") or (side.mesh_axes if side
                                             else None)
        if mesh is None and not mesh_axes:
            import warnings
            warnings.warn(
                "enable_sharding() given no mesh/mesh_axes and the "
                "artifact has no sharding sidecar; serving replicated")
            return None
        inputs = req.get("input_specs")
        if inputs is None and side is not None:
            inputs = side.inputs
        params = req.get("param_specs")
        if params is None and side is not None:
            params = side.params
        spec = _sh.ShardingSpec(mesh_axes or {"_explicit_mesh": 1},
                                inputs, params)
        return _sh.resolve(spec, mesh=mesh, devices=req.get("devices"),
                           n_inputs=n_in, n_params=len(self._params))

    @property
    def sharding(self):
        """The active :class:`~paddle_tpu.serving.sharding
        .ResolvedSharding`, or None when running replicated."""
        return self._sharding

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name) -> _IOHandle:
        return self._inputs[name]

    def get_output_names(self) -> List[str]:
        return [f"out{i}" for i in range(len(self._outputs))]

    def get_output_handle(self, name) -> _IOHandle:
        return self._outputs[int(name.replace("out", ""))]

    def run(self, inputs=None):
        """Either positional (returns numpy list, reference Run) or via the
        input handles (reference ZeroCopyRun)."""
        if inputs is not None:
            xs = [jnp.asarray(a) for a in inputs]
        else:
            xs = [self._inputs[n]._value for n in self._input_names]
        outs = self._call_cached(xs)
        if self._n_out is not None:
            outs = outs[:self._n_out]
        self._outputs = []
        for o in outs:
            h = _IOHandle()
            h._value = o
            self._outputs.append(h)
        return [np.asarray(o) for o in outs]

    def _call_cached(self, xs):
        """Execute through the shape-keyed ExecutableCache: one AOT
        XLA compile per input signature (shape-polymorphic artifacts
        re-lower per shape otherwise), AOT so the executable is
        serializable into the persistent tier.

        Sharded predictors commit each input onto its NamedSharding and
        append the sharding token to the cache key — replicas over
        different device subsets share the process-wide default cache, so
        the token (which includes device ids) is what keeps their
        executables, and the unsharded 2-tuple keys, from colliding.

        The key is process-stable (artifact abspath + shape/dtype
        signature + sharding token, no ids), so it doubles as the
        persistent-store key: a restarted process loads the serialized
        executable instead of compiling, and with a warm store a whole
        fleet start performs zero XLA compiles for known signatures."""
        from ..serving.cache import signature_of
        sig = signature_of(xs)
        exported = self._exported

        if self._sharding is None:
            key = (self._model_key, sig)
        else:
            key = (self._model_key, sig, self._sharding.token)
            xs = [jax.device_put(x, s) for x, s in
                  zip(xs, self._sharding.input_shardings)]
        params = self._params

        def _compile():
            return jax.jit(lambda ps, *xargs: exported.call(
                ps, *xargs)).lower(params, *xs).compile()

        fn = self._exec_cache.get_or_compile(key, _compile,
                                             persist_key=repr(key))
        outs = fn(self._params, *xs)
        return list(outs) if isinstance(outs, (list, tuple)) else [outs]


def create_predictor(config: Config) -> Predictor:
    """reference: paddle_infer.create_predictor."""
    return Predictor(config)
