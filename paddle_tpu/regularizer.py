"""Weight regularizers (reference: python/paddle/fluid/regularizer.py —
appended to grads as `grad += coeff * param` ops; here picked up by the fused
optimizer update)."""
from __future__ import annotations


class WeightDecayRegularizer:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self):
        return self._coeff


class L2Decay(WeightDecayRegularizer):
    """reference: fluid/regularizer.py L2DecayRegularizer."""


class L1Decay(WeightDecayRegularizer):
    """reference: fluid/regularizer.py L1DecayRegularizer. The fused update
    applies sign(p)*coeff for L1."""
    _l1 = True


L2DecayRegularizer = L2Decay
L1DecayRegularizer = L1Decay
