"""weight_norm / spectral_norm utilities.

Reference: python/paddle/nn/utils/weight_norm_hook.py — reparameterize a
layer's `weight` as g * v/||v|| via forward-pre-hook.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Parameter
from ..ops import math as _math
from ..ops.dispatch import apply


def _norm_except_dim(w, dim):
    import jax.numpy as jnp

    def impl(a):
        if dim is None or dim == -1:
            return jnp.sqrt(jnp.sum(a * a))
        axes = tuple(i for i in range(a.ndim) if i != dim)
        return jnp.sqrt(jnp.sum(a * a, axis=axes))
    return apply("norm_except_dim", impl, w)


def weight_norm(layer, name="weight", dim=0):
    w = getattr(layer, name)
    g = Parameter(_norm_except_dim(w, dim)._data)
    v = Parameter(w._data)
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)
    del layer._parameters[name]

    def hook(lyr, inputs):
        import jax.numpy as jnp

        def impl(gg, vv):
            if dim is None or dim == -1:
                n = jnp.sqrt(jnp.sum(vv * vv))
                return vv * (gg / jnp.maximum(n, 1e-12))
            axes = tuple(i for i in range(vv.ndim) if i != dim)
            n = jnp.sqrt(jnp.sum(vv * vv, axis=axes, keepdims=True))
            shape = [1] * vv.ndim
            shape[dim] = -1
            return vv * (gg.reshape(shape) / jnp.maximum(n, 1e-12))
        object.__setattr__(lyr, name, apply("weight_norm", impl, g, v))
        return None

    handle = layer.register_forward_pre_hook(hook)
    layer._weight_norm_handle = handle
    return layer


def remove_weight_norm(layer, name="weight"):
    handle = getattr(layer, "_weight_norm_handle", None)
    if handle is not None:
        handle.remove()
    g = layer._parameters.pop(name + "_g")
    v = layer._parameters.pop(name + "_v")
    w = Parameter(v._data)
    layer.add_parameter(name, w)
    return layer


def spectral_norm_fn(layer, name="weight", n_power_iterations=1, eps=1e-12,
                     dim=None):
    """nn.utils.spectral_norm parity via power iteration pre-hook."""
    from .layers_common import SpectralNorm
    w = getattr(layer, name)
    sn = SpectralNorm(w.shape, dim=dim or 0, power_iters=n_power_iterations,
                      eps=eps)
    layer.add_sublayer("_spectral_norm", sn)
    orig = layer._parameters[name]
    layer._parameters[name + "_orig"] = orig
    del layer._parameters[name]

    def hook(lyr, inputs):
        object.__setattr__(lyr, name, sn(orig))
        return None

    layer.register_forward_pre_hook(hook)
    return layer


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Tensor-functional spectral normalisation (reference:
    fluid/layers/nn.py spectral_norm — weight / sigma_max). The
    reference op carries PERSISTENT u/v vectors that converge across
    calls; a pure functional has no state, so this runs a deterministic
    power iteration from a FIXED start (PRNGKey(0)) with
    ``max(power_iters, 20)`` steps — repeated calls are identical and
    accurate to ~1e-3 of true sigma; for the stateful forms use
    layers_common.SpectralNorm (layer) or spectral_norm_fn (hook)."""
    import jax
    import jax.numpy as jnp
    from ..ops.dispatch import apply

    iters = max(int(power_iters), 20)

    def impl(w):
        mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        h, wdim = mat.shape
        u = jax.random.normal(jax.random.PRNGKey(0), (h,), jnp.float32)
        for _ in range(iters):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ (mat @ v)
        return w / sigma
    return apply("spectral_norm", impl, weight)
