"""Gradient clipping.

Reference: python/paddle/fluid/clip.py — ClipGradByValue, ClipGradByNorm,
ClipGradByGlobalNorm (the hybrid-parallel variant clips per mp-group via
psum; here the global-norm sum is one fused computation and, under a mesh,
XLA reduces across shards automatically).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        """Functional form over [(param, grad Tensor)] pairs."""
        params = [p for p, _ in params_grads]
        grads = [g._data if isinstance(g, Tensor) else g for _, g in params_grads]
        clipped = self._clip_raw(params, grads)
        return [(p, Tensor(g)) for (p, _), g in zip(params_grads, clipped)]

    def _clip_raw(self, params, grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _clip_raw(self, params, grads):
        return [jnp.clip(g, self.min, self.max) if _clips(p) else g
                for p, g in zip(params, grads)]


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _clip_raw(self, params, grads):
        out = []
        for p, g in zip(params, grads):
            if not _clips(p):
                out.append(g)
                continue
            n = jnp.sqrt(jnp.sum(g.astype(jnp.float32) ** 2))
            scale = jnp.where(n > self.clip_norm, self.clip_norm / n, 1.0)
            out.append((g * scale.astype(g.dtype)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """reference: fluid/clip.py ClipGradByGlobalNorm — one global norm over
    all grads, scale all by clip/max(global, clip)."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _clip_raw(self, params, grads):
        sq = [jnp.sum(g.astype(jnp.float32) ** 2)
              for p, g in zip(params, grads) if _clips(p)]
        if not sq:
            return grads
        global_norm = jnp.sqrt(sum(sq))
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        return [g * scale.astype(g.dtype) if _clips(p) else g
                for p, g in zip(params, grads)]


def _clips(p):
    return getattr(p, "need_clip", True)


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    if not isinstance(parameters, (list, tuple)):
        parameters = [parameters]
    grads = [p._grad for p in parameters if p._grad is not None]
    if not grads:
        return Tensor(jnp.asarray(0.0))
    if norm_type == float("inf"):
        total = jnp.max(jnp.asarray([jnp.max(jnp.abs(g)) for g in grads]))
    else:
        total = jnp.power(sum(jnp.sum(jnp.abs(g.astype(jnp.float32)) ** norm_type)
                              for g in grads), 1.0 / norm_type)
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in parameters:
        if p._grad is not None:
            p._grad = p._grad * scale.astype(p._grad.dtype)
    return Tensor(total)


class GradientClipByValue(ClipGradByValue):
    pass


class GradientClipByNorm(ClipGradByNorm):
    pass


class GradientClipByGlobalNorm(ClipGradByGlobalNorm):
    pass
