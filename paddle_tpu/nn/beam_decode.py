"""BeamSearchDecoder + dynamic_decode (reference: nn/layer/rnn.py
BeamSearchDecoder :1103, dynamic_decode :1565 — there a While-op loop
over TensorArrays; here a static-unrolled loop over fixed-shape beam
state, backtraced with the gather_tree op).

Decoding state is fully fixed-shape: log-probs [B, K], finished mask
[B, K], per-step (token, parent) records stacked to [T, B, K] and
backtraced by ops.gather_tree at the end — no dynamic growth anywhere,
so the whole decode jits."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["BeamSearchDecoder", "dynamic_decode"]


class BeamSearchDecoder:
    """Wraps a cell into a beam-search step function.

    ``embedding_fn`` maps token ids [B*K] -> cell inputs; ``output_fn``
    maps cell outputs -> vocab logits (reference argument names kept).
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn


def dynamic_decode(decoder: BeamSearchDecoder, inits=None,
                   max_step_num=32, output_time_major=False, **kwargs):
    """Run beam-search decoding for ``max_step_num`` steps.

    ``inits``: the cell's initial states for a batch of size B (each
    [B, ...]); they are tiled to the beam internally. Returns
    ``(predicted_ids [B, T, K], final_scores [B, K])`` — column k of
    predicted_ids is the k-th best full sequence (backtraced through the
    beam parents with the gather_tree op), final_scores its accumulated
    log-probability. Early-exits nothing: T == max_step_num always
    (fixed shapes); finished beams keep emitting end_token with score
    frozen, matching the reference's padding convention.
    """
    if kwargs:
        raise TypeError(
            f"dynamic_decode: unsupported keyword(s) {sorted(kwargs)}; "
            f"supported: inits, max_step_num, output_time_major "
            f"(impute_finished/return_length from the reference are not "
            f"implemented — lengths are derivable from end_token "
            f"positions in the fixed-shape output)")
    cell = decoder.cell
    K = decoder.beam_size
    end = decoder.end_token

    # infer B from the initial state
    states = inits
    leaves, td = jax.tree_util.tree_flatten(
        states, is_leaf=lambda t: isinstance(t, Tensor))
    if not leaves:
        raise ValueError("dynamic_decode needs initial cell states "
                         "(inits) to size the batch")
    B = int(leaves[0].shape[0])

    def tile(t):
        raw = t._data if isinstance(t, Tensor) else jnp.asarray(t)
        return Tensor(jnp.repeat(raw, K, axis=0))     # [B*K, ...]
    leaves = [tile(t) for t in leaves]
    states = jax.tree_util.tree_unflatten(td, leaves)

    neg = -1e9
    log_probs = jnp.zeros((B, K), jnp.float32).at[:, 1:].set(neg)
    finished = jnp.zeros((B, K), jnp.bool_)
    last_ids = jnp.full((B * K,), decoder.start_token, jnp.int32)
    step_ids, step_parents = [], []

    for _ in range(int(max_step_num)):
        inp = Tensor(last_ids)
        if decoder.embedding_fn is not None:
            inp = decoder.embedding_fn(inp)
        out, new_states = cell(inp, states)
        logits = decoder.output_fn(out) if decoder.output_fn else out
        lraw = logits._data if isinstance(logits, Tensor) else logits
        V = lraw.shape[-1]
        logp = jax.nn.log_softmax(lraw.astype(jnp.float32), axis=-1)
        logp = logp.reshape(B, K, V)
        # finished beams: only end_token continues, at zero cost
        fmask = jnp.full((V,), neg).at[end].set(0.0)
        logp = jnp.where(finished[..., None], fmask[None, None, :], logp)
        scores = (log_probs[..., None] + logp).reshape(B, K * V)
        top_scores, top_idx = jax.lax.top_k(scores, K)      # [B, K]
        parent = (top_idx // V).astype(jnp.int32)
        token = (top_idx % V).astype(jnp.int32)
        log_probs = top_scores
        finished = jnp.take_along_axis(finished, parent, axis=1) | (
            token == end)
        # reorder every cell state by the chosen parents
        gather = (jnp.arange(B)[:, None] * K + parent).reshape(-1)
        new_leaves, ntd = jax.tree_util.tree_flatten(
            new_states, is_leaf=lambda t: isinstance(t, Tensor))
        new_leaves = [Tensor(jnp.take(
            (t._data if isinstance(t, Tensor) else jnp.asarray(t)),
            gather, axis=0)) for t in new_leaves]
        states = jax.tree_util.tree_unflatten(ntd, new_leaves)
        last_ids = token.reshape(-1)
        step_ids.append(token)
        step_parents.append(parent)

    from ..ops.beam import gather_tree
    ids_t = jnp.stack(step_ids)                      # [T, B, K]
    parents_t = jnp.stack(step_parents)
    seqs = gather_tree(Tensor(ids_t), Tensor(parents_t))
    sraw = seqs._data if isinstance(seqs, Tensor) else jnp.asarray(seqs)
    predicted = sraw if output_time_major else jnp.transpose(
        sraw, (1, 0, 2))                             # [T,B,K] / [B,T,K]
    return Tensor(predicted), Tensor(log_probs)
