"""Activation + loss layers (class forms).

Reference: python/paddle/nn/layer/activation.py, layer/loss.py.
"""
from __future__ import annotations

from .layer_base import Layer
from . import functional as F
from . import initializer as I


class ReLU(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.relu(x)


class ReLU6(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.relu6(x)


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self._approximate = approximate

    def forward(self, x):
        return F.gelu(x, self._approximate)


class Sigmoid(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.sigmoid(x)


class Tanh(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.tanh(x)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, self._axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.log_softmax(x, self._axis)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self._slope)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.elu(x, self._alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
        super().__init__()
        self._scale, self._alpha = scale, alpha

    def forward(self, x):
        return F.selu(x, self._scale, self._alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.celu(x, self._alpha)


class Silu(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.silu(x)


class Swish(Silu):
    pass


class Mish(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.mish(x)


class Hardswish(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.hardswish(x)


class Hardsigmoid(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.hardsigmoid(x)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self._min, self._max = min, max

    def forward(self, x):
        return F.hardtanh(x, self._min, self._max)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._t = threshold

    def forward(self, x):
        return F.hardshrink(x, self._t)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._t = threshold

    def forward(self, x):
        return F.softshrink(x, self._t)


class Tanhshrink(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.tanhshrink(x)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0, name=None):
        super().__init__()
        self._beta, self._threshold = beta, threshold

    def forward(self, x):
        return F.softplus(x, self._beta, self._threshold)


class Softsign(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.softsign(x)


class LogSigmoid(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.log_sigmoid(x)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, name=None):
        super().__init__()
        self._t = threshold

    def forward(self, x):
        return F.thresholded_relu(x, self._t)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self._groups, self._axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self._groups, self._axis)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


class RReLU(Layer):
    def __init__(self, lower=0.125, upper=0.3333333, name=None):
        super().__init__()
        self._lower, self._upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self._lower, self._upper, self.training)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.glu(x, self._axis)


# -- loss layers -------------------------------------------------------------

class CrossEntropyLoss(Layer):
    """reference: python/paddle/nn/layer/loss.py CrossEntropyLoss."""

    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True, name=None):
        super().__init__()
        self._kw = dict(weight=weight, ignore_index=ignore_index,
                        reduction=reduction, soft_label=soft_label, axis=axis,
                        use_softmax=use_softmax)

    def forward(self, input, label):
        return F.cross_entropy(input, label, **self._kw)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self._reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self._reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", name=None):
        super().__init__()
        self._kw = dict(weight=weight, ignore_index=ignore_index,
                        reduction=reduction)

    def forward(self, input, label):
        return F.nll_loss(input, label, **self._kw)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self._weight, self._reduction = weight, reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self._weight, self._reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None, name=None):
        super().__init__()
        self._kw = dict(weight=weight, reduction=reduction, pos_weight=pos_weight)

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, **self._kw)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.kl_div(input, label, self._reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self._reduction, self._delta = reduction, delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self._reduction, self._delta)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self._margin, self._reduction = margin, reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self._margin,
                                     self._reduction)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self._blank, self._reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self._blank, self._reduction, norm_by_times)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self._margin, self._reduction = margin, reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self._margin,
                                       self._reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self._kw = dict(margin=margin, p=p, epsilon=epsilon, swap=swap,
                        reduction=reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, **self._kw)


class HSigmoidLoss(Layer):
    """reference: nn/layer/loss.py HSigmoidLoss — layer wrapper over
    F.hsigmoid_loss holding the tree weight/bias parameters."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if num_classes < 2:
            raise ValueError("num_classes must be >= 2")
        self.num_classes = num_classes
        self.is_custom = is_custom
        self.is_sparse = is_sparse
        C = num_classes
        self.weight = self.create_parameter([C - 1, feature_size],
                                            weight_attr)
        self.bias = (None if bias_attr is False else
                     self.create_parameter([C - 1, 1], bias_attr,
                                           is_bias=True))

    def forward(self, input, label, path_table=None, path_code=None):
        from .functional.sampled import hsigmoid_loss
        if self.is_custom and (path_table is None or path_code is None):
            raise ValueError("is_custom=True needs path_table/path_code")
        return hsigmoid_loss(input, label, self.num_classes, self.weight,
                             self.bias, path_table, path_code,
                             self.is_sparse)


class PairwiseDistance(Layer):
    """reference: nn/layer/distance.py PairwiseDistance — p-norm of
    x - y along the last dim."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        from ..ops import p_norm
        return p_norm(x - y + self.epsilon, p=self.p, axis=-1,
                      keepdim=self.keepdim)
