"""Layer: the module base class.

TPU-native equivalent of the reference dygraph Layer
(reference: python/paddle/fluid/dygraph/layers.py:875 `Layer.__call__` with
pre/post forward hooks; create_parameter, sublayers/named_* walkers,
state_dict/set_state_dict, train/eval, apply, to_static_state).

Parameters are mutable Tensor holders, so a Layer works in both eager mode
(ops see current values) and traced mode (jit.to_static swaps tracers in —
see paddle_tpu/jit). Buffers mirror register_buffer semantics
(layers.py register_buffer).
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..core.tensor import Tensor, Parameter
from ..core import dtypes as _dt
from . import initializer as I


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class ParamAttr:
    """reference: python/paddle/fluid/param_attr.py ParamAttr."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, I.Initializer):
            return ParamAttr(initializer=attr)
        if attr is False:
            return False
        return ParamAttr()


class Layer:
    _global_counter: Dict[str, int] = collections.defaultdict(int)

    def __init__(self, name_scope=None, dtype="float32"):
        cls = type(self).__name__.lower()
        Layer._global_counter[cls] += 1
        self._full_name = name_scope or f"{cls}_{Layer._global_counter[cls] - 1}"
        self._dtype = _dt.convert_dtype(dtype)
        self._parameters: Dict[str, Optional[Parameter]] = collections.OrderedDict()
        self._sub_layers: Dict[str, Optional["Layer"]] = collections.OrderedDict()
        self._buffers: Dict[str, Optional[Tensor]] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self.training = True
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = 0
        self._casted_by_pure_fp16 = False

    # -- parameter/buffer management ---------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        """reference: layers.py create_parameter → LayerHelper.create_parameter."""
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = _dt.convert_dtype(dtype) or self._dtype
        init = attr.initializer or default_initializer
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierUniform()
        data = init(tuple(int(s) for s in shape), dtype)
        p = Parameter(data, name=attr.name, trainable=attr.trainable)
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            parameter = Parameter(parameter)
        self._parameters[name] = parameter
        if parameter is not None:
            object.__setattr__(self, name, parameter)
        return parameter

    def register_buffer(self, name, tensor, persistable=True):
        """reference: layers.py register_buffer."""
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def create_tensor(self, name=None, persistable=False, dtype=None):
        import jax.numpy as jnp
        t = Tensor(jnp.zeros([], _dt.convert_dtype(dtype) or self._dtype))
        return t

    # -- attribute plumbing -------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            params[name] = value
            buffers.pop(name, None) if buffers else None
            object.__setattr__(self, name, value)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            layers[name] = value
            object.__setattr__(self, name, value)
        else:
            if params is not None and name in params and value is None:
                params[name] = None
            if layers is not None and name in layers and value is None:
                layers[name] = None
            if buffers is not None and name in buffers:
                buffers[name] = value
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"'{type(self).__name__}' has no attribute '{name}'")

    # -- walkers ------------------------------------------------------------
    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True,
                         include_self=True) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p
            if not include_sublayers:
                break

    def sublayers(self, include_self=False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False,
                        layers_set=None) -> Iterator[Tuple[str, "Layer"]]:
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from sub.named_sublayers(prefix=sub_prefix, include_self=True,
                                           layers_set=layers_set)

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def children(self):
        return (l for l in self._sub_layers.values() if l is not None)

    def named_children(self):
        return ((n, l) for n, l in self._sub_layers.items() if l is not None)

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # -- modes --------------------------------------------------------------
    def train(self):
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    # -- hooks --------------------------------------------------------------
    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # -- execution ----------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            out = hook(self, inputs, outputs)
            if out is not None:
                outputs = out
        return outputs

    # -- state --------------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        """reference: layers.py state_dict — params + persistable buffers."""
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters():
            dest[structured_name_prefix + name] = p
        for name, layer in self.named_sublayers(include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                full = f"{name}.{bname}" if name else bname
                dest[structured_name_prefix + full] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        """reference: layers.py set_state_dict (shape-checked copy)."""
        own = self.state_dict()
        missing, unexpected = [], []
        for k, t in own.items():
            if k in state_dict:
                v = state_dict[k]
                raw = v.numpy() if isinstance(v, Tensor) else np.asarray(v)
                if tuple(raw.shape) != tuple(t.shape):
                    raise ValueError(
                        f"shape mismatch for {k}: {raw.shape} vs {tuple(t.shape)}")
                t.set_value(raw.astype(t.dtype))
            else:
                missing.append(k)
        for k in state_dict:
            if k not in own:
                unexpected.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    # -- dtype casting ------------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._cast_to(_dt.convert_dtype(dtype))
        return self

    def astype(self, dtype):
        self._cast_to(_dt.convert_dtype(dtype))
        return self

    def _cast_to(self, dtype, floating_only=True):
        for _, p in self.named_parameters():
            if not floating_only or _dt.is_floating(p.dtype):
                p._data = p._data.astype(dtype)
        for _, b in self.named_buffers():
            if not floating_only or _dt.is_floating(b.dtype):
                b._data = b._data.astype(dtype)
        for l in self.sublayers(include_self=True):
            l._dtype = dtype

    def float(self):
        return self.astype("float32")

    def half(self):
        return self.astype("float16")

    def bfloat16(self):
        return self.astype("bfloat16")

    def full_name(self):
        return self._full_name

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = [f"{type(self).__name__}({extra}" if extra else f"{type(self).__name__}("]
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            sub_repr = repr(sub).split("\n")
            lines.append(f"  ({name}): " + "\n  ".join(sub_repr))
        lines.append(")")
        return "\n".join(lines) if len(lines) > 2 else \
            f"{type(self).__name__}({extra})"

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()
