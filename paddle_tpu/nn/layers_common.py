"""Core NN layers: Linear, Conv, Norm, Pool, Embedding, Dropout, padding,
upsample, activations-as-layers.

Reference: python/paddle/nn/layer/{common.py, conv.py, norm.py, pooling.py,
activation.py} — each Layer here owns Parameters and calls the functional op.
"""
from __future__ import annotations

import numpy as np

from .layer_base import Layer, ParamAttr
from . import initializer as I
from . import functional as F
from ..core.tensor import Tensor
from ..core import dtypes as _dt
from ..ops import creation, manipulation, math as _math


class Linear(Layer):
    """reference: python/paddle/nn/layer/common.py Linear (weight [in, out])."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True)
        self.name = name

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.weight.shape[0]}, out_features={self.weight.shape[1]}"


class _ConvNd(Layer):
    def __init__(self, n, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 transpose=False, output_padding=0):
        super().__init__()
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) else [kernel_size] * n
        self._n = n
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        self._transpose = transpose
        self._output_padding = output_padding
        if transpose:
            shape = [in_channels, out_channels // groups] + list(ks)
        else:
            shape = [out_channels, in_channels // groups] + list(ks)
        fan_in = in_channels * int(np.prod(ks)) // groups
        self.weight = self.create_parameter(
            shape, attr=weight_attr,
            default_initializer=I.Uniform(-np.sqrt(1.0 / fan_in), np.sqrt(1.0 / fan_in)))
        self.bias = self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True,
            default_initializer=I.Uniform(-np.sqrt(1.0 / fan_in), np.sqrt(1.0 / fan_in)))

    def forward(self, x):
        fns = {1: (F.conv1d, F.conv1d_transpose), 2: (F.conv2d, F.conv2d_transpose),
               3: (F.conv3d, F.conv3d_transpose)}
        fwd, tr = fns[self._n]
        if self._transpose:
            return tr(x, self.weight, self.bias, stride=self._stride,
                      padding=self._padding, output_padding=self._output_padding,
                      groups=self._groups, dilation=self._dilation,
                      data_format=self._data_format)
        return fwd(x, self.weight, self.bias, stride=self._stride,
                   padding=self._padding, dilation=self._dilation,
                   groups=self._groups, data_format=self._data_format)


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(1, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)


class Conv2D(_ConvNd):
    """reference: python/paddle/nn/layer/conv.py Conv2D → conv2d op."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(2, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(3, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(1, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(2, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(3, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)


class _BatchNormBase(Layer):
    """reference: python/paddle/nn/layer/norm.py _BatchNormBase."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self._num_features = num_features
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)
        self._mean = self.register_buffer(
            "_mean", Tensor(np.zeros(num_features, np.float32)))
        self._variance = self.register_buffer(
            "_variance", Tensor(np.ones(num_features, np.float32)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)


class BatchNorm(_BatchNormBase):
    """fluid-style BatchNorm (acts on any rank, channel axis 1)."""


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """reference: operators/sync_batch_norm_op.cu — on TPU, batch stats are
    global automatically when the batch axis is sharded over the mesh under
    jit (XLA inserts the cross-replica psum); eager single-process mode equals
    plain BatchNorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        # walk and replace _BatchNormBase instances
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, _BatchNormBase) and not isinstance(sub, SyncBatchNorm):
                sync = SyncBatchNorm(sub._num_features, sub._momentum,
                                     sub._epsilon, data_format=sub._data_format)
                if sub.weight is not None:
                    sync.weight.set_value(sub.weight)
                    sync.bias.set_value(sub.bias)
                sync._mean.set_value(sub._mean)
                sync._variance.set_value(sub._variance)
                layer._sub_layers[name] = sync
                object.__setattr__(layer, name, sync)
            elif isinstance(sub, Layer):
                cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    """reference: python/paddle/nn/layer/norm.py LayerNorm."""

    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(self._normalized_shape,
                                              attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_channels], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL", name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_features], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format, name)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCDHW", name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format, name)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self._args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self._args[:4])


class SpectralNorm(Layer):
    """reference: operators/spectral_norm_op.cc (power-iteration weight norm)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12, name=None):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter(
            [h], default_initializer=I.Normal(0, 1))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            [w], default_initializer=I.Normal(0, 1))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        import jax.numpy as jnp
        from ..ops.dispatch import apply
        dim, iters, eps = self._dim, self._power_iters, self._eps

        def impl(w, u, v):
            mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            for _ in range(iters):
                v = mat.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = mat @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ mat @ v
            return w / sigma
        return apply("spectral_norm", impl, weight, self.weight_u, self.weight_v)


# -- pooling layers ---------------------------------------------------------

class _PoolNd(Layer):
    def __init__(self, fn, *args, **kw):
        super().__init__()
        self._fn = fn
        self._args = args
        self._kw = kw

    def forward(self, x):
        return self._fn(x, *self._args, **self._kw)


class MaxPool1D(_PoolNd):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, name=None):
        super().__init__(F.max_pool1d, kernel_size, stride, padding,
                         return_mask, ceil_mode)


class MaxPool2D(_PoolNd):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__(F.max_pool2d, kernel_size, stride, padding,
                         return_mask, ceil_mode, data_format)


class MaxPool3D(_PoolNd):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCDHW", name=None):
        super().__init__(F.max_pool3d, kernel_size, stride, padding,
                         return_mask, ceil_mode, data_format)


class AvgPool1D(_PoolNd):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        super().__init__(F.avg_pool1d, kernel_size, stride, padding,
                         exclusive, ceil_mode)


class AvgPool2D(_PoolNd):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW",
                 name=None):
        super().__init__(F.avg_pool2d, kernel_size, stride, padding,
                         ceil_mode, exclusive, divisor_override, data_format)


class AvgPool3D(_PoolNd):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCDHW",
                 name=None):
        super().__init__(F.avg_pool3d, kernel_size, stride, padding,
                         ceil_mode, exclusive, divisor_override, data_format)


class AdaptiveAvgPool1D(_PoolNd):
    def __init__(self, output_size, name=None):
        super().__init__(F.adaptive_avg_pool1d, output_size)


class AdaptiveAvgPool2D(_PoolNd):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__(F.adaptive_avg_pool2d, output_size, data_format)


class AdaptiveAvgPool3D(_PoolNd):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__(F.adaptive_avg_pool3d, output_size, data_format)


class AdaptiveMaxPool1D(_PoolNd):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(F.adaptive_max_pool1d, output_size, return_mask)


class AdaptiveMaxPool2D(_PoolNd):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(F.adaptive_max_pool2d, output_size, return_mask)


class AdaptiveMaxPool3D(_PoolNd):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(F.adaptive_max_pool3d, output_size, return_mask)


# -- embedding / dropout / misc --------------------------------------------

class Embedding(Layer):
    """reference: python/paddle/nn/layer/common.py Embedding → lookup_table_v2."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._padding_idx = padding_idx
        self._sparse = sparse
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        if padding_idx is not None:
            with _no_grad():
                w = self.weight.numpy()
                w[padding_idx] = 0
                self.weight.set_value(w)

    def forward(self, x):
        return F.embedding(x, self.weight, self._padding_idx, self._sparse)


def _no_grad():
    from ..core.autograd_engine import no_grad
    return no_grad()


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, self.axis, self.training, self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, self.training, self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, self.training, self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self._start, self._stop = start_axis, stop_axis

    def forward(self, x):
        return manipulation.flatten(x, self._start, self._stop)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self._kw = dict(size=size, scale_factor=scale_factor, mode=mode,
                        align_corners=align_corners, align_mode=align_mode,
                        data_format=data_format)

    def forward(self, x):
        return F.interpolate(x, **self._kw)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self._r = upscale_factor
        self._fmt = data_format

    def forward(self, x):
        return manipulation.pixel_shuffle(x, self._r, self._fmt)


class _PadNd(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW"):
        super().__init__()
        self._padding = padding
        self._mode = mode
        self._value = value
        self._fmt = data_format

    def forward(self, x):
        return F.pad(x, self._padding, self._mode, self._value, self._fmt)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL",
                 name=None):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        super().__init__(padding, mode, value, data_format)


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW",
                 name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(Pad2D):
    pass


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self._axis, self._eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self._axis, self._eps)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr)
        self.bias = self.create_parameter([1, out_features], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self._args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self._args)
