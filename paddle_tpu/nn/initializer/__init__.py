"""Weight initializers (paddle.nn.initializer parity).

Reference: python/paddle/fluid/initializer.py (ConstantInitializer,
UniformInitializer, NormalInitializer, TruncatedNormalInitializer,
XavierInitializer, MSRAInitializer, BilinearInitializer, NumpyArrayInitializer)
and python/paddle/nn/initializer/. Initializers here are callables that
produce a fresh jax array for a given shape/dtype using the global Generator.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ...core import dtypes as _dt
from ...core import generator as _gen


class Initializer:
    def __call__(self, shape, dtype=None):
        raise NotImplementedError

    def _dtype(self, dtype):
        d = _dt.convert_dtype(dtype)
        return d if d is not None else _dt.get_default_dtype()


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype=None):
        return jnp.full(shape, self.value, self._dtype(dtype))


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=None):
        return jax.random.uniform(_gen.next_key(), shape, self._dtype(dtype),
                                  self.low, self.high)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None):
        return (jax.random.normal(_gen.next_key(), shape, self._dtype(dtype))
                * self.std + self.mean)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None):
        return (jax.random.truncated_normal(_gen.next_key(), -2.0, 2.0, shape,
                                            self._dtype(dtype))
                * self.std + self.mean)


def _fans(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels [out_c, in_c, *k] (paddle layout)
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class XavierUniform(Initializer):
    """reference: fluid/initializer.py XavierInitializer(uniform=True)."""

    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(_gen.next_key(), shape, self._dtype(dtype),
                                  -limit, limit)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return jax.random.normal(_gen.next_key(), shape, self._dtype(dtype)) * std


class KaimingUniform(Initializer):
    """reference: fluid/initializer.py MSRAInitializer(uniform=True)."""

    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu", name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _gain(self):
        if self.nonlinearity == "leaky_relu":
            return math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        return math.sqrt(2.0) if self.nonlinearity == "relu" else 1.0

    def __call__(self, shape, dtype=None):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        limit = self._gain() * math.sqrt(3.0 / fi)
        return jax.random.uniform(_gen.next_key(), shape, self._dtype(dtype),
                                  -limit, limit)


class KaimingNormal(KaimingUniform):
    def __call__(self, shape, dtype=None):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        std = self._gain() / math.sqrt(fi)
        return jax.random.normal(_gen.next_key(), shape, self._dtype(dtype)) * std


class Assign(Initializer):
    """reference: NumpyArrayInitializer."""

    def __init__(self, value, name=None):
        self.value = value

    def __call__(self, shape, dtype=None):
        arr = np.asarray(self.value._data if hasattr(self.value, "_data") else self.value)
        if tuple(arr.shape) != tuple(shape):
            arr = arr.reshape(shape)
        return jnp.asarray(arr, self._dtype(dtype))


class Bilinear(Initializer):
    """reference: fluid/initializer.py BilinearInitializer (upsample deconv)."""

    def __call__(self, shape, dtype=None):
        weight = np.zeros(shape, np.float32)
        f = math.ceil(shape[-1] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape[-2:]))):
            x, y = i % shape[-1], i // shape[-1]
            v = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
            weight[..., y, x] = v
        return jnp.asarray(weight, self._dtype(dtype))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def __call__(self, shape, dtype=None):
        rows, cols = shape[0], int(np.prod(shape[1:]))
        n = max(rows, cols)
        a = jax.random.normal(_gen.next_key(), (n, n), jnp.float32)
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diag(r))
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(self._dtype(dtype))


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def __call__(self, shape, dtype=None):
        w = np.zeros(shape, np.float32)
        oc, ic = shape[0], shape[1]
        mins = min(oc // self.groups, ic)
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for i in range(mins):
                w[(g * (oc // self.groups) + i, i) + tuple(centers)] = 1.0
        return jnp.asarray(w, self._dtype(dtype))


def calculate_gain(nonlinearity, param=None):
    if nonlinearity == "tanh":
        return 5.0 / 3
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity == "selu":
        return 3.0 / 4
    return 1.0


# legacy fluid-style aliases (reference: fluid/initializer.py module tail)
ConstantInitializer = Constant
UniformInitializer = Uniform
NormalInitializer = Normal
TruncatedNormalInitializer = TruncatedNormal
XavierInitializer = XavierUniform
MSRAInitializer = KaimingNormal
NumpyArrayInitializer = Assign
