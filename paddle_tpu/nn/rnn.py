"""Recurrent layers: SimpleRNN / LSTM / GRU + cells.

Reference: python/paddle/nn/layer/rnn.py (RNNCellBase, LSTMCell :1038,
GRUCell :1181, RNN :238, LSTM :1460, GRU :1616) and the cudnn_lstm_op.
TPU design: the time loop is a `lax.scan` inside ONE traced op, so the whole
sequence compiles to a single XLA while-loop with the cell body fused —
replacing the reference's per-timestep kernel launches / cuDNN call.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .layer_base import Layer
from . import initializer as I
from ..ops.dispatch import apply
from ..ops import creation


def _init_state(shape, dtype):
    return jnp.zeros(shape, dtype)


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype="float32",
                           init_value=0.0, batch_dim_idx=0):
        b = batch_ref.shape[batch_dim_idx]
        return creation.full([b, self.hidden_size], init_value, dtype)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        k = 1.0 / np.sqrt(hidden_size)
        init = I.Uniform(-k, k)
        self.weight_ih = self.create_parameter([hidden_size, input_size],
                                               weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size],
                                               weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([hidden_size], bias_ih_attr,
                                             is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter([hidden_size], bias_hh_attr,
                                             is_bias=True, default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs, dtype=inputs.dtype)

        def impl(x, h, wi, wh, bi, bh):
            z = x @ wi.T + bi + h @ wh.T + bh
            h2 = jnp.tanh(z) if self.activation == "tanh" else jax.nn.relu(z)
            return h2, h2
        return apply("simple_rnn_cell", impl, inputs, states, self.weight_ih,
                     self.weight_hh, self.bias_ih, self.bias_hh)


class LSTMCell(RNNCellBase):
    """reference: rnn.py:1038 (gate order i,f,g,o like paddle)."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        k = 1.0 / np.sqrt(hidden_size)
        init = I.Uniform(-k, k)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size],
                                               weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size],
                                               weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([4 * hidden_size], bias_ih_attr,
                                             is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter([4 * hidden_size], bias_hh_attr,
                                             is_bias=True, default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            h = self.get_initial_states(inputs, dtype=inputs.dtype)
            states = (h, h)

        def impl(x, h, c, wi, wh, bi, bh):
            gates = x @ wi.T + bi + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c2 = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h2 = jax.nn.sigmoid(o) * jnp.tanh(c2)
            return h2, (h2, c2)
        return apply("lstm_cell", impl, inputs, states[0], states[1],
                     self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh)


class GRUCell(RNNCellBase):
    """reference: rnn.py:1181 (paddle GRU formulation)."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        k = 1.0 / np.sqrt(hidden_size)
        init = I.Uniform(-k, k)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size],
                                               weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size],
                                               weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([3 * hidden_size], bias_ih_attr,
                                             is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter([3 * hidden_size], bias_hh_attr,
                                             is_bias=True, default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs, dtype=inputs.dtype)

        def impl(x, h, wi, wh, bi, bh):
            xg = x @ wi.T + bi
            hg = h @ wh.T + bh
            xr, xz, xn = jnp.split(xg, 3, axis=-1)
            hr, hz, hn = jnp.split(hg, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            h2 = (1 - z) * n + z * h
            return h2, h2
        return apply("gru_cell", impl, inputs, states, self.weight_ih,
                     self.weight_hh, self.bias_ih, self.bias_hh)


class RNN(Layer):
    """Wraps a cell into a sequence scan (reference: rnn.py:238 RNN —
    there a python loop / recurrent op; here lax.scan)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        return _scan_rnn(self.cell, inputs, initial_states, sequence_length,
                         self.is_reverse, self.time_major)


def _cell_params(cell):
    return [cell.weight_ih, cell.weight_hh, cell.bias_ih, cell.bias_hh]


def _scan_rnn(cell, inputs, initial_states, sequence_length, is_reverse,
              time_major):
    kind = ("lstm" if isinstance(cell, LSTMCell)
            else "gru" if isinstance(cell, GRUCell) else "rnn")
    act = getattr(cell, "activation", "tanh")
    hidden = cell.hidden_size

    def impl(x, wi, wh, bi, bh, *rest):
        rest = list(rest)
        seq_len = rest.pop(0) if sequence_length is not None else None
        init = rest
        if not time_major:
            x = jnp.swapaxes(x, 0, 1)  # [T,B,I]
        T = x.shape[0]
        if seq_len is not None:
            # per-row masking (reference: the LoD/padded sequence_length
            # contract): forward reads t, reverse reads len-1-t (its own
            # valid prefix reversed), rows past their length freeze the
            # state and emit zeros
            sl = seq_len.astype(jnp.int32)                    # [B]
            t_idx = jnp.arange(T)[:, None]                    # [T,1]
            if is_reverse:
                pos = sl[None, :] - 1 - t_idx
                pos_c = jnp.clip(pos, 0, T - 1)               # [T,B]
                x = jnp.take_along_axis(
                    x, pos_c[:, :, None].astype(jnp.int32), axis=0)
            else:
                pos_c = None        # forward order needs no shuffle
            alive = (t_idx < sl[None, :])                     # [T,B]
        elif is_reverse:
            x = jnp.flip(x, 0)
            alive = None
        else:
            alive = None
        b = x.shape[1]
        if init:
            h0 = init[0]
            c0 = init[1] if kind == "lstm" else None
        else:
            h0 = jnp.zeros((b, hidden), x.dtype)
            c0 = jnp.zeros((b, hidden), x.dtype) if kind == "lstm" else None

        def body(carry, xt):
            if kind == "lstm":
                h, c = carry
                gates = xt @ wi.T + bi + h @ wh.T + bh
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                c2 = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
                h2 = jax.nn.sigmoid(o) * jnp.tanh(c2)
                return (h2, c2), h2
            if kind == "gru":
                h = carry
                xg = xt @ wi.T + bi
                hg = h @ wh.T + bh
                xr, xz, xn = jnp.split(xg, 3, axis=-1)
                hr, hz, hn = jnp.split(hg, 3, axis=-1)
                r = jax.nn.sigmoid(xr + hr)
                z = jax.nn.sigmoid(xz + hz)
                n = jnp.tanh(xn + r * hn)
                h2 = (1 - z) * n + z * h
                return h2, h2
            h = carry
            z = xt @ wi.T + bi + h @ wh.T + bh
            h2 = jnp.tanh(z) if act == "tanh" else jax.nn.relu(z)
            return h2, h2

        carry0 = (h0, c0) if kind == "lstm" else h0
        if alive is not None:
            def masked_body(carry, inp):
                xt, at = inp
                new_carry, y = body(carry, xt)
                am = at[:, None].astype(y.dtype)
                if kind == "lstm":
                    (h_old, c_old), (h_new, c_new) = carry, new_carry
                    new_carry = (h_new * am + h_old * (1 - am),
                                 c_new * am + c_old * (1 - am))
                else:
                    new_carry = new_carry * am + carry * (1 - am)
                return new_carry, y * am
            carryT, ys = jax.lax.scan(masked_body, carry0, (x, alive))
            if is_reverse:
                # outputs are in PROCESSING order; scatter back to the
                # source positions (position len-1-t)
                src_idx = jnp.where(alive, pos_c, T - 1)      # [T,B]
                out = jnp.zeros_like(ys)
                out = out.at[src_idx,
                             jnp.arange(ys.shape[1])[None, :]].add(
                    ys * alive[:, :, None].astype(ys.dtype))
                ys = out
            # forward: ys is already source-ordered and body masked it
        else:
            carryT, ys = jax.lax.scan(body, carry0, x)
            if is_reverse:
                ys = jnp.flip(ys, 0)
        if not time_major:
            ys = jnp.swapaxes(ys, 0, 1)
        if kind == "lstm":
            return ys, carryT[0], carryT[1]
        return ys, carryT

    args = [inputs] + _cell_params(cell)
    if sequence_length is not None:
        args.append(sequence_length)
    if initial_states is not None:
        if kind == "lstm":
            args += [initial_states[0], initial_states[1]]
        else:
            args += [initial_states]
    out = apply(f"rnn_scan_{kind}", impl, *args)
    if kind == "lstm":
        ys, h, c = out
        return ys, (h, c)
    ys, h = out
    return ys, h


class _MultiLayerRNN(Layer):
    """Stacked (optionally bidirectional) recurrent network
    (reference: rnn.py LSTM :1460 / GRU :1616 / SimpleRNN :1322)."""

    MODE = "rnn"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        num_dir = 2 if self.bidirect else 1
        self.num_directions = num_dir

        cell_cls = {"rnn": SimpleRNNCell, "lstm": LSTMCell, "gru": GRUCell}[self.MODE]
        self._cells = []
        for layer in range(num_layers):
            in_sz = input_size if layer == 0 else hidden_size * num_dir
            for d in range(num_dir):
                kw = {}
                if self.MODE == "rnn":
                    kw["activation"] = activation
                cell = cell_cls(in_sz, hidden_size, weight_ih_attr,
                                weight_hh_attr, bias_ih_attr, bias_hh_attr, **kw)
                self.add_sublayer(f"cell_{layer}_{d}", cell)
                self._cells.append(cell)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from .functional import dropout as F_dropout
        states_out = []
        x = inputs
        idx = 0
        for layer in range(self.num_layers):
            outs = []
            for d in range(self.num_directions):
                cell = self._cells[idx]
                init = None
                if initial_states is not None:
                    if self.MODE == "lstm":
                        init = (initial_states[0][idx], initial_states[1][idx])
                    else:
                        init = initial_states[idx]
                ys, st = _scan_rnn(cell, x, init, sequence_length,
                                   is_reverse=(d == 1), time_major=self.time_major)
                outs.append(ys)
                states_out.append(st)
                idx += 1
            if self.num_directions == 2:
                from ..ops import manipulation
                x = manipulation.concat(outs, axis=-1)
            else:
                x = outs[0]
            if self.dropout and layer < self.num_layers - 1:
                x = F_dropout(x, self.dropout, training=self.training)
        from ..ops import manipulation as mp
        if self.MODE == "lstm":
            h = mp.stack([s[0] for s in states_out], 0)
            c = mp.stack([s[1] for s in states_out], 0)
            return x, (h, c)
        h = mp.stack(states_out, 0)
        return x, h


class SimpleRNN(_MultiLayerRNN):
    MODE = "rnn"


class LSTM(_MultiLayerRNN):
    MODE = "lstm"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, "tanh", weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr, name)


class GRU(_MultiLayerRNN):
    MODE = "gru"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, "tanh", weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr, name)


class BiRNN(Layer):
    """reference: nn/layer/rnn.py BiRNN — forward + backward cells over
    the same sequence, outputs concatenated on the feature dim."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.bw = RNN(cell_bw, is_reverse=True, time_major=time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if initial_states is None:
            st_fw = st_bw = None
        else:
            st_fw, st_bw = initial_states
        out_fw, last_fw = self.fw(inputs, st_fw, sequence_length)
        # RNN(is_reverse=True) already returns TIME-ALIGNED outputs
        # (_scan_rnn flips back after the scan), so concat directly like
        # the reference BiRNN
        out_bw, last_bw = self.bw(inputs, st_bw, sequence_length)
        from ..ops import manipulation as _m
        out = _m.concat([out_fw, out_bw], axis=2)
        return out, (last_fw, last_bw)
