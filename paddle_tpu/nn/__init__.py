"""paddle.nn parity namespace (reference: python/paddle/nn/__init__.py)."""
from .layer_base import Layer, ParamAttr, HookRemoveHelper
from .container import Sequential, LayerList, LayerDict, ParameterList
from .layers_common import (
    Linear, Conv1D, Conv2D, Conv3D, Conv1DTranspose, Conv2DTranspose,
    Conv3DTranspose, BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
    SyncBatchNorm, LayerNorm, GroupNorm, InstanceNorm1D, InstanceNorm2D,
    InstanceNorm3D, LocalResponseNorm, SpectralNorm,
    MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D, AvgPool2D, AvgPool3D,
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D,
    Embedding, Dropout, Dropout2D, Dropout3D, AlphaDropout, Flatten,
    Upsample, UpsamplingBilinear2D, UpsamplingNearest2D, PixelShuffle,
    Pad1D, Pad2D, Pad3D, ZeroPad2D, CosineSimilarity, Bilinear, Unfold)
from .layers_activation import (
    ReLU, ReLU6, GELU, Sigmoid, Tanh, Softmax, LogSoftmax, LeakyReLU, ELU,
    SELU, CELU, Silu, Swish, Mish, Hardswish, Hardsigmoid, Hardtanh,
    Hardshrink, Softshrink, Tanhshrink, Softplus, Softsign, LogSigmoid,
    ThresholdedReLU, Maxout, PReLU, RReLU, GLU,
    CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss, BCEWithLogitsLoss,
    KLDivLoss, SmoothL1Loss, MarginRankingLoss, CTCLoss, CosineEmbeddingLoss,
    TripletMarginLoss, HSigmoidLoss, PairwiseDistance)
from .transformer import (MultiHeadAttention, TransformerEncoderLayer,
                          TransformerEncoder, TransformerDecoderLayer,
                          TransformerDecoder, Transformer, CAUSAL_MASK,
                          FLASH_CROSSOVER)
from .rnn import (RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN,
                  SimpleRNN, LSTM, GRU, BiRNN)
from .beam_decode import BeamSearchDecoder, dynamic_decode  # noqa: F401
from .clip import (ClipGradByGlobalNorm, ClipGradByNorm,  # noqa: F401
                   ClipGradByValue)
from .utils_weight_norm import spectral_norm  # noqa: F401
from . import layers_activation as loss  # noqa: F401  (paddle.nn.loss)
from . import functional
from . import initializer
from .utils_weight_norm import weight_norm, remove_weight_norm, spectral_norm_fn

# paddle exposes utils under nn.utils
from . import utils  # noqa: F401
