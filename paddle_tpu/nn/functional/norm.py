"""Normalization functionals.

Parity targets: batch_norm, sync_batch_norm, layer_norm, instance_norm,
group_norm, lrn, spectral/weight norm helpers (reference:
paddle/fluid/operators/batch_norm_op.cc, layer_norm_op.cc, group_norm_op.cc,
instance_norm_op.cc, lrn_op.cc). On TPU sync_batch_norm == batch_norm with
batch-stat psum over the data-parallel mesh axis (done by GSPMD when the batch
is sharded) — no separate kernel needed.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

import jax

from ...ops.dispatch import apply
from ...core.tensor import Tensor


def _stat_dtype(a):
    """Normalization statistics accumulate in f32 for low-precision inputs
    (the TPU bf16 recipe: bf16 tensors, f32 statistics)."""
    return (jnp.float32 if a.dtype in (jnp.bfloat16, jnp.float16)
            else a.dtype)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    """reference: operators/batch_norm_op.cc (momentum convention:
    running = momentum*running + (1-momentum)*batch)."""
    channel_axis = 1 if data_format.startswith("NC") else -1
    use_batch_stats = training and not use_global_stats

    def stat_shape(a):
        s = [1] * a.ndim
        s[channel_axis] = a.shape[channel_axis]
        return s

    if use_batch_stats:
        def impl(a, w, b):
            axes = tuple(i for i in range(a.ndim)
                         if i != (channel_axis % a.ndim))
            # statistics accumulate in f32 even for bf16/f16 activations
            # (XLA fuses the upcast into the reduction; the normalized
            # output is cast back, so activation HBM traffic stays low)
            sdt = _stat_dtype(a)
            af = a.astype(sdt)
            mean = jnp.mean(af, axis=axes)
            var = jnp.var(af, axis=axes)
            ss = stat_shape(a)
            out = (af - mean.reshape(ss)) * jax.lax.rsqrt(
                var.reshape(ss) + epsilon)
            if w is not None:
                out = out * w.reshape(ss).astype(sdt)
            if b is not None:
                out = out + b.reshape(ss).astype(sdt)
            return out.astype(a.dtype), mean, var
        out, batch_mean, batch_var = apply(
            "batch_norm", impl, x,
            weight if weight is not None else None,
            bias if bias is not None else None)
        # running-stat update is state mutation, outside the tape
        if running_mean is not None:
            with _no_grad():
                # biased batch variance, matching the reference convention
                # (batch_norm_op.cc:397 uses the plain batch var, no n/(n-1))
                running_mean.set_value(momentum * running_mean
                                       + (1.0 - momentum) * batch_mean.detach())
                running_var.set_value(momentum * running_var
                                      + (1.0 - momentum) * batch_var.detach())
        return out

    def impl_eval(a, m, v, w, b):
        ss = stat_shape(a)
        sdt = _stat_dtype(a)
        out = (a.astype(sdt) - m.reshape(ss).astype(sdt)) * jax.lax.rsqrt(
            v.reshape(ss).astype(sdt) + epsilon)
        if w is not None:
            out = out * w.reshape(ss).astype(sdt)
        if b is not None:
            out = out + b.reshape(ss).astype(sdt)
        return out.astype(a.dtype)
    return apply("batch_norm", impl_eval, x, running_mean, running_var,
                 weight, bias)


def _no_grad():
    from ...core.autograd_engine import no_grad
    return no_grad()


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    """reference: operators/layer_norm_op.cc."""
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(normalized_shape)

    def impl(a, *wb):
        axes = tuple(range(a.ndim - n_axes, a.ndim))
        sdt = _stat_dtype(a)
        af = a.astype(sdt)
        mean = jnp.mean(af, axis=axes, keepdims=True)
        var = jnp.var(af, axis=axes, keepdims=True)
        out = (af - mean) * jax.lax.rsqrt(var + epsilon)
        it = iter(wb)
        if weight is not None:
            out = out * next(it).astype(sdt)
        if bias is not None:
            out = out + next(it).astype(sdt)
        return out.astype(a.dtype)
    args = [x] + [t for t in (weight, bias) if t is not None]
    return apply("layer_norm", impl, *args)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    """reference: operators/instance_norm_op.cc."""
    def impl(a, *wb):
        axes = tuple(range(2, a.ndim))  # per-sample per-channel stats
        sdt = _stat_dtype(a)
        af = a.astype(sdt)
        mean = jnp.mean(af, axis=axes, keepdims=True)
        var = jnp.var(af, axis=axes, keepdims=True)
        out = (af - mean) * jax.lax.rsqrt(var + eps)
        it = iter(wb)
        ss = [1, a.shape[1]] + [1] * (a.ndim - 2)
        if weight is not None:
            out = out * next(it).reshape(ss).astype(sdt)
        if bias is not None:
            out = out + next(it).reshape(ss).astype(sdt)
        return out.astype(a.dtype)
    args = [x] + [t for t in (weight, bias) if t is not None]
    return apply("instance_norm", impl, *args)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    """reference: operators/group_norm_op.cc."""
    def impl(a, *wb):
        n, c = a.shape[0], a.shape[1]
        spatial = a.shape[2:]
        sdt = _stat_dtype(a)
        g = a.astype(sdt).reshape((n, num_groups, c // num_groups) + spatial)
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) * jax.lax.rsqrt(var + epsilon)).reshape(a.shape)
        it = iter(wb)
        ss = [1, c] + [1] * (a.ndim - 2)
        if weight is not None:
            out = out * next(it).reshape(ss).astype(sdt)
        if bias is not None:
            out = out + next(it).reshape(ss).astype(sdt)
        return out.astype(a.dtype)
    args = [x] + [t for t in (weight, bias) if t is not None]
    return apply("group_norm", impl, *args)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    """reference: operators/lrn_op.cc."""
    def impl(a):
        sq = a * a
        # sum over `size` adjacent channels
        half = size // 2
        pad = [(0, 0)] * a.ndim
        pad[1] = (half, size - half - 1)
        padded = jnp.pad(sq, pad)
        acc = jnp.zeros_like(a)
        for i in range(size):
            acc = acc + padded[:, i:i + a.shape[1]]
        return a / jnp.power(k + alpha * acc, beta)
    return apply("lrn", impl, x)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def impl(a):
        n = jnp.power(jnp.sum(jnp.power(jnp.abs(a), p), axis=axis, keepdims=True), 1.0 / p)
        return a / jnp.maximum(n, epsilon)
    return apply("normalize", impl, x)
