"""Convolutions via jax.lax.conv_general_dilated.

Parity targets: conv2d, conv3d, conv1d, depthwise_conv2d, conv2d_transpose,
conv3d_transpose (reference: paddle/fluid/operators/conv_op.cc,
conv_transpose_op.cc, + cudnn kernel variants). One lax primitive replaces the
reference's per-backend kernel matrix; XLA tiles it onto the MXU.
Data layout follows paddle's default NCHW / kernel OIHW.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...ops.dispatch import apply


def _tuplize(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(x) for x in v)
        if len(v) == 2 * n:  # per-side pairs
            return tuple(v)
        return tuple(int(v[0]) for _ in range(n))
    return tuple(int(v) for _ in range(n))


def _padding_arg(padding, n, dilation, kernel):
    """paddle padding: int, list, 'SAME', 'VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (list, tuple)) and len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    p = _tuplize(padding, n)
    return [(x, x) for x in p]


def _dim_numbers(n, channel_last=False):
    if n == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if n == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else ("NCDHW", "OIDHW", "NCDHW")


def _conv_nd(n, x, weight, bias, stride, padding, dilation, groups, data_format):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    stride = _tuplize(stride, n)
    dilation = _tuplize(dilation, n)
    dn = _dim_numbers(n, channel_last)
    pad = _padding_arg(padding, n, dilation, None)

    def impl(a, w, *b):
        kernel = w
        if channel_last:
            # paddle stores kernels OIHW regardless; transpose for lax layout
            perm = list(range(2, 2 + n)) + [1, 0]
            kernel = jnp.transpose(w, perm)
        out = lax.conv_general_dilated(
            a, kernel, window_strides=stride, padding=pad,
            rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=groups,
            preferred_element_type=None)
        if b:
            bias_shape = [1] * out.ndim
            bias_shape[-1 if channel_last else 1] = b[0].shape[0]
            out = out + b[0].reshape(bias_shape)
        return out

    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply(f"conv{n}d", impl, *args)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    fmt = "NWC" if data_format == "NLC" else "NCW"
    return _conv_nd(1, x, weight, bias, stride, padding, dilation, groups, fmt)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv_nd(2, x, weight, bias, stride, padding, dilation, groups, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv_nd(3, x, weight, bias, stride, padding, dilation, groups, data_format)


def _conv_transpose_nd(n, x, weight, bias, stride, padding, output_padding,
                       dilation, groups, output_size, data_format):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    stride = _tuplize(stride, n)
    dilation = _tuplize(dilation, n)
    opad = _tuplize(output_padding, n)
    dn = _dim_numbers(n, channel_last)
    if isinstance(padding, str):
        raise ValueError("string padding not supported for conv_transpose")
    pads = _padding_arg(padding, n, dilation, None)

    def impl(a, w, *b):
        # paddle transpose-conv kernels are [in_c, out_c/groups, *k]
        # grad-of-conv: lhs_dilation = stride, padding adjusted
        k = w.shape[2:]
        adj_pad = [
            (dilation[i] * (k[i] - 1) - pads[i][0],
             dilation[i] * (k[i] - 1) - pads[i][1] + opad[i])
            for i in range(n)]
        # flip spatial dims and swap i/o channels: OIHW with O=out
        kernel = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        if groups > 1:
            # [in_c, out_c/g, *k] -> [g, in_c/g, out_c/g, *k] -> [out_c, in_c/g, *k]
            ic = kernel.shape[0]
            kernel = kernel.reshape((groups, ic // groups) + kernel.shape[1:])
            kernel = jnp.moveaxis(kernel, 2, 1)  # g, out/g, in/g, *k
            kernel = kernel.reshape((kernel.shape[0] * kernel.shape[1],) + kernel.shape[2:])
        else:
            kernel = jnp.swapaxes(kernel, 0, 1)
        if channel_last:
            perm = list(range(2, 2 + n)) + [1, 0]
            kernel = jnp.transpose(kernel, perm)
        out = lax.conv_general_dilated(
            a, kernel, window_strides=(1,) * n, padding=adj_pad,
            lhs_dilation=stride, rhs_dilation=dilation,
            dimension_numbers=dn, feature_group_count=groups)
        if b:
            bias_shape = [1] * out.ndim
            bias_shape[-1 if channel_last else 1] = b[0].shape[0]
            out = out + b[0].reshape(bias_shape)
        return out

    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply(f"conv{n}d_transpose", impl, *args)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCL", name=None):
    fmt = "NWC" if data_format == "NLC" else "NCW"
    return _conv_transpose_nd(1, x, weight, bias, stride, padding,
                              output_padding, dilation, groups, output_size, fmt)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCHW", name=None):
    return _conv_transpose_nd(2, x, weight, bias, stride, padding,
                              output_padding, dilation, groups, output_size,
                              data_format)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCDHW", name=None):
    return _conv_transpose_nd(3, x, weight, bias, stride, padding,
                              output_padding, dilation, groups, output_size,
                              data_format)
