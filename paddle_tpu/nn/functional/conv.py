"""Convolutions via jax.lax.conv_general_dilated.

Parity targets: conv2d, conv3d, conv1d, depthwise_conv2d, conv2d_transpose,
conv3d_transpose (reference: paddle/fluid/operators/conv_op.cc,
conv_transpose_op.cc, + cudnn kernel variants). One lax primitive replaces the
reference's per-backend kernel matrix; XLA tiles it onto the MXU.
Data layout follows paddle's default NCHW / kernel OIHW.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...ops.dispatch import apply


def _tuplize(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(x) for x in v)
        if len(v) == 2 * n:  # per-side pairs
            return tuple(v)
        return tuple(int(v[0]) for _ in range(n))
    return tuple(int(v) for _ in range(n))


def _padding_arg(padding, n, dilation, kernel):
    """paddle padding: int, list, 'SAME', 'VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (list, tuple)) and len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    p = _tuplize(padding, n)
    return [(x, x) for x in p]


def _dim_numbers(n, channel_last=False):
    if n == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if n == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else ("NCDHW", "OIDHW", "NCDHW")


def _conv_nd(n, x, weight, bias, stride, padding, dilation, groups, data_format):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    stride = _tuplize(stride, n)
    dilation = _tuplize(dilation, n)
    dn = _dim_numbers(n, channel_last)
    pad = _padding_arg(padding, n, dilation, None)

    def impl(a, w, *b):
        kernel = w
        if channel_last:
            # paddle stores kernels OIHW regardless; transpose for lax layout
            perm = list(range(2, 2 + n)) + [1, 0]
            kernel = jnp.transpose(w, perm)
        out = lax.conv_general_dilated(
            a, kernel, window_strides=stride, padding=pad,
            rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=groups,
            preferred_element_type=None)
        if b:
            bias_shape = [1] * out.ndim
            bias_shape[-1 if channel_last else 1] = b[0].shape[0]
            out = out + b[0].reshape(bias_shape)
        return out

    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply(f"conv{n}d", impl, *args)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    fmt = "NWC" if data_format == "NLC" else "NCW"
    return _conv_nd(1, x, weight, bias, stride, padding, dilation, groups, fmt)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv_nd(2, x, weight, bias, stride, padding, dilation, groups, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv_nd(3, x, weight, bias, stride, padding, dilation, groups, data_format)


def _conv_transpose_nd(n, x, weight, bias, stride, padding, output_padding,
                       dilation, groups, output_size, data_format):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    stride = _tuplize(stride, n)
    dilation = _tuplize(dilation, n)
    opad = _tuplize(output_padding, n)
    dn = _dim_numbers(n, channel_last)
    if isinstance(padding, str):
        raise ValueError("string padding not supported for conv_transpose")
    pads = _padding_arg(padding, n, dilation, None)

    def impl(a, w, *b):
        # paddle transpose-conv kernels are [in_c, out_c/groups, *k]
        # grad-of-conv: lhs_dilation = stride, padding adjusted
        k = w.shape[2:]
        adj_pad = [
            (dilation[i] * (k[i] - 1) - pads[i][0],
             dilation[i] * (k[i] - 1) - pads[i][1] + opad[i])
            for i in range(n)]
        # flip spatial dims and swap i/o channels: OIHW with O=out
        kernel = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        if groups > 1:
            # [in_c, out_c/g, *k] -> [g, in_c/g, out_c/g, *k] -> [out_c, in_c/g, *k]
            ic = kernel.shape[0]
            kernel = kernel.reshape((groups, ic // groups) + kernel.shape[1:])
            kernel = jnp.moveaxis(kernel, 2, 1)  # g, out/g, in/g, *k
            kernel = kernel.reshape((kernel.shape[0] * kernel.shape[1],) + kernel.shape[2:])
        else:
            kernel = jnp.swapaxes(kernel, 0, 1)
        if channel_last:
            perm = list(range(2, 2 + n)) + [1, 0]
            kernel = jnp.transpose(kernel, perm)
        out = lax.conv_general_dilated(
            a, kernel, window_strides=(1,) * n, padding=adj_pad,
            lhs_dilation=stride, rhs_dilation=dilation,
            dimension_numbers=dn, feature_group_count=groups)
        if b:
            bias_shape = [1] * out.ndim
            bias_shape[-1 if channel_last else 1] = b[0].shape[0]
            out = out + b[0].reshape(bias_shape)
        return out

    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply(f"conv{n}d_transpose", impl, *args)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCL", name=None):
    fmt = "NWC" if data_format == "NLC" else "NCW"
    return _conv_transpose_nd(1, x, weight, bias, stride, padding,
                              output_padding, dilation, groups, output_size, fmt)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCHW", name=None):
    return _conv_transpose_nd(2, x, weight, bias, stride, padding,
                              output_padding, dilation, groups, output_size,
                              data_format)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCDHW", name=None):
    return _conv_transpose_nd(3, x, weight, bias, stride, padding,
                              output_padding, dilation, groups, output_size,
                              data_format)


def deformable_conv(x, offset, weight, mask=None, bias=None, stride=1,
                    padding=0, dilation=1, deformable_groups=1, groups=1,
                    im2col_step=None, name=None):
    """reference: operators/deformable_conv_op.cc (v1) /
    deformable_conv_v2 (with modulation ``mask``).

    x [B, C, H, W]; offset [B, 2*dg*kh*kw, Ho, Wo] (y,x interleaved per
    tap, reference layout); mask [B, dg*kh*kw, Ho, Wo]; weight
    [Cout, C/groups, kh, kw]. Implemented as bilinear sampling (gather) +
    one big contraction — the MXU does the matmul, XLA fuses the sampling.
    """
    sh, sw = _tuplize(stride, 2)
    dh, dw = _tuplize(dilation, 2)
    if isinstance(padding, (list, tuple)) and len(padding) == 4:
        pt, pb, pl, pr = padding
    else:
        ph_, pw_ = _tuplize(padding, 2)
        pt = pb = ph_
        pl = pr = pw_
    kh, kw = int(weight.shape[2]), int(weight.shape[3])
    dg = int(deformable_groups)

    def impl(a, off, w, *rest):
        it = iter(rest)
        msk = next(it) if mask is not None else None
        b = next(it) if bias is not None else None
        B, C, H, W = a.shape
        Ho, Wo = off.shape[2], off.shape[3]
        K = kh * kw
        # base sampling grid per output position and tap
        oy = jnp.arange(Ho) * sh - pt
        ox = jnp.arange(Wo) * sw - pl
        ky = jnp.arange(kh) * dh
        kx = jnp.arange(kw) * dw
        base_y = oy[:, None, None, None] + ky[None, None, :, None]  # Ho,1,kh,1
        base_x = ox[None, :, None, None] + kx[None, None, None, :]  # 1,Wo,1,kw
        off_r = off.reshape(B, dg, K, 2, Ho, Wo)
        dy = off_r[:, :, :, 0]                      # [B,dg,K,Ho,Wo]
        dx = off_r[:, :, :, 1]
        # per-tap base grids [K, Ho, Wo]
        yy = (ky[:, None, None] + oy[None, :, None]).astype(jnp.float32)
        xx = (kx[:, None, None] + ox[None, None, :]).astype(jnp.float32)
        grid_y = jnp.broadcast_to(yy[:, None, :, :],
                                  (kh, kw, Ho, Wo)).reshape(K, Ho, Wo)
        grid_x = jnp.broadcast_to(xx[None, :, :, :],
                                  (kh, kw, Ho, Wo)).reshape(K, Ho, Wo)
        sy = grid_y[None, None] + dy                # [B,dg,K,Ho,Wo]
        sx = grid_x[None, None] + dx

        y0 = jnp.floor(sy)
        x0 = jnp.floor(sx)
        wy = sy - y0
        wx = sx - x0
        valid = (sy > -1) & (sy < H) & (sx > -1) & (sx < W)

        def tap(yi, xi):
            # out-of-range corners contribute ZERO (reference
            # DmcnIm2colBilinear zeroes corners with h_low < 0 etc.,
            # it does not substitute edge pixels)
            ok = ((yi >= 0) & (yi <= H - 1) & (xi >= 0)
                  & (xi <= W - 1))                         # [B,dg,K,Ho,Wo]
            ycl = jnp.clip(yi.astype(jnp.int32), 0, H - 1)
            xcl = jnp.clip(xi.astype(jnp.int32), 0, W - 1)
            # gather per deformable group: channels split into dg blocks
            a_g = a.reshape(B, dg, C // dg, H, W)

            def per_b(ab, yb, xb):
                # ab [dg, C/dg, H, W]; yb/xb [dg, K, Ho, Wo]
                def per_g(ag, yg, xg):
                    flat = ag.reshape(ag.shape[0], -1)     # [C/dg, H*W]
                    lin = (yg * W + xg).reshape(-1)        # [K*Ho*Wo]
                    return flat[:, lin].reshape(
                        ag.shape[0], K, Ho, Wo)
                return jax.vmap(per_g)(ab, yb, xb)
            vals = jax.vmap(per_b)(a_g, ycl, xcl)          # [B,dg,C/dg,K,...]
            return vals * ok[:, :, None]

        v00 = tap(y0, x0)
        v01 = tap(y0, x0 + 1)
        v10 = tap(y0 + 1, x0)
        v11 = tap(y0 + 1, x0 + 1)
        wy_ = wy[:, :, None]
        wx_ = wx[:, :, None]
        sampled = (v00 * (1 - wy_) * (1 - wx_) + v01 * (1 - wy_) * wx_
                   + v10 * wy_ * (1 - wx_) + v11 * wy_ * wx_)
        sampled = jnp.where(valid[:, :, None], sampled, 0.0)
        if msk is not None:
            m_r = msk.reshape(B, dg, 1, K, Ho, Wo)
            sampled = sampled * m_r
        sampled = sampled.reshape(B, C, K, Ho, Wo)
        wk = w.reshape(w.shape[0], C // groups, K)
        if groups == 1:
            out = jnp.einsum("bckhw,ock->bohw", sampled, wk)
        else:
            sp = sampled.reshape(B, groups, C // groups, K, Ho, Wo)
            wg = wk.reshape(groups, w.shape[0] // groups, C // groups, K)
            out = jnp.einsum("bgckhw,gock->bgohw", sp, wg)
            out = out.reshape(B, w.shape[0], Ho, Wo)
        if b is not None:
            out = out + b.reshape(1, -1, 1, 1)
        return out

    args = [x, offset, weight]
    if mask is not None:
        args.append(mask)
    if bias is not None:
        args.append(bias)
    return apply("deformable_conv", impl, *args)
