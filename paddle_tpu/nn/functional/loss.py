"""Loss functionals.

Parity targets (reference: paddle/fluid/operators/): softmax_with_cross_entropy,
cross_entropy2, bce_loss, sigmoid_cross_entropy_with_logits, nll_loss,
kldiv_loss, smooth_l1_loss, huber_loss, hinge_loss, log_loss, mse (via ops),
margin_rank_loss, cos_sim, ctc/warpctc (deferred), sigmoid_focal_loss,
square_error_cost, npair/triplet-era losses.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...ops.dispatch import apply
from ...core.tensor import Tensor


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, name=None):
    """reference: operators/softmax_with_cross_entropy_op.cc +
    python/paddle/nn/functional/loss.py cross_entropy."""
    def impl(logits, lab, *w):
        logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax \
            else jnp.log(jnp.maximum(logits, 1e-30))
        if soft_label:
            loss = -jnp.sum(lab * logp, axis=axis)
        else:
            lab_i = lab.astype(jnp.int32)
            if lab_i.ndim == logp.ndim:  # [N,...,1] hard labels
                lab_i = jnp.squeeze(lab_i, axis)
            valid = lab_i != ignore_index
            safe = jnp.where(valid, lab_i, 0)
            picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, axis), axis)
            loss = -jnp.squeeze(picked, axis)
            if w:
                cw = jnp.take(w[0], safe)
                loss = loss * cw
            loss = jnp.where(valid, loss, 0.0)
            if reduction == "mean":
                if w:
                    denom = jnp.sum(jnp.where(valid, jnp.take(w[0], safe), 0.0))
                else:
                    denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
                return jnp.sum(loss) / denom
        return _reduce(loss, reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return apply("softmax_with_cross_entropy", impl, *args)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    out = cross_entropy(logits, label, soft_label=soft_label,
                        ignore_index=ignore_index, reduction="none", axis=axis)
    out = out.unsqueeze(axis)
    if return_softmax:
        from .activation import softmax as _softmax
        return out, _softmax(logits, axis=axis)
    return out


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    """reference: operators/nll_loss_op.cc (input is log-probabilities)."""
    return _nll(input, label, weight, ignore_index, reduction)


def _nll(input, label, weight, ignore_index, reduction):
    def impl(logp, lab, *w):
        lab_i = lab.astype(jnp.int32)
        valid = lab_i != ignore_index
        safe = jnp.where(valid, lab_i, 0)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), 1)
        loss = -jnp.squeeze(picked, 1)
        cw = jnp.take(w[0], safe) if w else jnp.ones_like(loss)
        loss = jnp.where(valid, loss * cw, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(jnp.where(valid, cw, 0.0)), 1e-12)
        return _reduce(loss, reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return apply("nll_loss", impl, *args)


def mse_loss(input, label, reduction="mean", name=None):
    return apply("mse_loss",
                 lambda a, b: _reduce((a - b) ** 2, reduction), input, label)


def l1_loss(input, label, reduction="mean", name=None):
    return apply("l1_loss",
                 lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label)


def square_error_cost(input, label):
    """reference: operators/squared_l2_distance_op / fluid.layers.square_error_cost."""
    return apply("square_error_cost", lambda a, b: (a - b) ** 2, input, label)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def impl(p, y, *w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return apply("bce_loss", impl, *args)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    """reference: operators/sigmoid_cross_entropy_with_logits_op.cc."""
    def impl(z, y, *extra):
        it = iter(extra)
        w = next(it) if weight is not None else None
        pw = next(it) if pos_weight is not None else None
        # stable: max(z,0) - z*y + log(1+exp(-|z|)) with pos_weight variant
        if pw is not None:
            log_w = (pw - 1) * y + 1
            loss = (1 - y) * z + log_w * (jnp.logaddexp(0.0, -jnp.abs(z))
                                          + jnp.maximum(-z, 0.0))
        else:
            loss = jnp.maximum(z, 0.0) - z * y + jnp.logaddexp(0.0, -jnp.abs(z))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)
    args = [logit, label] + [t for t in (weight, pos_weight) if t is not None]
    return apply("sigmoid_cross_entropy_with_logits", impl, *args)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    """reference: operators/sigmoid_focal_loss_op.cc."""
    def impl(z, y, *n):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0.0) - z * y + jnp.logaddexp(0.0, -jnp.abs(z))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if n:
            loss = loss / n[0]
        return _reduce(loss, reduction)
    args = [logit, label] + ([normalizer] if normalizer is not None else [])
    return apply("sigmoid_focal_loss", impl, *args)


def kl_div(input, label, reduction="mean", name=None):
    """reference: operators/kldiv_loss_op.cc (input is log-prob)."""
    def impl(logp, y):
        loss = jnp.where(y > 0, y * (jnp.log(jnp.maximum(y, 1e-30)) - logp), 0.0)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)
    return apply("kldiv_loss", impl, input, label)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    """reference: operators/smooth_l1_loss_op.cc / huber semantics."""
    def impl(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(loss, reduction)
    return apply("smooth_l1_loss", impl, input, label)


def huber_loss(input, label, delta=1.0):
    def impl(a, b):
        d = jnp.abs(a - b)
        return jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))
    return apply("huber_loss", impl, input, label)


def hinge_loss(input, label):
    return apply("hinge_loss",
                 lambda a, y: jnp.maximum(0.0, 1.0 - (2.0 * y - 1.0) * a), input, label)


def log_loss(input, label, epsilon=1e-4, name=None):
    return apply("log_loss",
                 lambda p, y: -y * jnp.log(p + epsilon)
                 - (1 - y) * jnp.log(1 - p + epsilon), input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return apply("margin_rank_loss",
                 lambda a, b, y: _reduce(jnp.maximum(0.0, -y * (a - b) + margin),
                                         reduction), input, other, label)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def impl(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.sqrt(jnp.sum(a * a, axis=axis)) * jnp.sqrt(jnp.sum(b * b, axis=axis))
        return num / jnp.maximum(den, eps)
    return apply("cos_sim", impl, x1, x2)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def impl(a, b, y):
        cos = jnp.sum(a * b, axis=1) / jnp.maximum(
            jnp.linalg.norm(a, axis=1) * jnp.linalg.norm(b, axis=1), 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return apply("cosine_embedding_loss", impl, input1, input2, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def impl(a, pos, neg):
        dp = jnp.power(jnp.sum(jnp.power(jnp.abs(a - pos), p), -1) + epsilon, 1 / p)
        dn = jnp.power(jnp.sum(jnp.power(jnp.abs(a - neg), p), -1) + epsilon, 1 / p)
        if swap:
            dsn = jnp.power(jnp.sum(jnp.power(jnp.abs(pos - neg), p), -1) + epsilon, 1 / p)
            dn = jnp.minimum(dn, dsn)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)
    return apply("triplet_margin_loss", impl, input, positive, negative)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    """reference: operators/label_smooth_op.cc."""
    def impl(y, *pd):
        k = y.shape[-1]
        if pd:
            return (1 - epsilon) * y + epsilon * pd[0]
        return (1 - epsilon) * y + epsilon / k
    args = [label] + ([prior_dist] if prior_dist is not None else [])
    return apply("label_smooth", impl, *args)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via dynamic-programming in log space (reference: warpctc op).
    log_probs: [T, N, C] (paddle layout), labels: [N, S]."""
    def impl(lp, lab, in_len, lab_len):
        T, N, C = lp.shape
        S = lab.shape[1]
        # extended label seq with blanks: length 2S+1
        ext = jnp.full((N, 2 * S + 1), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        ext_len = 2 * lab_len.astype(jnp.int32) + 1
        neg_inf = jnp.asarray(-1e30, lp.dtype)

        lp = jax.nn.log_softmax(lp, axis=-1)

        def emit(t):
            # [N, 2S+1] log prob of each extended symbol at time t
            return jnp.take_along_axis(lp[t], ext, axis=1)

        alpha0 = jnp.full((N, 2 * S + 1), neg_inf)
        alpha0 = alpha0.at[:, 0].set(emit(0)[:, 0])
        alpha0 = alpha0.at[:, 1].set(jnp.where(ext_len > 1, emit(0)[:, 1], neg_inf))

        same = jnp.concatenate(
            [jnp.zeros((N, 2), bool),
             ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, t):
            shift1 = jnp.concatenate([jnp.full((N, 1), neg_inf), alpha[:, :-1]], 1)
            shift2 = jnp.concatenate([jnp.full((N, 2), neg_inf), alpha[:, :-2]], 1)
            shift2 = jnp.where(same, neg_inf, shift2)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, shift1), shift2)
            new = merged + emit(t)
            keep = (t >= in_len)[:, None]
            return jnp.where(keep, alpha, new), None

        alphaT, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        idx_last = ext_len - 1
        ll_last = jnp.take_along_axis(alphaT, idx_last[:, None], 1)[:, 0]
        ll_prev = jnp.take_along_axis(alphaT, jnp.maximum(idx_last - 1, 0)[:, None], 1)[:, 0]
        loss = -jnp.logaddexp(ll_last, ll_prev)
        if norm_by_times:
            loss = loss / in_len.astype(loss.dtype)
        if reduction == "mean":
            return jnp.mean(loss / lab_len.astype(loss.dtype))
        return _reduce(loss, reduction)
    return apply("warpctc", impl, log_probs, labels, input_lengths, label_lengths)


def warpctc(input, label, blank=0, norm_by_times=False, input_length=None,
            label_length=None):
    """Fluid-era alias of ctc_loss (reference: operators/warpctc_op.cc;
    per-sequence losses, the op's raw output). Lengths default to the
    full padded extents."""
    import numpy as _np
    from ...core.tensor import Tensor as _T
    T_len = input.shape[0]
    S_len = label.shape[1]
    N = input.shape[1]
    if input_length is None:
        input_length = _T(_np.full((N,), T_len, _np.int64))
    if label_length is None:
        label_length = _T(_np.full((N,), S_len, _np.int64))
    return ctc_loss(input, label, input_length, label_length, blank=blank,
                    reduction="none", norm_by_times=norm_by_times)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None):
    """reference: paddle.nn.functional.hinge_embedding_loss — label in
    {1, -1}: loss = x if y==1 else max(0, margin - x)."""
    def impl(x, y):
        val = jnp.where(y > 0, x, jnp.maximum(0.0, margin - x))
        return _reduce(val, reduction)
    return apply("hinge_embedding_loss", impl, input, label)


def rank_loss(label, left, right, name=None):
    """reference: operators/rank_loss_op.cc — pairwise RankNet loss:
    C = log(1 + exp(o)) - o * label with o = left - right."""
    def impl(lab, l, r):
        o = l - r
        return jnp.log1p(jnp.exp(-jnp.abs(o))) + jnp.maximum(o, 0.0) \
            - o * lab
    return apply("rank_loss", impl, label, left, right)


def dice_loss(input, label, epsilon=1e-5, name=None):
    """reference: python/paddle/fluid/layers/nn.py dice_loss — 1 - 2|X∩Y| /
    (|X|+|Y|); input [N, ..., C] probabilities, label [N, ..., 1] ids."""
    def impl(x, y):
        num_classes = x.shape[-1]
        oh = jax.nn.one_hot(y.squeeze(-1), num_classes, dtype=x.dtype)
        x_flat = x.reshape(x.shape[0], -1)
        y_flat = oh.reshape(x.shape[0], -1)
        inter = jnp.sum(x_flat * y_flat, axis=1)
        union = jnp.sum(x_flat, axis=1) + jnp.sum(y_flat, axis=1)
        # epsilon on the denominator ONLY — fluid layers.nn dice_loss
        return jnp.mean(1.0 - (2.0 * inter) / (union + epsilon))
    return apply("dice_loss", impl, input, label)


def ctc_greedy_decoder(input, blank=None, input_length=None, padding_value=0):
    """reference: operators/ctc_align_op.cc + fluid layers
    ctc_greedy_decoder — argmax per step then collapse repeats/blanks.
    input: [T, N, C] log-probs (paddle warpctc layout), or [N, T, C]
    when ``input_length`` is given (the padded+lengths convention);
    returns (decoded [N, T], lengths)."""
    from ...ops import beam as _beam

    batch_major = input_length is not None

    def impl(lp):
        ids = jnp.argmax(lp, axis=-1)      # [T, N] or [N, T]
        return ids if batch_major else ids.T
    ids = apply("ctc_argmax", impl, input)
    b = blank if blank is not None else 0
    return _beam.ctc_align(ids, blank=b, merge_repeated=True,
                           padding_value=padding_value,
                           lengths=input_length)
