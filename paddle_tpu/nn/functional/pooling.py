"""Pooling via lax.reduce_window.

Parity targets: pool2d/pool3d (max/avg), max_pool2d_with_index, adaptive
pools (reference: paddle/fluid/operators/pool_op.cc,
max_pool2d_with_index_op). NCHW default layout.
"""
from __future__ import annotations

import builtins

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...ops.dispatch import apply


def _tuplize(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in (v if len(v) == n else [v[0]] * n))
    return tuple(int(v) for _ in range(n))


def _pool_nd(n, kind, x, kernel_size, stride, padding, ceil_mode,
             count_include_pad=True, channel_last=False):
    ks = _tuplize(kernel_size, n)
    st = _tuplize(stride if stride is not None else kernel_size, n)
    if isinstance(padding, str):
        pad_mode = padding.upper()
        pads = None
    else:
        pad_mode = None
        p = padding
        if isinstance(p, (list, tuple)) and len(p) == 2 * n:
            pads = [(int(p[2 * i]), int(p[2 * i + 1])) for i in range(n)]
        else:
            p = _tuplize(p, n)
            pads = [(v, v) for v in p]
        if ceil_mode:
            pads = [(lo, hi + s - 1) for (lo, hi), s in zip(pads, st)]

    def window_dims(a):
        if channel_last:
            return (1,) + ks + (1,), (1,) + st + (1,), \
                ([(0, 0)] + pads + [(0, 0)]) if pads is not None else pad_mode
        return (1, 1) + ks, (1, 1) + st, \
            ([(0, 0), (0, 0)] + pads) if pads is not None else pad_mode

    def impl(a):
        wd, ws, pd = window_dims(a)
        if kind == "max":
            # scalar init (not an array) keeps reduce_window on the monoid
            # primitive, which is the reverse-differentiable path under jit
            init = (-jnp.inf if jnp.issubdtype(a.dtype, jnp.floating)
                    else int(jnp.iinfo(a.dtype).min))
            return lax.reduce_window(a, init, lax.max, wd, ws, pd)
        s = lax.reduce_window(a, 0.0, lax.add, wd, ws, pd)
        all_zero = pads is not None and builtins.all(p == (0, 0) for p in pads)
        if count_include_pad or pd == "VALID" or all_zero:
            return s / np.prod(ks)
        ones = jnp.ones_like(a)
        cnt = lax.reduce_window(ones, 0.0, lax.add, wd, ws, pd)
        return s / cnt
    return apply(f"pool{n}d_{kind}", impl, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    return _pool_nd(1, "max", x, kernel_size, stride, padding, ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    out = _pool_nd(2, "max", x, kernel_size, stride, padding, ceil_mode,
                   channel_last=(data_format == "NHWC"))
    if return_mask:
        idx = _max_pool_indices(x, kernel_size, stride, padding, ceil_mode)
        return out, idx
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool_nd(3, "max", x, kernel_size, stride, padding, ceil_mode,
                    channel_last=(data_format == "NDHWC"))


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool_nd(1, "avg", x, kernel_size, stride, padding, ceil_mode,
                    count_include_pad=not exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool_nd(2, "avg", x, kernel_size, stride, padding, ceil_mode,
                    count_include_pad=not exclusive,
                    channel_last=(data_format == "NHWC"))


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool_nd(3, "avg", x, kernel_size, stride, padding, ceil_mode,
                    count_include_pad=not exclusive,
                    channel_last=(data_format == "NDHWC"))


def _max_pool_indices(x, kernel_size, stride, padding, ceil_mode):
    """Indices of maxima (flattened per-channel HW index), matching the
    reference max_pool2d_with_index op."""
    ks = _tuplize(kernel_size, 2)
    st = _tuplize(stride if stride is not None else kernel_size, 2)
    p = _tuplize(padding if not isinstance(padding, str) else 0, 2)

    def impl(a):
        n, c, h, w = a.shape
        hw_idx = jnp.arange(h * w, dtype=jnp.float32).reshape(1, 1, h, w)
        hw_idx = jnp.broadcast_to(hw_idx, a.shape)
        pads = [(0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])]

        def select(acc, cur):
            acc_v, acc_i = acc
            cur_v, cur_i = cur
            take_cur = cur_v > acc_v
            return (jnp.where(take_cur, cur_v, acc_v),
                    jnp.where(take_cur, cur_i, acc_i))
        init_v = jnp.asarray(-jnp.inf, a.dtype)
        init_i = jnp.asarray(-1.0, jnp.float32)
        v, i = lax.reduce_window((a, hw_idx), (init_v, init_i), select,
                                 (1, 1) + ks, (1, 1) + st, pads)
        return i.astype(jnp.int64)
    return apply("max_pool2d_index", impl, x)


def _adaptive_bounds(in_size, out_size):
    starts = (np.arange(out_size) * in_size) // out_size
    ends = np.ceil((np.arange(out_size) + 1) * in_size / out_size).astype(int)
    return starts, ends


def _adaptive_pool_nd(n, kind, x, output_size, channel_last=False):
    out_sz = _tuplize(output_size, n)

    def impl(a):
        spatial_off = (a.ndim - n - 1) if channel_last else (a.ndim - n)
        out = a
        # Uniform case: integer bins → plain strided pooling (fast path).
        uniform = builtins.all(
            a.shape[spatial_off + i] % out_sz[i] == 0 for i in range(n))
        if uniform:
            ks = tuple(a.shape[spatial_off + i] // out_sz[i] for i in range(n))
            wd = [1] * a.ndim
            st = [1] * a.ndim
            for i in range(n):
                wd[spatial_off + i] = ks[i]
                st[spatial_off + i] = ks[i]
            if kind == "max":
                return lax.reduce_window(a, -jnp.inf,
                                         lax.max, tuple(wd), tuple(st), "VALID")
            s = lax.reduce_window(a, 0.0, lax.add,
                                  tuple(wd), tuple(st), "VALID")
            return s / np.prod(ks)
        # General case: gather per output bin along each dim.
        for i in range(n):
            dim = spatial_off + i
            starts, ends = _adaptive_bounds(out.shape[dim], out_sz[i])
            slices = []
            for s0, e0 in zip(starts, ends):
                sl = jnp.take(out, jnp.arange(s0, e0), axis=dim)
                red = jnp.max(sl, axis=dim, keepdims=True) if kind == "max" \
                    else jnp.mean(sl, axis=dim, keepdims=True)
                slices.append(red)
            out = jnp.concatenate(slices, axis=dim)
        return out
    return apply(f"adaptive_pool{n}d_{kind}", impl, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool_nd(1, "avg", x, output_size)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool_nd(2, "avg", x, output_size,
                             channel_last=(data_format == "NHWC"))


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool_nd(3, "avg", x, output_size,
                             channel_last=(data_format == "NDHWC"))


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool_nd(1, "max", x, output_size)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool_nd(2, "max", x, output_size)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool_nd(3, "max", x, output_size)
