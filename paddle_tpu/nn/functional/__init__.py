"""paddle.nn.functional parity namespace."""
from .activation import *  # noqa: F401,F403
from .conv import (conv1d, conv2d, conv3d, conv1d_transpose,  # noqa: F401
                   conv2d_transpose, conv3d_transpose, deformable_conv)
from .pooling import (max_pool1d, max_pool2d, max_pool3d, avg_pool1d,  # noqa: F401
                      avg_pool2d, avg_pool3d, adaptive_avg_pool1d,
                      adaptive_avg_pool2d, adaptive_avg_pool3d,
                      adaptive_max_pool1d, adaptive_max_pool2d,
                      adaptive_max_pool3d)
from .norm import (batch_norm, layer_norm, instance_norm, group_norm,  # noqa: F401
                   local_response_norm, normalize)
from .loss import (cross_entropy, softmax_with_cross_entropy, nll_loss,  # noqa: F401
                   mse_loss, l1_loss, square_error_cost, binary_cross_entropy,
                   binary_cross_entropy_with_logits, sigmoid_focal_loss,
                   kl_div, smooth_l1_loss, huber_loss, hinge_loss, log_loss,
                   margin_ranking_loss, cosine_similarity,
                   cosine_embedding_loss, triplet_margin_loss, label_smooth,
                   ctc_loss,
                   warpctc, hinge_embedding_loss, rank_loss,
                   dice_loss, ctc_greedy_decoder)
from .common import (linear, dropout, dropout2d, dropout3d, alpha_dropout,  # noqa: F401
                     embedding, one_hot, interpolate, upsample, grid_sample,
                     affine_grid, bilinear, pad, temporal_shift,
                     sequence_mask, diag_embed, unfold, npair_loss)
from .sampled import (hsigmoid_loss, hierarchical_sigmoid, nce,  # noqa: F401
                      class_center_sample, sampling_id, sample_logits)
from ...ops.pallas_attention import flash_attention  # noqa: F401
from ...ops.manipulation import pixel_shuffle, pixel_unshuffle  # noqa: F401


# -- inplace-variant aliases + beam re-export (reference: functional __all__)
from ...ops.beam import gather_tree  # noqa: F401,E402


def _inplace(fn, x, *a, **k):
    out = fn(x, *a, **k)
    x._swap_payload(out)     # tape-recorded inplace (core/tensor.py)
    return x


def tanh_(x, name=None):
    from ...ops.math import tanh as _t
    return _inplace(_t, x)


def elu_(x, alpha=1.0, name=None):
    return _inplace(elu, x, alpha)


def softmax_(x, axis=-1, dtype=None, name=None):
    out = softmax(x, axis)
    if dtype is not None:
        from ...ops.manipulation import cast
        out = cast(out, dtype)
    x._swap_payload(out)
    return x
