"""Functional activations (paddle.nn.functional parity).

Reference: paddle/fluid/operators/activation_op.cc (FOR_EACH_ACTIVATION_OP
macro family, SURVEY Appendix A) — the reference registers each as a C++/CUDA
kernel pair; here each is one jnp expression XLA fuses into neighbours.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...ops.dispatch import apply
from ...core.tensor import Tensor

__all__ = [
    "relu", "relu_", "relu6", "gelu", "sigmoid", "tanh", "softmax",
    "log_softmax", "leaky_relu", "elu", "selu", "celu", "silu", "swish",
    "mish", "hardswish", "hardsigmoid", "hardtanh", "hardshrink",
    "softshrink", "tanhshrink", "softplus", "softsign", "prelu", "rrelu",
    "maxout", "thresholded_relu", "log_sigmoid", "glu", "gumbel_softmax",
]


def relu(x, name=None):
    return apply("relu", jax.nn.relu, x)


def relu_(x, name=None):
    x._swap_payload(relu(x))
    return x


def relu6(x, name=None):
    return apply("relu6", lambda a: jnp.clip(a, 0.0, 6.0), x)


def gelu(x, approximate=False, name=None):
    return apply("gelu", lambda a: jax.nn.gelu(a, approximate=approximate), x)


def sigmoid(x, name=None):
    return apply("sigmoid", jax.nn.sigmoid, x)


def tanh(x, name=None):
    return apply("tanh", jnp.tanh, x)


def softmax(x, axis=-1, dtype=None, name=None):
    def impl(a):
        if dtype is not None:
            a = a.astype(np.dtype(dtype))
        return jax.nn.softmax(a, axis=axis)
    return apply("softmax", impl, x)


def log_softmax(x, axis=-1, dtype=None, name=None):
    def impl(a):
        if dtype is not None:
            a = a.astype(np.dtype(dtype))
        return jax.nn.log_softmax(a, axis=axis)
    return apply("log_softmax", impl, x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply("leaky_relu", lambda a: jax.nn.leaky_relu(a, negative_slope), x)


def elu(x, alpha=1.0, name=None):
    return apply("elu", lambda a: jax.nn.elu(a, alpha), x)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply("selu", lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), x)


def celu(x, alpha=1.0, name=None):
    return apply("celu", lambda a: jax.nn.celu(a, alpha), x)


def silu(x, name=None):
    return apply("silu", jax.nn.silu, x)


def swish(x, name=None):
    return silu(x)


def mish(x, name=None):
    return apply("mish", lambda a: a * jnp.tanh(jax.nn.softplus(a)), x)


def hardswish(x, name=None):
    return apply("hard_swish", lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, x)


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply("hard_sigmoid", lambda a: jnp.clip(a * slope + offset, 0.0, 1.0), x)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply("brelu", lambda a: jnp.clip(a, min, max), x)


def hardshrink(x, threshold=0.5, name=None):
    return apply("hard_shrink", lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), x)


def softshrink(x, threshold=0.5, name=None):
    return apply("softshrink",
                 lambda a: jnp.where(a > threshold, a - threshold,
                                     jnp.where(a < -threshold, a + threshold, 0.0)), x)


def tanhshrink(x, name=None):
    return apply("tanh_shrink", lambda a: a - jnp.tanh(a), x)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply("softplus",
                 lambda a: jnp.where(a * beta > threshold, a,
                                     jax.nn.softplus(a * beta) / beta), x)


def softsign(x, name=None):
    return apply("softsign", jax.nn.soft_sign, x)


def prelu(x, weight, data_format="NCHW", name=None):
    def impl(a, w):
        if w.size == 1:
            return jnp.where(a > 0, a, w.reshape(()) * a)
        shape = [1] * a.ndim
        ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
        shape[ch_axis] = w.size
        return jnp.where(a > 0, a, w.reshape(shape) * a)
    return apply("prelu", impl, x, weight)


def rrelu(x, lower=0.125, upper=0.3333333, training=False, name=None):
    from ...core import generator as _gen
    if training:
        key = _gen.next_key()
        return apply("rrelu",
                     lambda a: jnp.where(
                         a >= 0, a,
                         a * jax.random.uniform(key, a.shape, a.dtype, lower, upper)), x)
    mid = (lower + upper) / 2.0
    return apply("rrelu", lambda a: jnp.where(a >= 0, a, a * mid), x)


def maxout(x, groups, axis=1, name=None):
    def impl(a):
        s = list(a.shape)
        c = s[axis]
        new = s[:axis] + [c // groups, groups] + s[axis + 1:]
        return jnp.max(a.reshape(new), axis=axis + 1)
    return apply("maxout", impl, x)


def thresholded_relu(x, threshold=1.0, name=None):
    return apply("thresholded_relu", lambda a: jnp.where(a > threshold, a, 0.0), x)


def log_sigmoid(x, name=None):
    return apply("logsigmoid", jax.nn.log_sigmoid, x)


def glu(x, axis=-1, name=None):
    return apply("glu", lambda a: jax.nn.glu(a, axis=axis), x)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core import generator as _gen
    key = _gen.next_key()

    def impl(a):
        g = jax.random.gumbel(key, a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False)
            # straight-through: forward=y_hard, backward=softmax
            y = jax.lax.stop_gradient(y_hard - y) + y
        return y
    return apply("gumbel_softmax", impl, x)
