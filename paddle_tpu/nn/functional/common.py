"""Common functionals: linear, dropout, embedding, interpolate, etc.

Parity targets: fc/matmul+bias (reference: operators/mul_op.cc + fc),
dropout (dropout_op.cc), lookup_table_v2 (embedding), interp family
(bilinear_interp_v2 etc.), grid_sample, affine_grid, one_hot, cosine ops.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...ops.dispatch import apply
from ...core.tensor import Tensor
from ...core import generator as _gen
from ...ops.manipulation import pad as _pad  # re-export target
from .activation import *  # noqa: F401,F403 (paddle exposes these under F too)
from ...ops.manipulation import unfold  # noqa: F401


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b with paddle weight layout [in, out]
    (reference: python/paddle/nn/functional/common.py linear →  matmul_v2 +
    elementwise_add)."""
    if bias is not None:
        return apply("linear", lambda a, w, b: jnp.matmul(a, w) + b, x, weight, bias)
    return apply("linear", lambda a, w: jnp.matmul(a, w), x, weight)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    """reference: operators/dropout_op.cc (two modes preserved)."""
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply("dropout", lambda a: a * (1.0 - p), x)
        return x
    key = _gen.next_key()

    def impl(a):
        shape = list(a.shape)
        if axis is not None:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)
    return apply("dropout", impl, x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    return dropout(x, p, axis=[0, 1] if data_format == "NCHW" else [0, 3],
                   training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    return dropout(x, p, axis=[0, 1] if data_format == "NCDHW" else [0, 4],
                   training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    key = _gen.next_key()

    def impl(a):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        coef_a = (q + alpha_p ** 2 * q * p) ** -0.5
        coef_b = -coef_a * alpha_p * p
        return coef_a * jnp.where(keep, a, alpha_p) + coef_b
    return apply("alpha_dropout", impl, x)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """reference: operators/lookup_table_v2_op.cc. `sparse` selects
    SelectedRows grads in the reference; XLA handles gather/scatter-add
    fusion so it is accepted and ignored."""
    def impl(w, i):
        out = jnp.take(w, i.astype(jnp.int32), axis=0)
        if padding_idx is not None:
            mask = (i == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out
    return apply("lookup_table_v2", impl, weight, x)


def one_hot(x, num_classes, name=None):
    from ...ops.creation import one_hot as _oh
    return _oh(x, num_classes)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW", name=None):
    """reference: operators/interpolate_v2_op.cc (nearest/bilinear/bicubic/
    trilinear/area)."""
    mode = mode.lower()
    channel_last = data_format in ("NHWC", "NWC", "NDHWC")

    def out_shape(a):
        spatial = a.shape[1:-1] if channel_last else a.shape[2:]
        if size is not None:
            s = size
            if isinstance(s, Tensor):
                s = s.numpy().tolist()
            return tuple(int(v.item() if isinstance(v, Tensor) else v) for v in
                         (s if isinstance(s, (list, tuple)) else [s]))
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
            else [scale_factor] * len(spatial)
        return tuple(int(d * f) for d, f in zip(spatial, sf))

    jax_method = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
                  "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]

    def impl(a):
        tgt = out_shape(a)
        if channel_last:
            full = (a.shape[0],) + tgt + (a.shape[-1],)
        else:
            full = a.shape[:2] + tgt
        if mode == "nearest":
            # paddle nearest uses floor on src index = i * scale
            idx = []
            spatial_off = 1 if channel_last else 2
            out = a
            for d, t in enumerate(tgt):
                src = a.shape[spatial_off + d]
                ii = jnp.floor(jnp.arange(t) * (src / t)).astype(jnp.int32)
                out = jnp.take(out, ii, axis=spatial_off + d)
            return out
        if align_corners:
            # jax.image.resize has no align_corners; do coordinate remap
            spatial_off = 1 if channel_last else 2
            out = a
            for d, t in enumerate(tgt):
                src = out.shape[spatial_off + d]
                if t == 1 or src == 1:
                    coords = jnp.zeros(t)
                else:
                    coords = jnp.linspace(0, src - 1, t)
                i0 = jnp.floor(coords).astype(jnp.int32)
                i1 = jnp.minimum(i0 + 1, src - 1)
                w1 = (coords - i0).astype(a.dtype)
                g0 = jnp.take(out, i0, axis=spatial_off + d)
                g1 = jnp.take(out, i1, axis=spatial_off + d)
                bshape = [1] * out.ndim
                bshape[spatial_off + d] = t
                w1 = w1.reshape(bshape)
                out = g0 * (1 - w1) + g1 * w1
            return out
        return jax.image.resize(a, full, method=jax_method)
    return apply("interpolate_v2", impl, x)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format, name)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """reference: operators/grid_sampler_op.cc. x: [N,C,H,W], grid: [N,Hg,Wg,2]."""
    def impl(a, g):
        n, c, h, w = a.shape
        gx, gy = g[..., 0], g[..., 1]
        if align_corners:
            fx = (gx + 1) * 0.5 * (w - 1)
            fy = (gy + 1) * 0.5 * (h - 1)
        else:
            fx = ((gx + 1) * w - 1) * 0.5
            fy = ((gy + 1) * h - 1) * 0.5

        def sample(ix, iy):
            inside = (ix >= 0) & (ix < w) & (iy >= 0) & (iy < h)
            cx = jnp.clip(ix, 0, w - 1)
            cy = jnp.clip(iy, 0, h - 1)
            # batch gather: a[n, :, cy, cx]
            bidx = jnp.arange(n).reshape(n, 1, 1)
            vals = a[bidx, :, cy, cx]          # [N,Hg,Wg,C]
            vals = jnp.moveaxis(vals, -1, 1)   # [N,C,Hg,Wg]
            if padding_mode == "zeros":
                vals = jnp.where(inside[:, None], vals, 0.0)
            return vals

        x0 = jnp.floor(fx).astype(jnp.int32)
        y0 = jnp.floor(fy).astype(jnp.int32)
        if mode == "nearest":
            return sample(jnp.round(fx).astype(jnp.int32),
                          jnp.round(fy).astype(jnp.int32))
        x1, y1 = x0 + 1, y0 + 1
        wx = (fx - x0).astype(a.dtype)[:, None]
        wy = (fy - y0).astype(a.dtype)[:, None]
        v00 = sample(x0, y0)
        v01 = sample(x1, y0)
        v10 = sample(x0, y1)
        v11 = sample(x1, y1)
        return (v00 * (1 - wx) * (1 - wy) + v01 * wx * (1 - wy)
                + v10 * (1 - wx) * wy + v11 * wx * wy)
    return apply("grid_sampler", impl, x, grid)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """reference: operators/affine_grid_op.cc."""
    if isinstance(out_shape, Tensor):
        out_shape = out_shape.numpy().tolist()
    n, c, h, w = [int(v) for v in out_shape]

    def impl(th):
        if align_corners:
            xs = jnp.linspace(-1, 1, w)
            ys = jnp.linspace(-1, 1, h)
        else:
            xs = (jnp.arange(w) * 2 + 1) / w - 1
            ys = (jnp.arange(h) * 2 + 1) / h - 1
        gx, gy = jnp.meshgrid(xs, ys)  # [H,W]
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)  # [H,W,3]
        out = jnp.einsum("hwk,njk->nhwj", base, th)  # theta [N,2,3]
        return out
    return apply("affine_grid", impl, theta)


def bilinear(x1, x2, weight, bias=None, name=None):
    """reference: operators/bilinear_tensor_product_op.cc."""
    def impl(a, b, w, *bi):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bi:
            out = out + bi[0]
        return out
    args = [x1, x2, weight] + ([bias] if bias is not None else [])
    return apply("bilinear_tensor_product", impl, *args)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    return _pad(x, pad, mode, value, data_format, name)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    """reference: operators/temporal_shift_op.cc."""
    def impl(a):
        nt, c, h, w = a.shape
        n = nt // seg_num
        v = a.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate([v[:, 1:, :fold], jnp.zeros_like(v[:, :1, :fold])], 1)
        right = jnp.concatenate([jnp.zeros_like(v[:, :1, fold:2 * fold]),
                                 v[:, :-1, fold:2 * fold]], 1)
        rest = v[:, :, 2 * fold:]
        return jnp.concatenate([left, right, rest], 2).reshape(nt, c, h, w)
    return apply("temporal_shift", impl, x)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def impl(a, p, y):
        sim = jnp.matmul(a, p.T)
        y = y.reshape(-1, 1)
        tgt = (y == y.T).astype(sim.dtype)
        tgt = tgt / jnp.sum(tgt, axis=1, keepdims=True)
        logp = jax.nn.log_softmax(sim, axis=1)
        ce = -jnp.mean(jnp.sum(tgt * logp, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, 1)) + jnp.mean(jnp.sum(p * p, 1))) / 2
        return ce + reg
    return apply("npair_loss", impl, anchor, positive, labels)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """reference: operators/sequence_ops/sequence_mask_op.cc."""
    d = np.dtype(dtype) if not isinstance(dtype, np.dtype) else dtype

    def impl(lens):
        m = maxlen
        if m is None:
            m = int(np.asarray(lens).max()) if not isinstance(lens, jax.core.Tracer) \
                else lens.shape[-1]
        rng = jnp.arange(m)
        return (rng < lens[..., None]).astype(d)
    return apply("sequence_mask", impl, x)


def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    from ...ops.creation import diag_embed as _de
    return _de(x, offset, dim1, dim2)
