"""Sampled / tree-structured classification heads.

The reference implements these as single CPU/GPU kernels that mix RNG,
gather and a tiny amount of math (nce_op.h:80, hierarchical_sigmoid_op.h,
class_center_sample_op.cu, sample_logits_op.cc). TPU-first the split is
different: the RNG uses the framework Generator's key stream, the gathers
are plain jnp indexing, and everything stays fixed-shape so the whole head
fuses into the surrounding jit region.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core import generator as _gen
from ...core.tensor import Tensor
from ...ops.dispatch import apply, raw as _raw

__all__ = ["hsigmoid_loss", "hierarchical_sigmoid", "nce",
           "class_center_sample", "sampling_id", "sample_logits"]


# -- hierarchical sigmoid -----------------------------------------------------

def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """reference: operators/hierarchical_sigmoid_op.cc + math/
    matrix_bit_code.h SimpleCode (``code = label + num_classes``,
    ``calc_index(b) = (code >> (b+1)) - 1``, ``calc_bit(b) = code & (1<<b)``).

    Default (no path_table) builds the complete binary tree the reference's
    SimpleCodeTable encodes; custom trees pass ``path_table`` [N, L] (node
    ids, -1 padding) and ``path_code`` [N, L] (0/1 bits). ``is_sparse`` is
    accepted for API parity — with XLA the dense path's gather/scatter is
    already sparse in effect.

    input [N, D]; label [N] or [N, 1]; weight [num_classes-1, D];
    bias [num_classes-1] or [num_classes-1, 1]. Returns [N, 1].
    """
    C = int(num_classes)
    if path_table is None:
        # Bit budget: codes lie in [C, 2C-1] so floor(log2) <= ceil(log2(C)).
        max_len = max(int(np.ceil(np.log2(max(C, 2)))) + 1, 1)

        def impl(x, lab, w, *maybe_b):
            lab = lab.reshape(-1).astype(jnp.int32)
            code = lab + C
            bits = jnp.arange(max_len, dtype=jnp.int32)
            # bit b is a path edge iff b < floor(log2(code)), i.e. iff the
            # code still has set bits above position b — pure integer test
            # (float32 log2 is off-by-one near powers of two past 2^24)
            valid = (code[:, None] >> (bits[None, :] + 1)) > 0   # [N, L]
            idx = jnp.where(valid, (code[:, None] >> (bits[None, :] + 1)) - 1, 0)
            t = ((code[:, None] >> bits[None, :]) & 1).astype(x.dtype)
            pre = jnp.einsum("nd,nld->nl", x, w[idx])            # [N, L]
            if maybe_b:
                pre = pre + maybe_b[0].reshape(-1)[idx]
            pre = jnp.clip(pre, -40.0, 40.0)
            loss = jax.nn.softplus(pre) - t * pre                # BCE-with-logits
            return jnp.sum(jnp.where(valid, loss, 0), axis=1, keepdims=True)
        args = (input, label, weight) + ((bias,) if bias is not None else ())
        return apply("hsigmoid_loss", impl, *args)

    def impl(x, lab, w, pt, pc, *maybe_b):
        pt = pt.astype(jnp.int32)
        valid = pt >= 0
        idx = jnp.where(valid, pt, 0)
        t = pc.astype(x.dtype)
        pre = jnp.einsum("nd,nld->nl", x, w[idx])
        if maybe_b:
            pre = pre + maybe_b[0].reshape(-1)[idx]
        pre = jnp.clip(pre, -40.0, 40.0)
        loss = jax.nn.softplus(pre) - t * pre
        return jnp.sum(jnp.where(valid, loss, 0), axis=1, keepdims=True)
    args = (input, label, weight, path_table, path_code) + (
        (bias,) if bias is not None else ())
    return apply("hsigmoid_loss", impl, *args)


def hierarchical_sigmoid(input, label, num_classes, weight, bias=None,
                         path_table=None, path_code=None, is_sparse=False,
                         name=None):
    """Fluid-era alias (reference: fluid/layers/nn.py hsigmoid)."""
    return hsigmoid_loss(input, label, num_classes, weight, bias,
                         path_table, path_code, is_sparse)


# -- NCE ----------------------------------------------------------------------

def _log_uniform_prob(c, range_max):
    """P(c) under LogUniformSampler(range_max): support [0, range_max-1],
    normalised by log(range_max + 1)."""
    cf = c.astype(jnp.float32)
    return jnp.log((cf + 2.0) / (cf + 1.0)) / np.log(range_max + 1.0)


def _sample_classes(key, shape, num_classes, sampler, range_max=None):
    if sampler == "uniform":
        s = jax.random.randint(key, shape, 0, num_classes)
        p = jnp.full(shape, 1.0 / num_classes, jnp.float32)
        return s, p
    if sampler == "log_uniform":
        r = num_classes if range_max is None else range_max
        u = jax.random.uniform(key, shape)
        s = jnp.clip(
            jnp.exp(u * np.log(r + 1.0)).astype(jnp.int32) - 1,
            0, r - 1)
        return s, _log_uniform_prob(s, r)
    raise ValueError(f"nce: unknown sampler {sampler!r} "
                     "(uniform | log_uniform | custom_dist)")


def nce(input, label, weight, bias=None, num_neg_samples=10,
        num_total_classes=None, sampler="uniform", custom_dist=None,
        seed=0, sample_weight=None, name=None):
    """reference: operators/nce_op.h:80 (NCEKernel::Compute).

    Per row: sample ``num_neg_samples`` negative classes, compute
    ``o = sigmoid(x . w_c + b_c)`` for the true and sampled classes, and

        cost = sum_true  -log(o / (o + b))  +  sum_neg -log(b / (o + b))

    with ``b = P(class) * num_neg_samples`` (nce_op.h:203-205). The
    reference samples on the host with a seeded std::mt19937; here the
    negatives come from the Generator key stream (pass ``seed`` for a
    fixed draw). Returns cost [N, 1].
    """
    C = int(num_total_classes if num_total_classes is not None
            else _raw(weight).shape[0])
    k = int(num_neg_samples)
    key = _gen.next_key() if not seed else jax.random.PRNGKey(int(seed))

    if sampler == "custom_dist":
        probs = jnp.asarray(np.asarray(custom_dist, np.float32))

    def impl(x, lab, w, *rest):
        rest = list(rest)
        b_vec = rest.pop(0) if bias is not None else None
        sw = rest.pop(0) if sample_weight is not None else None
        lab = lab.reshape(x.shape[0], -1).astype(jnp.int32)     # [N, T]
        if sampler == "custom_dist":
            neg = jax.random.categorical(key, jnp.log(probs + 1e-30)[None, :],
                                         shape=(x.shape[0], k))
            neg_p = probs[neg]
        else:
            # nce_op.h constructs LogUniformSampler(num_total_classes - 1):
            # support [0, C-2], normalised by log(C) — NOT the
            # sample_logits sampler's LogUniformSampler(C)
            neg, neg_p = _sample_classes(key, (x.shape[0], k), C, sampler,
                                         range_max=C - 1)
        classes = jnp.concatenate([lab, neg], axis=1)           # [N, T+k]
        if sampler == "custom_dist":
            p = probs[classes]
        elif sampler == "uniform":
            p = jnp.full(classes.shape, 1.0 / C, jnp.float32)
        else:
            p = _log_uniform_prob(classes, C - 1)
        logits = jnp.einsum("nd,nsd->ns", x, w[classes])
        if b_vec is not None:
            logits = logits + b_vec.reshape(-1)[classes]
        o = jax.nn.sigmoid(logits)
        bq = (p * k).astype(o.dtype)
        T = lab.shape[1]
        is_true = jnp.arange(classes.shape[1]) < T
        cost = jnp.where(is_true[None, :],
                         -jnp.log(o / (o + bq) + 1e-12),
                         -jnp.log(bq / (o + bq) + 1e-12))
        out = jnp.sum(cost, axis=1, keepdims=True)
        if sw is not None:
            out = out * sw.reshape(-1, 1)
        return out
    args = [input, label, weight]
    if bias is not None:
        args.append(bias)
    if sample_weight is not None:
        args.append(sample_weight)
    return apply("nce", impl, *args)


# -- class_center_sample ------------------------------------------------------

def class_center_sample(label, num_classes, num_samples, group=None,
                        name=None):
    """reference: operators/class_center_sample_op.cu (PartialFC sampling,
    python/paddle/nn/functional/common.py class_center_sample).

    Keeps every positive class center and pads with uniformly sampled
    negatives up to ``num_samples``; returns (remapped_label [N],
    sampled_class_center [num_samples]). Fixed-shape by construction:
    positives sort first via a -1 key, negatives carry a random uniform
    key, one argsort picks the sample set. Requires num_samples >= the
    number of distinct positive classes (reference enforces the same).
    """
    C, S = int(num_classes), int(num_samples)
    if S > C:
        raise ValueError(f"class_center_sample: num_samples={S} > "
                         f"num_classes={C}")
    lab_raw = _raw(label)
    if not isinstance(lab_raw, jax.core.Tracer):
        npos = int(np.unique(np.asarray(lab_raw)).size)
        if npos > S:
            raise ValueError(
                f"class_center_sample: batch holds {npos} distinct positive "
                f"classes but num_samples={S}; every positive center must "
                f"fit (reference enforces the same)")
    key = _gen.next_key()

    def impl(lab):
        lab = lab.reshape(-1).astype(jnp.int32)
        pos = jnp.zeros((C,), jnp.bool_).at[lab].set(True)
        u = jax.random.uniform(key, (C,))
        order = jnp.argsort(jnp.where(pos, -1.0, u))
        sampled = jnp.sort(order[:S])                 # ascending like the ref
        remap = jnp.zeros((C,), jnp.int32).at[sampled].set(
            jnp.arange(S, dtype=jnp.int32))
        return remap[lab], sampled
    return apply("class_center_sample", impl, label)


# -- sampling_id / sample_logits ---------------------------------------------

def sampling_id(x, min=0, max=None, seed=0, dtype="int64", name=None):
    """reference: operators/sampling_id_op.cc — one categorical draw per
    row of a probability matrix [N, C]."""
    key = _gen.next_key() if not seed else jax.random.PRNGKey(int(seed))

    def impl(p):
        return jax.random.categorical(
            key, jnp.log(jnp.maximum(p, 1e-30)), axis=-1).astype(jnp.int64)
    return apply("sampling_id", impl, x)


def sample_logits(logits, label, num_samples, uniq=True,
                  remove_accidental_hits=True, seed=0, name=None):
    """reference: operators/sample_logits_op.cc — sampled-softmax
    preparation: Samples = [true | log-uniform negatives], sampled logits
    adjusted by -log(q(class)) (subtract-log-q), accidental hits masked to
    -1e20. Returns (sampled_logits [N, T+S], sampled_label [N, T] — the
    in-sample positions of the true classes, i.e. arange(T)).

    ``uniq=True`` (default, like the reference's unique sampler) draws the
    negatives *without replacement* per row via Gumbel top-k over the
    log-uniform weights; the subtract-log-q correction then uses the
    without-replacement inclusion probability q = 1 - (1-p)^S. uniq=False
    is S independent draws with q = p.
    """
    S = int(num_samples)
    key = _gen.next_key() if not seed else jax.random.PRNGKey(int(seed))

    def impl(lg, lab):
        n, C = lg.shape
        lab = lab.reshape(n, -1).astype(jnp.int32)              # [N, T]
        T = lab.shape[1]
        if uniq:
            # Gumbel top-k = weighted sampling without replacement
            logp = jnp.log(_log_uniform_prob(jnp.arange(C), C))  # [C]
            g = jax.random.gumbel(key, (n, C))
            _, neg = jax.lax.top_k(logp[None, :] + g, S)         # [N, S]
            neg = neg.astype(jnp.int32)
        else:
            neg, _ = _sample_classes(key, (n, S), C, "log_uniform")
        classes = jnp.concatenate([lab, neg], axis=1)           # [N, T+S]
        p = _log_uniform_prob(classes, C)
        if uniq:
            q = -jnp.expm1(S * jnp.log1p(-p))   # P(class in top-k sample)
        else:
            q = p
        s_logits = jnp.take_along_axis(lg, classes, axis=1) - jnp.log(q)
        if remove_accidental_hits:
            hit = (neg[:, :, None] == lab[:, None, :]).any(-1)  # [N, S]
            s_logits = s_logits.at[:, T:].set(
                jnp.where(hit, -1e20, s_logits[:, T:]))
        s_label = jnp.tile(jnp.arange(T, dtype=jnp.int64)[None, :], (n, 1))
        return s_logits, s_label
    return apply("sample_logits", impl, logits, label)
