"""paddle.nn.utils parity."""
from ..utils_weight_norm import weight_norm, remove_weight_norm
from ..utils_weight_norm import spectral_norm_fn as spectral_norm


def parameters_to_vector(parameters, name=None):
    from ...ops import manipulation
    return manipulation.concat([p.flatten() for p in parameters], axis=0)


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    for p in parameters:
        n = p.size
        p.set_value(vec[offset:offset + n].reshape(p.shape))
        offset += n
