"""Transformer layers.

Reference: python/paddle/nn/layer/transformer.py — MultiHeadAttention (:109),
TransformerEncoderLayer (:431), TransformerEncoder (:551),
TransformerDecoderLayer (:623), TransformerDecoder (:768), Transformer (:859).
The attention core is a single fused jnp composition (one XLA fusion group /
flash-attention Pallas kernel under jit) instead of the reference's chain of
matmul/scale/softmax/dropout ops.
"""
from __future__ import annotations

import collections
import numpy as np

from .layer_base import Layer
from .container import LayerList
from .layers_common import Linear, Dropout, LayerNorm
from . import functional as F
from ..ops import creation, manipulation, math as _math
from ..core.tensor import Tensor


class _CausalMask:
    """Sentinel attn_mask value declaring "standard causal mask" without
    materialising the [L, L] additive tensor. Lets MultiHeadAttention
    route to the fused flash kernel (which applies causality inside the
    kernel) and lets the dense path build the triu mask lazily."""

    def __repr__(self):
        return "<causal attention mask>"


CAUSAL_MASK = _CausalMask()

# measured crossover on the v5e chip (docs/perf_notes.md round 4): XLA
# dense attention wins up to S=2048, the Pallas flash kernel wins 1.39x
# at 4096 and is the only option at 8192 (dense materialises [B,H,S,S])
FLASH_CROSSOVER = 4096


def _convert_attention_mask(attn_mask, dtype):
    """reference: transformer.py _convert_attention_mask — bool mask →
    additive -inf mask."""
    if attn_mask is None:
        return None
    if attn_mask.dtype == np.dtype("bool"):
        return (attn_mask.astype(dtype) - 1.0) * 1e9
    return attn_mask.astype(dtype)


class MultiHeadAttention(Layer):
    """reference: transformer.py:109.

    TPU extension: ``attn_impl`` selects the attention core —
    ``"auto"`` (default) uses the Pallas flash kernel when the sequence
    reaches FLASH_CROSSOVER and the call is eligible (no attention-prob
    dropout in training mode, no need_weights, no incremental cache, and
    the mask is None or the CAUSAL_MASK sentinel), ``"flash"`` forces it
    for any eligible call, ``"dense"`` never uses it. The reference has
    no such knob — its fused attention lives in external libraries."""

    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None,
                 attn_impl="auto", attn_blocks=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.kdim = kdim or embed_dim
        self.vdim = vdim or embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        if attn_impl not in ("auto", "dense", "flash"):
            raise ValueError(f"attn_impl {attn_impl!r} not in "
                             "('auto', 'dense', 'flash')")
        self.attn_impl = attn_impl
        # explicit (block_q, block_k) for the flash kernel; None defers to
        # the paddle_tpu.tuner winner cache (falling back to the kernel's
        # historical 128)
        if attn_blocks is not None:
            attn_blocks = (int(attn_blocks[0]), int(attn_blocks[1]))
        self.attn_blocks = attn_blocks
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _flash_eligible(self, attn_mask, cache, seq_len):
        if self.attn_impl == "dense":
            return False
        if (self.need_weights or cache is not None
                or (self.dropout and self.training)):
            return False
        if not (attn_mask is None or isinstance(attn_mask, _CausalMask)):
            return False           # arbitrary additive masks: dense only
        if self.head_dim % 8 != 0:
            return False           # lane-tile constraint on the kernel
        if self.attn_impl == "flash":
            return True
        return seq_len >= FLASH_CROSSOVER

    def _split_heads(self, x):
        # [B, L, E] -> [B, H, L, D]
        b, l = x.shape[0], x.shape[1]
        return manipulation.transpose(
            manipulation.reshape(x, [b, l, self.num_heads, self.head_dim]),
            [0, 2, 1, 3])

    def compute_kv(self, key, value):
        return self.StaticCache(self._split_heads(self.k_proj(key)),
                                self._split_heads(self.v_proj(value)))

    def gen_cache(self, key, value=None, type=None):
        if type == MultiHeadAttention.StaticCache:
            return self.compute_kv(key, value if value is not None else key)
        # incremental decoding cache seeded empty
        b = key.shape[0]
        k = creation.zeros([b, self.num_heads, 0, self.head_dim], key.dtype)
        return self.Cache(k, k)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = key if value is None else value
        if self._flash_eligible(attn_mask, cache, query.shape[1]):
            # fused Pallas path: [B, L, H, D] layout straight from the
            # projections, causality applied inside the kernel
            from ..ops.pallas_attention import flash_attention
            b, lq = query.shape[0], query.shape[1]
            shape = [b, -1, self.num_heads, self.head_dim]
            qf = manipulation.reshape(self.q_proj(query), shape)
            kf = manipulation.reshape(self.k_proj(key), shape)
            vf = manipulation.reshape(self.v_proj(value), shape)
            blocks = self.attn_blocks or (None, None)
            out, _ = flash_attention(
                qf, kf, vf, causal=isinstance(attn_mask, _CausalMask),
                block_q=blocks[0], block_k=blocks[1])
            out = manipulation.reshape(out, [b, lq, self.embed_dim])
            return self.out_proj(out)
        q = self._split_heads(self.q_proj(query))
        if isinstance(cache, self.StaticCache):
            k, v = cache.k, cache.v
        else:
            k = self._split_heads(self.k_proj(key))
            v = self._split_heads(self.v_proj(value))
            if isinstance(cache, self.Cache):
                k = manipulation.concat([cache.k, k], axis=2)
                v = manipulation.concat([cache.v, v], axis=2)
                cache = self.Cache(k, v)

        if isinstance(attn_mask, _CausalMask):
            # dense fallback for the sentinel: materialise the additive
            # causal mask. With an incremental-decode cache lq < lk and
            # query row i sits at absolute position lk - lq + i, so the
            # triu offset shifts by the prefix length (offset 1 when
            # lq == lk)
            lq, lk = q.shape[2], k.shape[2]
            attn_mask = creation.triu(
                creation.full([lq, lk], -1e9, q.dtype), lk - lq + 1)
        mask = _convert_attention_mask(attn_mask, q.dtype)
        scale = 1.0 / np.sqrt(self.head_dim)
        product = _math.matmul(q * scale, k, transpose_y=True)
        if mask is not None:
            product = product + mask
        weights = F.softmax(product)
        if self.dropout:
            weights = F.dropout(weights, self.dropout, training=self.training,
                                mode="upscale_in_train")
        out = _math.matmul(weights, v)                       # [B,H,L,D]
        out = manipulation.transpose(out, [0, 2, 1, 3])
        out = manipulation.reshape(out, [out.shape[0], out.shape[1], self.embed_dim])
        out = self.out_proj(out)

        outs = [out]
        if self.need_weights:
            outs.append(weights)
        if cache is not None and isinstance(cache, self.Cache):
            outs.append(cache)
        return out if len(outs) == 1 else tuple(outs)


class TransformerEncoderLayer(Layer):
    """reference: transformer.py:431."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 attn_impl="auto", attn_blocks=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr,
                                            attn_impl=attn_impl,
                                            attn_blocks=attn_blocks)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout, mode="upscale_in_train")
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout, mode="upscale_in_train")
        self.dropout2 = Dropout(dropout, mode="upscale_in_train")
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, incremental_cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, incremental_cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerEncoder(Layer):
    """reference: transformer.py:551."""

    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList([encoder_layer] +
                                [copy.deepcopy(encoder_layer)
                                 for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, src_mask)
            else:
                output, new_cache = mod(output, src_mask, cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    """reference: transformer.py:623."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr, bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                             weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout, mode="upscale_in_train")
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout, mode="upscale_in_train")
        self.dropout2 = Dropout(dropout, mode="upscale_in_train")
        self.dropout3 = Dropout(dropout, mode="upscale_in_train")
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
            incremental_cache = None
        else:
            tgt, incremental_cache = self.self_attn(tgt, tgt, tgt, tgt_mask, cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        else:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask, cache[1])
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (incremental_cache,))

    def gen_cache(self, memory):
        incremental = self.self_attn.gen_cache(memory)
        static = self.cross_attn.gen_cache(memory, memory,
                                           type=MultiHeadAttention.StaticCache)
        return incremental, static


class TransformerDecoder(Layer):
    """reference: transformer.py:768."""

    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList([decoder_layer] +
                                [copy.deepcopy(decoder_layer)
                                 for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        output = tgt
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, memory, tgt_mask, memory_mask)
            else:
                output, new_cache = mod(output, memory, tgt_mask, memory_mask,
                                        cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        return [layer.gen_cache(memory) for layer in self.layers]


class Transformer(Layer):
    """reference: transformer.py:859."""

    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers, enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers, dec_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    def generate_square_subsequent_mask(self, length):
        return creation.triu(
            creation.full([length, length], -np.inf, "float32"), 1)
