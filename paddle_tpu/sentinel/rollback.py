"""Last-known-good snapshots for the sentinel's ``rollback`` rung.

A thin adapter over the sharded checkpoint format
(``incubate/checkpoint/sharded.py``): periodic snapshots of model +
optimizer state, each committed atomically with its health stamp
(``incubate.checkpoint.async_ckpt.commit_checkpoint`` — the stamp rides
inside the same ``os.replace`` as the shards, so a crash can never leave a
committed-but-stampless snapshot), and a restore that walks snapshots
newest-first skipping anything stamped unhealthy or failing its shard
checksums. A missing stamp means healthy (pre-sentinel checkpoints stay
restorable — backward compat). ``async_save=True`` moves the whole
snapshot off the step path onto the shared writer thread.
"""
from __future__ import annotations

import os
import shutil
import threading
import warnings
from typing import Dict, List, Optional

from ..core import monitor as _monitor
from ..incubate.checkpoint.sharded import (
    load_sharded, CheckpointIntegrityError,
    write_health_stamp, read_health_stamp)
from ..incubate.checkpoint.async_ckpt import (
    AsyncCheckpointer, cleanup_stale_staging, commit_checkpoint)


def _snap_no(name: str) -> Optional[int]:
    suffix = name.split("_", 1)[1] if name.startswith("snap_") else ""
    return int(suffix) if suffix.isdigit() else None


class CheckpointRollback:
    """Snapshot/restore pair used by :class:`~paddle_tpu.sentinel.Sentinel`.

    ``model`` and ``optimizer`` are anything with ``state_dict`` /
    ``set_state_dict`` (an ``nn.Layer``, an ``Optimizer``); either may be
    None. ``keep_last`` bounds disk use — but unhealthy-stamped snapshots
    never count against it, so a divergence cannot GC away the last good
    state it will need.
    """

    def __init__(self, path: str, model=None, optimizer=None,
                 keep_last: int = 2, async_save: bool = False):
        self.path = str(path)
        self._model = model
        self._optimizer = optimizer
        self.keep_last = max(1, int(keep_last))
        self._ckpt = AsyncCheckpointer() if async_save else None
        # unhealthy verdicts whose snapshot was still queued/in-flight when
        # the sentinel spoke — applied when that snapshot publishes (the
        # commit hook) or, for snapshots that never publish, consumed and
        # re-checked by the restore walk after draining the writer
        self._unhealthy_lock = threading.Lock()
        self._pending_unhealthy: Dict[int, Optional[str]] = {}
        # orphaned *.tmp staging dirs from a previous crashed run; startup
        # only, so this can never race our own writer
        cleanup_stale_staging(self.path)

    # -- save side -----------------------------------------------------------
    def _snap_dir(self, step: int) -> str:
        return os.path.join(self.path, f"snap_{step}")

    def _state(self) -> dict:
        state = {}
        if self._model is not None:
            state["model"] = dict(self._model.state_dict())
        if self._optimizer is not None:
            state["optimizer"] = dict(self._optimizer.state_dict())
        return state

    def snapshot(self, step: int, healthy: bool = True,
                 reason: Optional[str] = None) -> str:
        """Commit one snapshot with its health stamp folded into the same
        atomic publish; GC old *healthy* ones. With ``async_save`` the whole
        fetch+write runs on the writer thread and GC fires post-commit."""
        d = self._snap_dir(step)
        if self._ckpt is not None:
            self._ckpt.save(self._state(), d, step=step, healthy=healthy,
                            reason=reason,
                            on_commit=lambda: self._on_commit(step, d))
        else:
            commit_checkpoint(self._state(), d, healthy=healthy, step=step,
                              reason=reason)
            self._gc()
        return d

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Drain any in-flight async snapshots (no-op when synchronous)."""
        if self._ckpt is not None:
            return self._ckpt.wait(timeout)
        return True

    def steps(self) -> List[int]:
        if not os.path.isdir(self.path):
            return []
        return sorted(s for s in (_snap_no(n) for n in os.listdir(self.path))
                      if s is not None)

    def _on_commit(self, step: int, d: str):
        """Writer-thread hook, fired strictly after a snapshot's atomic
        publish: apply any ``mark_unhealthy`` verdict that raced the
        in-flight save (the snapshot published with its save-time healthy
        stamp, which the sentinel has since overruled), then GC."""
        with self._unhealthy_lock:
            pending = step in self._pending_unhealthy
            reason = self._pending_unhealthy.pop(step, None)
        if pending:
            write_health_stamp(d, False, step=step, reason=reason)
        self._gc()

    def mark_unhealthy(self, step: int, reason: Optional[str] = None):
        """Retroactively stamp a snapshot bad (the sentinel discovered the
        divergence only after this state was already saved). With
        ``async_save`` the snapshot may still be queued or in flight — the
        verdict is recorded and applied the moment it publishes, so a
        restore can never pick a snapshot the sentinel declared bad."""
        d = self._snap_dir(step)
        if self._ckpt is not None:
            with self._unhealthy_lock:
                self._pending_unhealthy[step] = reason
        if os.path.isdir(d):
            write_health_stamp(d, False, step=step, reason=reason)
            if self._ckpt is not None and d not in self._ckpt.held_paths():
                # the verdict landed on the committed dir and no queued
                # save can republish it — drop the pending entry so a
                # future snapshot at the same step (post-rollback retrain
                # revisits step numbers) is not wrongly poisoned
                with self._unhealthy_lock:
                    self._pending_unhealthy.pop(step, None)

    def _gc(self):
        held = self._ckpt.held_paths() if self._ckpt is not None else ()
        healthy = [s for s in self.steps()
                   if read_health_stamp(self._snap_dir(s)).get("healthy",
                                                               True)]
        for s in healthy[:-self.keep_last]:
            d = self._snap_dir(s)
            if d in held:  # the writer still owns it — never sweep
                continue
            shutil.rmtree(d, ignore_errors=True)

    # -- restore side --------------------------------------------------------
    def restore_newest_healthy(self) -> Optional[int]:
        """Walk snapshots newest-first; restore the first one that is both
        health-stamped healthy (missing stamp = healthy) and integrity-
        intact. Returns the restored step, or None when nothing usable is
        left."""
        self.wait()  # a queued async snapshot may be the newest state
        # verdicts whose snapshot never published (superseded or degraded-
        # skipped saves never fire the commit hook): conservatively stamp
        # any same-step dir that does exist — the sentinel said this step's
        # state diverged, so restoring it is exactly what must not happen
        with self._unhealthy_lock:
            pending = dict(self._pending_unhealthy)
            self._pending_unhealthy.clear()
        for step, reason in pending.items():
            d = self._snap_dir(step)
            if os.path.isdir(d):
                write_health_stamp(d, False, step=step, reason=reason)
        for step in reversed(self.steps()):
            d = self._snap_dir(step)
            stamp = read_health_stamp(d)
            if not stamp.get("healthy", True):
                continue
            try:
                state = load_sharded(d)
            except (CheckpointIntegrityError, OSError, ValueError,
                    KeyError) as e:
                warnings.warn(
                    f"sentinel rollback: snapshot {d} is not intact ({e}); "
                    f"trying an older one")
                continue
            if self._model is not None and "model" in state:
                self._model.set_state_dict(state["model"])
            if self._optimizer is not None and "optimizer" in state:
                self._optimizer.set_state_dict(state["optimizer"])
            _monitor.stat_add("sentinel.rollbacks", 1)
            return step
        return None
