"""EWMA/z-score loss-spike detection.

The finiteness probe (guard.py) catches hard numerical failures — NaN/Inf
in the loss or any gradient. This module catches the *soft* failure mode:
a loss that is still finite but diverging (poisoned batch, LR blow-up,
optimizer-state corruption). It is fed from the loss value the trainer
already fetches for logging, so it adds zero device round-trips.

The statistics are exponentially-weighted (reference analog: the dynamic
loss-scaling counters in fluid/dygraph/amp/loss_scaler.py track a windowed
health signal the same way): an EWMA mean and an EWMA variance, with the
z-score of each new sample against them. Spike samples are *excluded* from
the statistics update so a divergence cannot drag the baseline up after it
(self-sealing detectors that average their own anomalies go blind within a
few steps).
"""
from __future__ import annotations

import math
from typing import Optional, Tuple


class LossSpikeDetector:
    """Streaming z-score detector over the scalar training loss.

    ``update(loss)`` returns ``(z, spike)``. During the first
    ``warmup_steps`` healthy samples the detector only learns the baseline
    and never reports a spike (the early loss curve is legitimately steep).
    Non-finite samples are the guard's job and are reported as a spike with
    ``z = inf`` without touching the statistics.
    """

    def __init__(self, alpha: float = 0.05, z_threshold: float = 6.0,
                 warmup_steps: int = 20, eps: float = 1e-12):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        if z_threshold <= 0.0:
            raise ValueError(f"z_threshold must be > 0, got {z_threshold}")
        self.alpha = float(alpha)
        self.z_threshold = float(z_threshold)
        self.warmup_steps = int(warmup_steps)
        self.eps = float(eps)
        self.reset()

    def reset(self):
        """Forget the baseline (after a rollback the restored loss regime
        may differ from the diverged one that trained the statistics)."""
        self._mean: Optional[float] = None
        self._var = 0.0
        self._healthy_samples = 0

    # -- introspection -------------------------------------------------------
    @property
    def mean(self) -> Optional[float]:
        return self._mean

    @property
    def std(self) -> float:
        return math.sqrt(max(self._var, 0.0))

    @property
    def warmed_up(self) -> bool:
        return self._healthy_samples >= self.warmup_steps

    def zscore(self, loss: float) -> float:
        """z of ``loss`` against the current baseline (0 while unlearned)."""
        if self._mean is None:
            return 0.0
        return (float(loss) - self._mean) / math.sqrt(self._var + self.eps)  # noqa: PTA001 -- host-side by contract: fed the float the guard already fetched (or the trainer logged), never a traced value

    # -- streaming update ----------------------------------------------------
    def update(self, loss: float) -> Tuple[float, bool]:
        """Feed one loss sample; returns ``(z, spike)``.

        Only an *upward* excursion is a spike — a loss dropping fast is
        good news, not divergence.
        """
        loss = float(loss)  # noqa: PTA001 -- host-side by contract: the guard fetched this scalar already; nothing here can be a tracer
        if not math.isfinite(loss):
            return float("inf"), True
        z = self.zscore(loss)
        spike = self.warmed_up and z > self.z_threshold
        if spike:
            return z, True
        # EW mean/variance (West-style): variance sees the pre-update delta
        if self._mean is None:
            self._mean = loss
        else:
            delta = loss - self._mean
            self._mean += self.alpha * delta
            self._var = (1.0 - self.alpha) * (self._var
                                              + self.alpha * delta * delta)
        self._healthy_samples += 1
        return z, False

    def state_dict(self) -> dict:
        return {"mean": self._mean, "var": self._var,
                "healthy_samples": self._healthy_samples}

    def load_state_dict(self, state: dict):
        self._mean = state.get("mean")
        self._var = float(state.get("var", 0.0))
        self._healthy_samples = int(state.get("healthy_samples", 0))
