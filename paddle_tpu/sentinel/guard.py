"""On-device step health probe: one fused reduction, one scalar fetch.

Reference: the framework layer's ``FLAGS_check_nan_inf`` / ``nan_inf_utils``
checks every output tensor from the host — O(n_tensors) device round-trips
per step. On TPU that serializes the async dispatch pipeline (the LazyTensor
argument, arxiv 2102.13267; enforced locally by the PTA002 lint), so the
probe here mirrors ``GradScaler._fused_unscale`` instead: reduce the loss
and *all* gradients to a single finiteness flag inside one XLA program and
fetch exactly one tiny array per guarded step. The fetch is the sentinel's
single sanctioned host sync, amortizable further with ``check_every > 1``.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..core import monitor as _monitor


@jax.jit
def _fused_health(grads, loss):
    """All-finite flag over ``loss`` + every grad, packed with the loss
    value into one length-2 f32 vector so the host pays a single fetch for
    both the health bit and the detector's loss sample."""
    checks = [jnp.all(jnp.isfinite(g)) for g in grads]
    checks.append(jnp.isfinite(loss))
    finite = jnp.all(jnp.stack(checks))
    return jnp.stack([finite.astype(jnp.float32),
                      loss.astype(jnp.float32)])


def poison_grads(optimizer):
    """Overwrite every present gradient with NaN (the FaultInjector ``nan``
    action at the ``grads`` site — deterministic divergence for tests)."""
    for p in optimizer._parameter_list:
        if p._grad is not None:
            p._grad = jnp.full_like(p._grad, jnp.nan)


def poison_loss(loss):
    """NaN of the same scalar shape/dtype as ``loss`` (``nan`` action at
    the ``loss`` site)."""
    if loss is None:
        return jnp.float32(jnp.nan)
    return jnp.full_like(jnp.asarray(loss), jnp.nan)


class StepGuard:
    """Amortized on-device health probe over (loss, grads).

    ``probe`` runs the fused reduction and fetches its 2-float result —
    ONE host sync, counted in ``sentinel.host_syncs`` so tests can assert
    the sync budget. ``should_check`` implements every-N-steps
    amortization: unchecked steps cost nothing at all.
    """

    def __init__(self, check_every: int = 1):
        if int(check_every) < 1:
            raise ValueError(f"check_every must be >= 1, got {check_every}")
        self.check_every = int(check_every)

    def should_check(self, step: int) -> bool:
        return step % self.check_every == 0

    def probe(self, grads: Sequence, loss=None) -> Tuple[bool, Optional[float]]:
        """Returns ``(finite, loss_value)``; ``loss_value`` is None when no
        loss was supplied (the probe then covers gradients only)."""
        have_loss = loss is not None
        loss_raw = jnp.asarray(loss, jnp.float32) if have_loss \
            else jnp.float32(0.0)
        out = _fused_health(tuple(grads), loss_raw)
        _monitor.stat_add("sentinel.checks", 1)
        _monitor.stat_add("sentinel.host_syncs", 1)
        vals = np.asarray(out)  # noqa: PTA002 -- the sentinel's ONE sanctioned fetch: a 2-float flag the policy engine must branch on; everything upstream stayed fused on device
        finite = bool(vals[0])
        return finite, float(vals[1]) if have_loss else None
