"""Quarantine directory: offline repro artifacts for poisoned batches.

When the policy ladder reaches ``quarantine_batch``, the offending inputs
and the step metadata are dumped here so the batch can be replayed offline
(was it the data, or the state?) without re-running the job. Layout::

    <quarantine_dir>/
      step_<n>/
        inputs.npz   # x0, x1, ..., y0, y1, ...  (host copies)
        meta.json    # step, reasons, loss, z, shapes/dtypes, wall time

Writes are tmp+rename so a crash mid-dump never leaves a half-readable
entry, and the directory is capped (``max_entries``) — a deterministic
divergence would otherwise quarantine every remaining batch of the epoch.
This is a cold path: it runs only after an anomaly already fired, so host
copies here are deliberate and harmless.
"""
from __future__ import annotations

import json
import os
import shutil
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core import monitor as _monitor


def _to_host(val) -> np.ndarray:
    data = getattr(val, "_data", val)  # Tensor -> jax.Array
    return np.asarray(data)


def _entry_count(root: str) -> int:
    if not os.path.isdir(root):
        return 0
    return sum(1 for n in os.listdir(root) if n.startswith("step_"))


def quarantine_batch(root: Optional[str], step: int,
                     batch: Optional[Tuple[Sequence, Sequence]],
                     reasons: List[str], loss: Optional[float] = None,
                     z: Optional[float] = None,
                     max_entries: int = 8) -> Optional[str]:
    """Dump ``batch`` (an ``(inputs, labels)`` pair of array/Tensor lists,
    or None for a metadata-only record) under ``root``. Returns the entry
    directory, or None when ``root`` is unset or the cap is reached."""
    if not root:
        return None
    if _entry_count(root) >= max(1, int(max_entries)):
        _monitor.stat_add("sentinel.quarantine_dropped", 1)
        return None
    final = os.path.join(root, f"step_{step}")
    tmp = os.path.join(root, f".tmp_step_{step}")
    if os.path.isdir(final):  # same step re-quarantined (e.g. after rollback)
        return final
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)

    blobs: Dict[str, np.ndarray] = {}
    spec: Dict[str, Dict] = {}
    if batch is not None:
        xs, ys = batch
        for prefix, vals in (("x", xs or []), ("y", ys or [])):
            for i, v in enumerate(vals):
                arr = _to_host(v)
                blobs[f"{prefix}{i}"] = arr
                spec[f"{prefix}{i}"] = {"shape": list(arr.shape),
                                        "dtype": str(arr.dtype)}
    if blobs:
        np.savez(os.path.join(tmp, "inputs.npz"), **blobs)
    meta = {"step": int(step), "reasons": list(reasons),
            "loss": None if loss is None else float(loss),
            "z": None if z is None else float(z),
            "inputs": spec, "time": time.time()}
    with open(os.path.join(tmp, "meta.json"), "w") as f:
        json.dump(meta, f, indent=1)
    os.replace(tmp, final)
    _monitor.stat_add("sentinel.quarantined", 1)
    return final


def read_quarantine(entry_dir: str) -> Tuple[dict, Dict[str, np.ndarray]]:
    """Load one quarantine entry back: ``(meta, {name: array})`` — the
    offline-repro half of the contract."""
    with open(os.path.join(entry_dir, "meta.json")) as f:
        meta = json.load(f)
    arrays: Dict[str, np.ndarray] = {}
    npz = os.path.join(entry_dir, "inputs.npz")
    if os.path.exists(npz):
        with np.load(npz) as z:
            arrays = {k: z[k] for k in z.files}
    return meta, arrays
