"""Numerical-anomaly sentinel: NaN/Inf guard, divergence detection, and
auto-rollback to last-known-good checkpoints.

The sentinel closes the robustness gap the elastic runtime leaves open:
that layer restarts *crashed* processes, but a numerically diverged run
does not crash — it keeps burning accelerator hours writing NaN into every
weight. Three layers, each usable alone:

- :class:`StepGuard` / :class:`LossSpikeDetector` — detection. One fused
  on-device finiteness reduction over loss + all grads with a single
  scalar fetch per guarded step, plus a host-side EWMA z-score spike
  detector over the loss the trainer already fetches.
- :class:`PolicyEngine` / :class:`Sentinel` — response. A configurable
  escalation ladder (``skip_step`` → ``quarantine_batch`` → ``rollback``
  → ``halt``) driven by consecutive anomaly counts, hooked into
  ``Optimizer.step`` so poisoned updates never reach the parameters.
- :class:`CheckpointRollback` — recovery. Health-stamped sharded
  snapshots with a newest-healthy restore walk.

Quickstart::

    import paddle_tpu as paddle
    from paddle_tpu import sentinel

    rb = sentinel.CheckpointRollback("ckpts", model=net, optimizer=opt)
    guard = sentinel.Sentinel(
        sentinel.SentinelConfig(quarantine_dir="quarantine"),
        optimizer=opt, rollback=rb)
    for step, (x, y) in enumerate(loader):
        loss = loss_fn(net(x), y)
        loss.backward()
        guard.observe(loss=loss, batch=([x], [y]))
        opt.step()               # guarded
        opt.clear_grad()
        if step % 50 == 0:
            rb.snapshot(step)

For ``hapi.Model`` users, ``hapi.callbacks.AnomalyGuardCallback`` wires
all of this up from the fit loop.
"""
from ..distributed.elastic import DIVERGENCE_EXIT_CODE  # noqa: F401
from .detector import LossSpikeDetector  # noqa: F401
from .guard import StepGuard, poison_grads, poison_loss  # noqa: F401
from .policy import (  # noqa: F401
    ACTIONS, DEFAULT_LADDER, AnomalyReport, PolicyEngine, Sentinel,
    SentinelConfig)
from .quarantine import quarantine_batch, read_quarantine  # noqa: F401
from .rollback import CheckpointRollback  # noqa: F401

__all__ = [
    "ACTIONS",
    "DEFAULT_LADDER",
    "DIVERGENCE_EXIT_CODE",
    "AnomalyReport",
    "CheckpointRollback",
    "LossSpikeDetector",
    "PolicyEngine",
    "Sentinel",
    "SentinelConfig",
    "StepGuard",
    "poison_grads",
    "poison_loss",
    "quarantine_batch",
    "read_quarantine",
]
