"""Anomaly policy engine: the configurable escalation ladder.

One anomaly is usually a poisoned batch; five in a row is a diverged run.
The ladder maps *consecutive* anomaly counts to increasingly drastic
responses::

    skip_step → quarantine_batch → rollback → halt

- ``skip_step``        zero the update (GradScaler found_inf semantics)
- ``quarantine_batch`` also dump the offending inputs for offline repro
- ``rollback``         restore the newest healthy snapshot
  (:class:`~paddle_tpu.sentinel.rollback.CheckpointRollback`), optionally
  rescaling the LR
- ``halt``             exit with
  :data:`~paddle_tpu.distributed.elastic.DIVERGENCE_EXIT_CODE` so the
  elastic supervisor tears the job down instead of burning its restart
  budget on a deterministic divergence

A healthy step resets the consecutive count; every rung also skips the
poisoned update (stepping on NaN grads is never an option).
"""
from __future__ import annotations

import dataclasses
import math
import sys
import warnings
from typing import List, Optional, Tuple

from ..core import monitor as _monitor
from ..distributed.elastic import DIVERGENCE_EXIT_CODE
from ..utils.resilience import fault_injector
from .detector import LossSpikeDetector
from .guard import StepGuard, poison_grads, poison_loss
from .quarantine import quarantine_batch

#: every action a ladder may contain, mildest first
ACTIONS = ("skip_step", "quarantine_batch", "rollback", "halt")

DEFAULT_LADDER = ("skip_step", "quarantine_batch", "rollback", "halt")


@dataclasses.dataclass
class SentinelConfig:
    """Knobs for :class:`Sentinel` (all host-side; nothing here recompiles
    the step)."""

    check_every: int = 1          # probe every Nth optimizer step
    ladder: Tuple[str, ...] = DEFAULT_LADDER
    tolerance: int = 1            # consecutive anomalies per rung
    z_threshold: float = 6.0      # loss-spike z-score trip point
    ewma_alpha: float = 0.05
    warmup_steps: int = 20        # detector learns before it may trip
    quarantine_dir: Optional[str] = None
    quarantine_max: int = 8
    lr_rescale: float = 1.0       # LR multiplier applied on rollback
    halt_exit_code: int = DIVERGENCE_EXIT_CODE

    def __post_init__(self):
        unknown = [a for a in self.ladder if a not in ACTIONS]
        if unknown:
            raise ValueError(
                f"unknown sentinel action(s) {unknown}; valid: {ACTIONS}")
        if not self.ladder:
            raise ValueError("ladder must have at least one action")
        if int(self.check_every) < 1:
            raise ValueError(f"check_every must be >= 1, got "
                             f"{self.check_every}")
        if int(self.tolerance) < 1:
            raise ValueError(f"tolerance must be >= 1, got {self.tolerance}")


@dataclasses.dataclass
class AnomalyReport:
    """What the sentinel saw and did for one guarded step."""

    step: int
    anomalous: bool
    reasons: List[str] = dataclasses.field(default_factory=list)
    action: Optional[str] = None
    loss: Optional[float] = None
    z: Optional[float] = None
    rolled_back_to: Optional[int] = None


class PolicyEngine:
    """Maps consecutive-anomaly counts onto the ladder."""

    def __init__(self, ladder: Tuple[str, ...] = DEFAULT_LADDER,
                 tolerance: int = 1):
        self.ladder = tuple(ladder)
        self.tolerance = max(1, int(tolerance))

    def decide(self, consecutive: int) -> str:
        rung = min((max(1, consecutive) - 1) // self.tolerance,
                   len(self.ladder) - 1)
        return self.ladder[rung]


class Sentinel:
    """Numerical-anomaly sentinel for the optimizer step.

    ::

        sentinel = Sentinel(SentinelConfig(ladder=("skip_step", "halt")),
                            optimizer=opt, rollback=rb)
        for x, y in loader:
            loss = loss_fn(net(x), y)
            loss.backward()
            sentinel.observe(loss=loss, batch=([x], [y]))  # optional ctx
            opt.step()      # guarded: NaN grads can never reach params
            opt.clear_grad()

    ``attach`` hooks :meth:`approve_step` into ``Optimizer.step`` so
    existing training loops are guarded without restructuring; a healthy
    guarded step costs one fused reduction plus one scalar fetch
    (``sentinel.host_syncs``), and ``check_every=N`` amortizes that to
    every Nth step. The FaultInjector sites ``grads`` / ``loss`` with the
    ``nan`` action poison the corresponding values right before the probe,
    making every rung deterministically testable
    (``PADDLE_TPU_FAULT_SPEC="grads:5:nan"``).
    """

    def __init__(self, config: Optional[SentinelConfig] = None,
                 optimizer=None, rollback=None):
        self.config = config or SentinelConfig()
        self.guard = StepGuard(self.config.check_every)
        self.detector = LossSpikeDetector(
            alpha=self.config.ewma_alpha,
            z_threshold=self.config.z_threshold,
            warmup_steps=self.config.warmup_steps)
        self.policy = PolicyEngine(self.config.ladder, self.config.tolerance)
        self.rollback = rollback
        self.last_report: Optional[AnomalyReport] = None
        self._step = 0
        self._consecutive = 0
        self.anomalies = 0  # lifetime total, all paths
        self._ctx_loss = None
        self._ctx_batch = None
        self._optimizer = None
        self._warned_no_rollback = False
        #: optional zero-arg callable returning the current ``(xs, ys)``
        #: batch for quarantine dumps when no batch was ``observe``d —
        #: AnomalyGuardCallback points this at ``Model._last_batch``
        self.batch_getter = None
        if optimizer is not None:
            self.attach(optimizer)

    # -- wiring --------------------------------------------------------------
    def attach(self, optimizer) -> "Sentinel":
        """Guard ``optimizer.step()`` (the hook lives in Optimizer.step)."""
        optimizer._sentinel = self
        self._optimizer = optimizer
        return self

    def detach(self, optimizer=None):
        opt = optimizer or self._optimizer
        if opt is not None and getattr(opt, "_sentinel", None) is self:
            opt._sentinel = None
        if opt is self._optimizer:
            self._optimizer = None

    def observe(self, loss=None, batch=None):
        """Give the next guarded step its context: the loss the trainer
        already holds (device scalar or the float it fetched for logging)
        and optionally the raw batch for quarantine dumps."""
        self._ctx_loss = loss
        self._ctx_batch = batch

    # -- the guard hook ------------------------------------------------------
    def approve_step(self, optimizer) -> bool:
        """Called by ``Optimizer.step``; True means apply the update."""
        step = self._step
        self._step += 1
        loss, batch = self._ctx_loss, self._ctx_batch
        self._ctx_loss = self._ctx_batch = None
        if batch is None and self.batch_getter is not None:
            batch = self.batch_getter()

        fi = fault_injector()
        if fi.armed("grads") and fi.fire("grads") == "nan":
            poison_grads(optimizer)
        if fi.armed("loss") and fi.fire("loss") == "nan":
            loss = poison_loss(loss)

        if not self.guard.should_check(step):
            return True  # amortized-out step: zero probe cost

        grads = [p._grad for p in optimizer._parameter_list
                 if p._grad is not None]
        loss_raw = getattr(loss, "_data", loss)  # Tensor -> jax.Array
        if not grads and loss_raw is None:
            return True  # nothing to probe

        finite, loss_val = self.guard.probe(grads, loss_raw)
        reasons: List[str] = []
        z = None
        if not finite:
            reasons.append("non_finite")
            _monitor.stat_add("sentinel.nan_steps", 1)
        elif loss_val is not None:
            z, spike = self.detector.update(loss_val)
            _monitor.stat_observe("sentinel.loss_z", z)
            if spike:
                reasons.append(f"loss_spike(z={z:.2f})")
                _monitor.stat_add("sentinel.spike_steps", 1)

        if not reasons:
            self._consecutive = 0
            self.last_report = AnomalyReport(step, False, loss=loss_val, z=z)
            return True

        self._consecutive += 1
        self.anomalies += 1
        action = self.policy.decide(self._consecutive)
        report = AnomalyReport(step, True, reasons=reasons, action=action,
                               loss=loss_val, z=z)
        self._apply(action, optimizer, report, batch)
        self.last_report = report
        _monitor.stat_add("sentinel.skipped_steps", 1)
        return False

    def feed_loss(self, loss, step: Optional[int] = None,
                  batch=None) -> Optional[AnomalyReport]:
        """Post-update loss path: feed the float the trainer already
        fetched for logging (zero extra host syncs). Runs the spike
        detector and, on anomaly, the same escalation ladder —  except the
        update is already applied, so a ``skip_step`` rung only records
        the anomaly. AnomalyGuardCallback calls this every batch.

        Returns the :class:`AnomalyReport` when anomalous, else None. A
        step already flagged by :meth:`approve_step` is not double-counted.
        """
        if step is None:
            step = max(0, self._step - 1)
        lr = self.last_report
        if lr is not None and lr.anomalous and lr.step == step:
            return None  # in-step probe already escalated this one
        if batch is None and self.batch_getter is not None:
            batch = self.batch_getter()
        loss_val = float(getattr(loss, "_data", loss))
        reasons: List[str] = []
        z = None
        if not math.isfinite(loss_val):
            reasons.append("non_finite")
            _monitor.stat_add("sentinel.nan_steps", 1)
        else:
            z, spike = self.detector.update(loss_val)
            _monitor.stat_observe("sentinel.loss_z", z)
            if spike:
                reasons.append(f"loss_spike(z={z:.2f})")
                _monitor.stat_add("sentinel.spike_steps", 1)
        if not reasons:
            self._consecutive = 0
            self.last_report = AnomalyReport(step, False, loss=loss_val, z=z)
            return None
        self._consecutive += 1
        self.anomalies += 1
        action = self.policy.decide(self._consecutive)
        report = AnomalyReport(step, True, reasons=reasons, action=action,
                               loss=loss_val, z=z)
        self._apply(action, self._optimizer, report, batch)
        self.last_report = report
        return report

    # -- actions -------------------------------------------------------------
    def _apply(self, action: str, optimizer, report: AnomalyReport, batch):
        # every escalation rung lands in the flight ring (always cheap);
        # the file dump below happens only at halt
        from ..observability import flight as _flight
        _flight.record_event("sentinel", {
            "action": action, "step": report.step,
            "reasons": list(report.reasons), "loss": report.loss,
            "z": report.z})
        if action in ("quarantine_batch", "halt"):
            quarantine_batch(self.config.quarantine_dir, report.step, batch,
                             report.reasons, loss=report.loss, z=report.z,
                             max_entries=self.config.quarantine_max)
        if action == "rollback":
            report.rolled_back_to = self._do_rollback(optimizer)
        if action == "halt":
            _monitor.stat_add("sentinel.halts", 1)
            dump_path = _flight.dump_if_armed("sentinel_halt")
            if dump_path:
                sys.stderr.write(
                    f"[sentinel] flight recording: {dump_path}\n")
            sys.stderr.write(
                f"[sentinel] halting at step {report.step}: "
                f"{', '.join(report.reasons)} (escalation exhausted after "
                f"{self._consecutive} consecutive anomalies); exiting "
                f"{self.config.halt_exit_code} so the elastic supervisor "
                f"does not restart a deterministic divergence\n")
            sys.stderr.flush()
            sys.exit(self.config.halt_exit_code)

    def _do_rollback(self, optimizer) -> Optional[int]:
        if self.rollback is None:
            if not self._warned_no_rollback:
                warnings.warn(
                    "sentinel: ladder reached 'rollback' but no rollback "
                    "adapter is configured; degrading to skip_step")
                self._warned_no_rollback = True
            return None
        restored = self.rollback.restore_newest_healthy()
        if restored is None:
            warnings.warn("sentinel: rollback found no healthy snapshot; "
                          "degrading to skip_step")
            return None
        # the diverged regime trained the detector's baseline — forget it
        self.detector.reset()
        if self.config.lr_rescale != 1.0:
            try:
                optimizer.set_lr(optimizer.get_lr()
                                 * self.config.lr_rescale)
            except RuntimeError:
                warnings.warn("sentinel: lr_rescale skipped — optimizer "
                              "uses an LRScheduler; adjust the schedule "
                              "instead")
        return restored
