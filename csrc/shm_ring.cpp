// Shared-memory SPSC ring buffer for DataLoader worker -> trainer batch
// transport.
//
// TPU-native equivalent of the reference's native DataLoader transport
// (reference: paddle/fluid/memory/allocation/mmap_allocator.cc — worker
// processes place LoDTensor payloads in shared memory and pass only
// handles through the queue; operators/reader/buffered_reader.cc does the
// staging). Python multiprocessing queues pickle the full batch through a
// pipe (two copies + syscall per chunk); this ring memcpys payload bytes
// into POSIX shared memory once, and only tiny metadata rides the queue.
//
// Design: one ring per worker, single producer (the worker) / single
// consumer (the trainer process) — head/tail are C++11 atomics, no locks.
// Layout: [header: capacity, head, tail][data bytes]. All functions are
// exported with C linkage for ctypes.
//
// Build: g++ -O2 -shared -fPIC -o libshm_ring.so shm_ring.cpp -lrt
// (paddle_tpu/core/shm_ring.py builds this on demand).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <new>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

namespace {

struct RingHeader {
  int64_t capacity;                 // data bytes (power of two not required)
  std::atomic<int64_t> head;        // consumer position (monotonic)
  std::atomic<int64_t> tail;        // producer position (monotonic)
};

inline char* data_of(RingHeader* h) {
  return reinterpret_cast<char*>(h) + sizeof(RingHeader);
}

inline int64_t used(const RingHeader* h) {
  return h->tail.load(std::memory_order_acquire) -
         h->head.load(std::memory_order_acquire);
}

void copy_in(RingHeader* h, int64_t pos, const char* src, int64_t n) {
  const int64_t cap = h->capacity;
  const int64_t off = pos % cap;
  const int64_t first = (off + n <= cap) ? n : cap - off;
  std::memcpy(data_of(h) + off, src, first);
  if (n > first) std::memcpy(data_of(h), src + first, n - first);
}

void copy_out(RingHeader* h, int64_t pos, char* dst, int64_t n) {
  const int64_t cap = h->capacity;
  const int64_t off = pos % cap;
  const int64_t first = (off + n <= cap) ? n : cap - off;
  std::memcpy(dst, data_of(h) + off, first);
  if (n > first) std::memcpy(dst + first, data_of(h), n - first);
}

void nap() {
  timespec ts{0, 200 * 1000};  // 200us
  nanosleep(&ts, nullptr);
}

}  // namespace

extern "C" {

// Create (trainer side) or open (worker side) a named ring. Returns the
// mapped base pointer, or 0 on failure.
void* shm_ring_create(const char* name, int64_t capacity) {
  shm_unlink(name);  // stale ring from a crashed run
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  const int64_t total = sizeof(RingHeader) + capacity;
  if (ftruncate(fd, total) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  auto* h = new (base) RingHeader();
  h->capacity = capacity;
  h->head.store(0);
  h->tail.store(0);
  return base;
}

void* shm_ring_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED,
                    fd, 0);
  close(fd);
  return base == MAP_FAILED ? nullptr : base;
}

// Blocking push of exactly n bytes. Returns 0, or -1 after ~timeout_ms of
// the consumer not draining.
int shm_ring_push(void* base, const char* src, int64_t n, int64_t timeout_ms) {
  auto* h = static_cast<RingHeader*>(base);
  if (n > h->capacity) return -2;  // payload larger than the ring
  int64_t waited_us = 0;
  while (h->capacity - used(h) < n) {
    nap();
    waited_us += 200;
    if (timeout_ms >= 0 && waited_us > timeout_ms * 1000) return -1;
  }
  const int64_t pos = h->tail.load(std::memory_order_relaxed);
  copy_in(h, pos, src, n);
  h->tail.store(pos + n, std::memory_order_release);
  return 0;
}

// Blocking pop of exactly n bytes (the size arrives via the metadata
// queue). Returns 0, or -1 on timeout.
int shm_ring_pop(void* base, char* dst, int64_t n, int64_t timeout_ms) {
  auto* h = static_cast<RingHeader*>(base);
  if (n > h->capacity) return -2;
  int64_t waited_us = 0;
  while (used(h) < n) {
    nap();
    waited_us += 200;
    if (timeout_ms >= 0 && waited_us > timeout_ms * 1000) return -1;
  }
  const int64_t pos = h->head.load(std::memory_order_relaxed);
  copy_out(h, pos, dst, n);
  h->head.store(pos + n, std::memory_order_release);
  return 0;
}

int64_t shm_ring_capacity(void* base) {
  return static_cast<RingHeader*>(base)->capacity;
}

int64_t shm_ring_used(void* base) {
  return used(static_cast<RingHeader*>(base));
}

void shm_ring_close(void* base) {
  auto* h = static_cast<RingHeader*>(base);
  munmap(base, sizeof(RingHeader) + h->capacity);
}

void shm_ring_unlink(const char* name) { shm_unlink(name); }

}  // extern "C"
