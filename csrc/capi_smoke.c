/* Smoke driver for the C inference API (paddle_tpu_capi.h): loads an
 * artifact, runs one float32 batch, prints outputs for the test harness
 * to compare against the Python predictor.
 *
 *   ./capi_smoke <model_prefix> <n> <d>   (input = n*d counter values)
 */
#include <stdio.h>
#include <stdlib.h>

#include "paddle_tpu_capi.h"

int main(int argc, char** argv) {
  if (argc < 4) {
    fprintf(stderr, "usage: %s <prefix> <n> <d>\n", argv[0]);
    return 2;
  }
  const char* prefix = argv[1];
  int n = atoi(argv[2]);
  int d = atoi(argv[3]);

  PTC_Predictor* p = PTC_PredictorCreate(prefix);
  if (!p) {
    fprintf(stderr, "create failed: %s\n", PTC_LastError());
    return 1;
  }
  printf("n_inputs %d\n", PTC_GetNumInputs(p));

  /* output getters before any PTC_Run must fail cleanly, not crash */
  if (PTC_GetOutputNumDims(p, 0) != -1 || PTC_GetOutputShape(p, 0) ||
      PTC_GetOutputData(p, 0) || PTC_GetOutputDType(p, 0) != -1) {
    fprintf(stderr, "pre-run output getters did not error\n");
    return 1;
  }
  printf("prerun guard ok (%s)\n", PTC_LastError());

  float* x = (float*)malloc(sizeof(float) * n * d);
  for (int i = 0; i < n * d; ++i) x[i] = (float)(i % 7) * 0.25f - 0.5f;
  int64_t shape[2] = {n, d};
  const void* inputs[1] = {x};
  const int64_t* shapes[1] = {shape};
  int ndims[1] = {2};
  int dtypes[1] = {PTC_FLOAT32};
  if (PTC_Run(p, inputs, shapes, ndims, dtypes, 1) != 0) {
    fprintf(stderr, "run failed: %s\n", PTC_LastError());
    return 1;
  }
  int nout = PTC_GetNumOutputs(p);
  printf("n_outputs %d\n", nout);
  for (int i = 0; i < nout; ++i) {
    int nd = PTC_GetOutputNumDims(p, i);
    const int64_t* s = PTC_GetOutputShape(p, i);
    printf("out %d dtype %d shape", i, PTC_GetOutputDType(p, i));
    long total = 1;
    for (int k = 0; k < nd; ++k) {
      printf(" %lld", (long long)s[k]);
      total *= (long)s[k];
    }
    printf("\ndata");
    const float* data = (const float*)PTC_GetOutputData(p, i);
    for (long k = 0; k < total; ++k) printf(" %.6f", data[k]);
    printf("\n");
  }
  /* second run with the same buffers must work (handle reuse) */
  if (PTC_Run(p, inputs, shapes, ndims, dtypes, 1) != 0) {
    fprintf(stderr, "rerun failed: %s\n", PTC_LastError());
    return 1;
  }
  printf("rerun ok\n");
  /* out-of-range index must fail cleanly too */
  if (PTC_GetOutputNumDims(p, nout) != -1 ||
      PTC_GetOutputData(p, -1) != NULL) {
    fprintf(stderr, "out-of-range output getters did not error\n");
    return 1;
  }
  printf("bounds guard ok\n");
  free(x);
  PTC_PredictorDestroy(p);
  printf("done\n");
  return 0;
}
