/* C shim over the Python Predictor (see paddle_tpu_capi.h).
 *
 * Embeds CPython (Py_InitializeEx) and drives
 * paddle_tpu.inference.Predictor through a tiny helper module defined
 * inline.  Input buffers cross zero-copy via memoryview -> np.frombuffer;
 * outputs are held as contiguous numpy arrays and exported through the
 * buffer protocol, so the caller reads the runtime's own memory.
 *
 * reference parity target: inference/capi_exp/pd_inference_api.h
 * (PD_PredictorCreate / PD_PredictorRun / PD_TensorData...).
 */
#include "paddle_tpu_capi.h"

#include <Python.h>

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

static std::string g_last_error;

static void set_err_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  g_last_error = "python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      g_last_error = PyUnicode_AsUTF8(s);
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

static const char* kHelperSrc = R"PY(
import os
if os.environ.get("PTC_FORCE_CPU"):
    import jax
    jax.config.update("jax_platforms", "cpu")
import numpy as np

_DTYPES = {0: np.float32, 1: np.int32, 2: np.int64}
_DTYPE_CODES = {np.dtype(np.float32): 0, np.dtype(np.int32): 1,
                np.dtype(np.int64): 2}


def create(prefix):
    import jax
    # deployment default: let jax pick; CPU hosts serve artifacts too
    from paddle_tpu.inference import Config, create_predictor
    return create_predictor(Config(prefix))


def run(pred, views, shapes, dtypes):
    xs = []
    for mv, shp, dt in zip(views, shapes, dtypes):
        a = np.frombuffer(mv, dtype=_DTYPES[int(dt)]).reshape(shp)
        xs.append(a)
    outs = pred.run(xs)
    keep = []
    for o in outs:
        a = np.ascontiguousarray(np.asarray(o))
        if a.dtype not in _DTYPE_CODES:
            a = np.ascontiguousarray(a, np.float32)
        keep.append(a)
    return keep


def out_dtype_code(a):
    return _DTYPE_CODES[a.dtype]
)PY";

struct PTC_Predictor {
  PyObject* helper;   // module dict holding create/run
  PyObject* pred;     // the python Predictor
  PyObject* outputs;  // list of contiguous numpy arrays from last run
  std::vector<std::vector<int64_t>> out_shapes;
  std::vector<Py_buffer> out_views;  // live buffer views into outputs
};

static bool g_py_owner = false;
static PyThreadState* g_saved_ts = nullptr;

static void release_out_views(PTC_Predictor* p) {
  for (auto& v : p->out_views) PyBuffer_Release(&v);
  p->out_views.clear();
}

extern "C" PTC_Predictor* PTC_PredictorCreate(const char* model_prefix) {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    g_py_owner = true;
    g_saved_ts = PyEval_SaveThread();
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  PTC_Predictor* p = nullptr;
  PyObject* mod = nullptr;
  PyObject* pred = nullptr;
  do {
    mod = PyModule_New("_ptc_helper");
    if (!mod) break;
    PyObject* globals = PyModule_GetDict(mod);
    PyDict_SetItemString(globals, "__builtins__", PyEval_GetBuiltins());
    PyObject* r = PyRun_String(kHelperSrc, Py_file_input, globals, globals);
    if (!r) break;
    Py_DECREF(r);
    PyObject* create = PyDict_GetItemString(globals, "create");
    pred = PyObject_CallFunction(create, "s", model_prefix);
    if (!pred) break;
    p = new PTC_Predictor();
    p->helper = mod;
    p->pred = pred;
    p->outputs = nullptr;
    mod = nullptr;
    pred = nullptr;
  } while (false);
  if (!p) set_err_from_python();
  Py_XDECREF(mod);
  Py_XDECREF(pred);
  PyGILState_Release(gil);
  return p;
}

extern "C" int PTC_GetNumInputs(PTC_Predictor* p) {
  PyGILState_STATE gil = PyGILState_Ensure();
  int n = -1;
  PyObject* names = PyObject_CallMethod(p->pred, "get_input_names", nullptr);
  if (names) {
    n = static_cast<int>(PyList_Size(names));
    Py_DECREF(names);
  } else {
    set_err_from_python();
  }
  PyGILState_Release(gil);
  return n;
}

extern "C" int PTC_Run(PTC_Predictor* p, const void* const* inputs,
                       const int64_t* const* shapes, const int* ndims,
                       const int* dtypes, int n_inputs) {
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* views = PyList_New(n_inputs);
  PyObject* shp_list = PyList_New(n_inputs);
  PyObject* dt_list = PyList_New(n_inputs);
  do {
    if (!views || !shp_list || !dt_list) break;
    bool ok = true;
    for (int i = 0; i < n_inputs; ++i) {
      int64_t elems = 1;
      PyObject* shp = PyTuple_New(ndims[i]);
      for (int d = 0; d < ndims[i]; ++d) {
        elems *= shapes[i][d];
        PyTuple_SET_ITEM(shp, d, PyLong_FromLongLong(shapes[i][d]));
      }
      int esize = dtypes[i] == PTC_FLOAT32 ? 4
                  : dtypes[i] == PTC_INT32 ? 4 : 8;
      PyObject* mv = PyMemoryView_FromMemory(
          const_cast<char*>(static_cast<const char*>(inputs[i])),
          elems * esize, PyBUF_READ);
      if (!mv) { Py_DECREF(shp); ok = false; break; }
      PyList_SET_ITEM(views, i, mv);
      PyList_SET_ITEM(shp_list, i, shp);
      PyList_SET_ITEM(dt_list, i, PyLong_FromLong(dtypes[i]));
    }
    if (!ok) break;
    PyObject* globals = PyModule_GetDict(p->helper);
    PyObject* runfn = PyDict_GetItemString(globals, "run");
    PyObject* outs = PyObject_CallFunctionObjArgs(
        runfn, p->pred, views, shp_list, dt_list, nullptr);
    if (!outs) break;
    release_out_views(p);
    Py_XDECREF(p->outputs);
    p->outputs = outs;
    Py_ssize_t n = PyList_Size(outs);
    p->out_shapes.assign(n, {});
    p->out_views.assign(n, Py_buffer{});
    bool view_ok = true;
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject* a = PyList_GetItem(outs, i);
      if (PyObject_GetBuffer(a, &p->out_views[i],
                             PyBUF_CONTIG_RO | PyBUF_FORMAT) != 0) {
        view_ok = false;
        break;
      }
      auto& vw = p->out_views[i];
      p->out_shapes[i].assign(vw.shape, vw.shape + vw.ndim);
    }
    if (!view_ok) {
      // a partially view-acquired output set must not look valid to the
      // getters: roll back to "no outputs" so they error cleanly
      release_out_views(p);
      p->out_shapes.clear();
      Py_CLEAR(p->outputs);
      break;
    }
    rc = 0;
  } while (false);
  if (rc != 0) set_err_from_python();
  Py_XDECREF(views);
  Py_XDECREF(shp_list);
  Py_XDECREF(dt_list);
  PyGILState_Release(gil);
  return rc;
}

extern "C" int PTC_GetNumOutputs(PTC_Predictor* p) {
  return p->outputs ? static_cast<int>(p->out_shapes.size()) : 0;
}

// output getters are only valid after a successful PTC_Run and for
// 0 <= i < PTC_GetNumOutputs; an embedding caller can easily violate
// either, so fail with an error instead of dereferencing null
static bool out_index_ok(PTC_Predictor* p, int i) {
  if (p->outputs && i >= 0 &&
      i < static_cast<int>(p->out_shapes.size()))
    return true;
  g_last_error = p->outputs ? "output index out of range"
                            : "no outputs: call PTC_Run first";
  return false;
}

extern "C" int PTC_GetOutputNumDims(PTC_Predictor* p, int i) {
  if (!out_index_ok(p, i)) return -1;
  return static_cast<int>(p->out_shapes[i].size());
}

extern "C" const int64_t* PTC_GetOutputShape(PTC_Predictor* p, int i) {
  if (!out_index_ok(p, i)) return nullptr;
  return p->out_shapes[i].data();
}

extern "C" int PTC_GetOutputDType(PTC_Predictor* p, int i) {
  if (!out_index_ok(p, i)) return -1;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* globals = PyModule_GetDict(p->helper);
  PyObject* fn = PyDict_GetItemString(globals, "out_dtype_code");
  PyObject* a = PyList_GetItem(p->outputs, i);
  PyObject* code = PyObject_CallFunctionObjArgs(fn, a, nullptr);
  int out = -1;
  if (code) {
    out = static_cast<int>(PyLong_AsLong(code));
    Py_DECREF(code);
  } else {
    set_err_from_python();
  }
  PyGILState_Release(gil);
  return out;
}

extern "C" const void* PTC_GetOutputData(PTC_Predictor* p, int i) {
  if (!out_index_ok(p, i)) return nullptr;
  return p->out_views[i].buf;
}

extern "C" void PTC_PredictorDestroy(PTC_Predictor* p) {
  if (!p) return;
  PyGILState_STATE gil = PyGILState_Ensure();
  release_out_views(p);
  Py_XDECREF(p->outputs);
  Py_XDECREF(p->pred);
  Py_XDECREF(p->helper);
  PyGILState_Release(gil);
  delete p;
}

extern "C" const char* PTC_LastError(void) { return g_last_error.c_str(); }
