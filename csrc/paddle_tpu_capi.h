/* C inference API over paddle_tpu's StableHLO deployment artifacts.
 *
 * The reference ships a C API over its C++ AnalysisPredictor
 * (reference: paddle/fluid/inference/capi_exp/pd_inference_api.h,
 * go/paddle/predictor.go builds on it). Here the runtime that executes
 * the artifact is XLA reached through the Python package, so this shim
 * embeds a CPython interpreter and exposes the same create/run/fetch
 * surface as plain C — callable from C, Go (cgo), or R (.C/Rcpp) without
 * any Python on the caller's side.
 *
 * Threading: calls take the GIL internally; the API is safe to call from
 * one thread at a time.  Dtypes: float32 (0), int32 (1), int64 (2).
 */
#ifndef PADDLE_TPU_CAPI_H_
#define PADDLE_TPU_CAPI_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct PTC_Predictor PTC_Predictor;

typedef enum {
  PTC_FLOAT32 = 0,
  PTC_INT32 = 1,
  PTC_INT64 = 2,
} PTC_DType;

/* Load the artifact pair <prefix>.pdmodel / <prefix>.pdiparams.
 * Returns NULL on failure (see PTC_LastError). */
PTC_Predictor* PTC_PredictorCreate(const char* model_prefix);

int PTC_GetNumInputs(PTC_Predictor* p);

/* Run with n_inputs host buffers (zero-copy into the runtime: the
 * buffers are wrapped, not copied; they must stay alive for the call).
 * shapes[i] has ndims[i] dims; dtypes[i] is a PTC_DType.
 * Returns 0 on success, -1 on error. */
int PTC_Run(PTC_Predictor* p, const void* const* inputs,
            const int64_t* const* shapes, const int* ndims,
            const int* dtypes, int n_inputs);

int PTC_GetNumOutputs(PTC_Predictor* p);
int PTC_GetOutputNumDims(PTC_Predictor* p, int i);
/* Pointer to the i-th output's dims (valid until the next Run). */
const int64_t* PTC_GetOutputShape(PTC_Predictor* p, int i);
int PTC_GetOutputDType(PTC_Predictor* p, int i);
/* Zero-copy pointer into the i-th output's host buffer (valid until the
 * next Run / destroy). */
const void* PTC_GetOutputData(PTC_Predictor* p, int i);

void PTC_PredictorDestroy(PTC_Predictor* p);

/* Last error message (thread-local not guaranteed; single-caller API). */
const char* PTC_LastError(void);

#ifdef __cplusplus
}
#endif

#endif /* PADDLE_TPU_CAPI_H_ */
