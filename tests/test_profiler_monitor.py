"""Profiler + monitor gauges (reference: platform/profiler.cc RecordEvent,
fluid/profiler.py:314 profiler context, platform/monitor.h:77 StatRegistry)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core import monitor


class TestProfiler:
    def test_capture_trace_directory(self, tmp_path):
        log_dir = str(tmp_path / "prof")
        with paddle.profiler.Profiler(log_dir=log_dir) as prof:
            x = paddle.to_tensor(np.ones((8, 8), np.float32))
            with paddle.profiler.RecordEvent("my_region"):
                y = paddle.matmul(x, x)
            y.numpy()
            prof.step()
        assert not paddle.profiler.is_profiling()
        # jax writes plugins/profile/<ts>/*.xplane.pb under the log dir
        captured = [str(p) for p in (tmp_path / "prof").rglob("*")
                    if p.is_file()]
        assert captured, "no trace files captured"
        assert "steps=1" in prof.step_info()

    def test_timer_only_mode(self):
        with paddle.profiler.Profiler(timer_only=True) as prof:
            for _ in range(3):
                prof.step()
        assert "steps=3" in prof.step_info()

    def test_fluid_style_context(self, tmp_path):
        with paddle.profiler.profiler(log_dir=str(tmp_path / "p2")):
            x = paddle.to_tensor(np.ones((4,), np.float32))
            (x + x).numpy()
        assert not paddle.profiler.is_profiling()

    def test_record_event_begin_end(self):
        ev = paddle.profiler.RecordEvent("manual")
        ev.begin()
        ev.end()  # no active trace: must not raise


class TestMonitor:
    def test_stat_registry(self):
        reg = monitor.StatRegistry()
        assert reg.add("mem", 10) == 10
        assert reg.add("mem", 5) == 15
        reg.set("peak", 99.5)
        assert reg.get("peak") == 99.5
        assert reg.stats() == {"mem": 15, "peak": 99.5}
        reg.reset("mem")
        assert reg.get("mem") == 0
        reg.reset()
        assert reg.stats() == {}

    def test_module_level_gauges(self):
        monitor.stat_add("test_gauge", 3)
        monitor.stat_add("test_gauge", 4)
        assert monitor.stat_get("test_gauge") == 7
        monitor.default_registry().reset("test_gauge")

    def test_device_memory_stats_shape(self):
        stats = monitor.device_memory_stats()
        assert isinstance(stats, dict)
