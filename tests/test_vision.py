"""Vision package tests: model forward/backward shapes, torch parity for
ResNet-50 architecture (param count), transforms numerics, dataset parsing
(reference test analogs: python/paddle/tests/test_vision_models.py,
test_transforms.py, test_datasets.py)."""
import gzip
import os
import pickle
import struct
import tarfile

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.optimizer as optim
from paddle_tpu.vision import models, transforms, datasets
from paddle_tpu.vision.transforms import functional as TF


def _n_params(model):
    return sum(int(np.prod(p.shape)) for p in model.parameters())


class TestModels:
    def test_lenet_forward_backward(self):
        m = models.LeNet()
        x = paddle.to_tensor(np.random.rand(2, 1, 28, 28).astype(np.float32))
        out = m(x)
        assert out.shape == [2, 10]
        loss = paddle.mean(out ** 2)
        loss.backward()
        assert m.features[0].weight.grad is not None

    @pytest.mark.slow
    def test_resnet18_forward(self):
        m = models.resnet18(num_classes=7)
        m.eval()
        x = paddle.to_tensor(np.random.rand(2, 3, 64, 64).astype(np.float32))
        assert m(x).shape == [2, 7]

    @pytest.mark.slow
    def test_resnet50_param_count_matches_torchvision(self):
        # canonical ResNet-50 ImageNet param count
        m = models.resnet50()
        assert _n_params(m) == 25_557_032

    @pytest.mark.slow
    def test_resnet50_forward_backward(self):
        m = models.resnet50(num_classes=10)
        x = paddle.to_tensor(np.random.rand(2, 3, 64, 64).astype(np.float32))
        out = m(x)
        assert out.shape == [2, 10]
        loss = paddle.mean(out ** 2)
        loss.backward()
        assert m.conv1.weight.grad is not None

    @pytest.mark.slow
    def test_vgg11_forward(self):
        m = models.vgg11(num_classes=5)
        m.eval()
        x = paddle.to_tensor(np.random.rand(1, 3, 224, 224).astype(np.float32))
        assert m(x).shape == [1, 5]

    @pytest.mark.slow
    def test_mobilenet_v1_v2_forward(self):
        for ctor in (models.mobilenet_v1, models.mobilenet_v2):
            m = ctor(num_classes=4)
            m.eval()
            x = paddle.to_tensor(
                np.random.rand(1, 3, 96, 96).astype(np.float32))
            assert m(x).shape == [1, 4]

    @pytest.mark.slow
    def test_mobilenet_v3_forward(self):
        m = models.mobilenet_v3_small(num_classes=4)
        m.eval()
        x = paddle.to_tensor(np.random.rand(1, 3, 96, 96).astype(np.float32))
        assert m(x).shape == [1, 4]

    @pytest.mark.slow
    def test_resnet18_short_convergence(self):
        paddle.seed(1)
        m = models.resnet18(num_classes=4)
        opt = optim.Momentum(learning_rate=0.01, momentum=0.9,
                             parameters=m.parameters())
        rng = np.random.RandomState(0)
        X = rng.rand(8, 3, 32, 32).astype(np.float32)
        Y = rng.randint(0, 4, (8,)).astype(np.int64)
        ce = paddle.nn.CrossEntropyLoss()
        losses = []
        for _ in range(10):
            loss = ce(m(paddle.to_tensor(X)), paddle.to_tensor(Y))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]


class TestTransforms:
    def test_to_tensor_normalize(self):
        img = (np.random.rand(8, 6, 3) * 255).astype(np.uint8)
        t = TF.to_tensor(img)
        assert t.shape == [3, 8, 6]
        assert float(paddle.max(t).numpy()) <= 1.0
        n = TF.normalize(np.transpose(img, (2, 0, 1)).astype(np.float32),
                         mean=[127.5] * 3, std=[127.5] * 3)
        np.testing.assert_allclose(
            n, (np.transpose(img, (2, 0, 1)) - 127.5) / 127.5, rtol=1e-6)

    def test_resize(self):
        img = (np.random.rand(16, 8, 3) * 255).astype(np.uint8)
        out = TF.resize(img, (4, 4))
        assert out.shape == (4, 4, 3)
        out2 = TF.resize(img, 8)  # short side to 8
        assert out2.shape == (16, 8, 3)

    def test_crops_flips(self):
        img = np.arange(5 * 4 * 3, dtype=np.uint8).reshape(5, 4, 3)
        assert TF.center_crop(img, 2).shape == (2, 2, 3)
        np.testing.assert_array_equal(TF.hflip(img), img[:, ::-1])
        np.testing.assert_array_equal(TF.vflip(img), img[::-1])
        assert TF.crop(img, 1, 1, 3, 2).shape == (3, 2, 3)

    def test_pad(self):
        img = np.ones((2, 2, 3), np.uint8)
        out = TF.pad(img, 1)
        assert out.shape == (4, 4, 3)
        assert out[0, 0, 0] == 0

    def test_adjusts(self):
        img = (np.random.rand(4, 4, 3) * 255).astype(np.uint8)
        assert TF.adjust_brightness(img, 1.0).dtype == np.uint8
        np.testing.assert_array_equal(TF.adjust_brightness(img, 1.0), img)
        np.testing.assert_array_equal(TF.adjust_contrast(img, 1.0), img)
        np.testing.assert_allclose(TF.adjust_hue(img, 0.0).astype(int), img,
                                   atol=2)
        gray = TF.to_grayscale(img, 3)
        assert gray.shape == img.shape
        assert np.all(gray[..., 0] == gray[..., 1])

    def test_compose_pipeline(self):
        tf = transforms.Compose([
            transforms.Resize(10),
            transforms.RandomCrop(8),
            transforms.RandomHorizontalFlip(0.5),
            transforms.ColorJitter(0.1, 0.1, 0.1, 0.1),
            transforms.ToTensor(),
            transforms.Normalize([0.5] * 3, [0.5] * 3),
        ])
        img = (np.random.rand(12, 12, 3) * 255).astype(np.uint8)
        out = tf(img)
        assert out.shape == (3, 8, 8)

    def test_random_erasing(self):
        img = np.ones((10, 10, 3), np.uint8) * 7
        out = transforms.RandomErasing(prob=1.0)(img)
        assert (out == 0).any()


def _write_mnist(tmp_path, n=20):
    imgs = (np.random.rand(n, 28, 28) * 255).astype(np.uint8)
    labels = np.random.randint(0, 10, n).astype(np.uint8)
    ip = os.path.join(tmp_path, "imgs.gz")
    lp = os.path.join(tmp_path, "labels.gz")
    with gzip.open(ip, "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, 28, 28))
        f.write(imgs.tobytes())
    with gzip.open(lp, "wb") as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(labels.tobytes())
    return ip, lp, imgs, labels


class TestDatasets:
    def test_mnist(self, tmp_path):
        ip, lp, imgs, labels = _write_mnist(str(tmp_path))
        ds = datasets.MNIST(image_path=ip, label_path=lp)
        assert len(ds) == 20
        x, y = ds[3]
        assert x.shape == (1, 28, 28)
        assert int(y[0]) == labels[3]
        np.testing.assert_allclose(x[0], imgs[3] / 255.0, rtol=1e-6)

    def test_mnist_with_dataloader(self, tmp_path):
        ip, lp, _, _ = _write_mnist(str(tmp_path))
        ds = datasets.MNIST(image_path=ip, label_path=lp)
        loader = paddle.io.DataLoader(ds, batch_size=8, shuffle=True)
        xb, yb = next(iter(loader))
        assert list(xb.shape) == [8, 1, 28, 28]

    def test_cifar10(self, tmp_path):
        data = (np.random.rand(10, 3072) * 255).astype(np.uint8)
        labels = list(range(10))
        path = os.path.join(str(tmp_path), "cifar-10.tar.gz")
        batch_file = os.path.join(str(tmp_path), "data_batch_1")
        with open(batch_file, "wb") as f:
            pickle.dump({b"data": data, b"labels": labels}, f)
        test_file = os.path.join(str(tmp_path), "test_batch")
        with open(test_file, "wb") as f:
            pickle.dump({b"data": data[:4], b"labels": labels[:4]}, f)
        with tarfile.open(path, "w:gz") as tf:
            tf.add(batch_file, arcname="cifar-10-batches-py/data_batch_1")
            tf.add(test_file, arcname="cifar-10-batches-py/test_batch")
        ds = datasets.Cifar10(data_file=path, mode="train")
        assert len(ds) == 10
        x, y = ds[0]
        assert x.shape == (3, 32, 32)
        ds_test = datasets.Cifar10(data_file=path, mode="test")
        assert len(ds_test) == 4

    def test_dataset_folder(self, tmp_path):
        for cls in ("cat", "dog"):
            d = os.path.join(str(tmp_path), cls)
            os.makedirs(d)
            for i in range(3):
                np.save(os.path.join(d, f"{i}.npy"),
                        (np.random.rand(8, 8, 3) * 255).astype(np.uint8))
        ds = datasets.DatasetFolder(str(tmp_path))
        assert ds.classes == ["cat", "dog"]
        assert len(ds) == 6
        x, y = ds[0]
        assert y == 0
        flat = datasets.ImageFolder(str(tmp_path))
        assert len(flat) == 6

    def test_download_raises(self):
        with pytest.raises(RuntimeError, match="download"):
            datasets.MNIST()


class TestReviewRegressions:
    def test_random_crop_pad_if_needed_width(self):
        img = np.ones((32, 20, 3), np.uint8)
        out = transforms.RandomCrop(32, pad_if_needed=True)(img)
        assert out.shape == (32, 32, 3)

    def test_rotate_expand(self):
        img = np.ones((10, 20, 3), np.uint8) * 255
        out = TF.rotate(img, 45, expand=True)
        assert out.shape[0] > 10 and out.shape[1] > 20
        # area preserved up to half-pixel boundary losses (nearest sampling)
        assert (out > 0).sum() >= (img > 0).sum() * 0.85
        # 90 degrees swaps the canvas dims exactly
        out90 = TF.rotate(img, 90, expand=True)
        assert out90.shape == (20, 10, 3)

    def test_to_tensor_dark_uint8(self):
        img = np.full((2, 2, 3), 1, np.uint8)
        t = TF.to_tensor(img)
        np.testing.assert_allclose(np.asarray(t._data), 1 / 255.0, rtol=1e-5)
        f = np.full((2, 2, 3), 0.5, np.float32)
        np.testing.assert_allclose(np.asarray(TF.to_tensor(f)._data), 0.5)


def test_vision_ops_facade():
    """paddle.vision.ops parity (reference: vision/ops.py — yolo_loss,
    yolo_box, deform_conv2d/DeformConv2D, read_file/decode_jpeg)."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.vision.ops as VO

    paddle.seed(0)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(1, 4, 6, 6).astype(np.float32))
    off = paddle.to_tensor(np.zeros((1, 18, 6, 6), np.float32))
    layer = VO.DeformConv2D(4, 8, 3, padding=1)
    out = layer(x, off)
    ref = paddle.nn.functional.conv2d(x, layer.weight, layer.bias,
                                      padding=1)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-4)

    # yolo_loss alias == ops.yolov3_loss
    p = paddle.to_tensor(rng.randn(1, 18, 4, 4).astype(np.float32))
    gt = np.zeros((1, 3, 4), np.float32)
    gt[0, 0] = [0.5, 0.5, 0.3, 0.3]
    gl = np.zeros((1, 3), np.int64)
    a = VO.yolo_loss(p, paddle.to_tensor(gt), paddle.to_tensor(gl),
                     anchors=[10, 13, 16, 30, 33, 23], anchor_mask=[0, 1, 2],
                     class_num=1, ignore_thresh=0.7, downsample_ratio=32,
                     use_label_smooth=False)
    from paddle_tpu import ops
    b = ops.yolov3_loss(p, paddle.to_tensor(gt), paddle.to_tensor(gl),
                        anchors=[10, 13, 16, 30, 33, 23],
                        anchor_mask=[0, 1, 2], class_num=1,
                        ignore_thresh=0.7, downsample_ratio=32)
    np.testing.assert_allclose(a.numpy(), b.numpy(), rtol=1e-6)
    assert hasattr(VO, "read_file") and hasattr(VO, "decode_jpeg")
