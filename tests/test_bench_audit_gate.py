"""PTA009 bench-audit gate (tools/check_audit_regression.py).

The gate traces the bench step paths (resnet_train_step /
gpt_train_step, registered by paddle_tpu.models.bench_audit) and
compares the MFU-moving counters against the committed
bench_audit_baseline.json. These tests drive the gate through its
--report seam with synthetic reports: a seeded fusion-break or
host-transfer regression MUST exit 1; matching counts MUST pass.
"""
import copy
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO) if REPO not in sys.path else None

from tools import check_audit_regression as gate  # noqa: E402


def _clean_stats():
    return {
        "tags": ["train", "bench"], "path": "paddle_tpu/models/x.py",
        "line": 1, "error": "", "trace_count": 1,
        "fingerprints": ["aa", "aa"], "fingerprint_stable": True,
        "transfers": [], "large_consts": [], "donation": None,
        "hlo": {"instructions": 1000, "fusions": 50, "copies": 20,
                "custom_calls": 0, "host_transfers": 0},
    }


def _clean_payload():
    return {"version": 1, "platform": "cpu", "error": "",
            "entrypoints": {n: _clean_stats()
                            for n in gate.ENTRYPOINTS}}


@pytest.fixture()
def baseline_file(tmp_path):
    base = gate.summarize(_clean_payload())
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps({"version": 1, "entrypoints": base}))
    return str(path)


def _run(payload, baseline_file, tmp_path):
    report = tmp_path / "report.json"
    report.write_text(json.dumps(payload))
    return gate.main(["--report", str(report),
                      "--baseline", baseline_file])


class TestSummarize:
    def test_counts(self):
        p = _clean_payload()
        st = p["entrypoints"]["gpt_train_step"]
        st["transfers"] = ["device_put", "device_put", "io_callback"]
        st["large_consts"] = [{"elements": 99999}]
        st["trace_count"] = 3
        st["fingerprint_stable"] = False
        st["donation"] = {"donatable_inputs": 4, "total_inputs": 8,
                          "donatable_bytes": 1024}
        s = gate.summarize(p)["gpt_train_step"]
        assert s == {"host_transfers": 3, "large_consts": 1,
                     "donatable_inputs": 4, "retraces": 2,
                     "fingerprint_unstable": 1, "copy_fraction": 0.02,
                     "collective_bytes": 0, "collective_issues": 0,
                     "unfused_boundary_bytes": 0}

    def test_error_entrypoint_carried(self):
        p = _clean_payload()
        p["entrypoints"]["resnet_train_step"]["error"] = "boom"
        assert "error" in gate.summarize(p)["resnet_train_step"]

    def test_missing_entrypoint_is_error(self):
        p = _clean_payload()
        del p["entrypoints"]["gpt_train_step"]
        assert "error" in gate.summarize(p)["gpt_train_step"]


class TestGate:
    def test_matching_counts_pass(self, baseline_file, tmp_path):
        assert _run(_clean_payload(), baseline_file, tmp_path) == 0

    def test_seeded_host_transfer_fails(self, baseline_file, tmp_path,
                                        capsys):
        p = _clean_payload()
        p["entrypoints"]["gpt_train_step"]["transfers"] = ["device_put"]
        assert _run(p, baseline_file, tmp_path) == 1
        assert "host_transfers regressed 0 -> 1" in capsys.readouterr().out

    def test_seeded_fusion_break_fails(self, baseline_file, tmp_path,
                                       capsys):
        # copy fraction jumping 2% -> 12% is a broken fusion, not noise
        p = _clean_payload()
        p["entrypoints"]["resnet_train_step"]["hlo"]["copies"] = 120
        assert _run(p, baseline_file, tmp_path) == 1
        assert "fusion broke" in capsys.readouterr().out

    def test_copy_fraction_slack_tolerated(self, baseline_file, tmp_path):
        # within the absolute slack (XLA version skew), not a failure
        p = _clean_payload()
        p["entrypoints"]["resnet_train_step"]["hlo"]["copies"] = 40
        assert _run(p, baseline_file, tmp_path) == 0

    def test_seeded_retrace_fails(self, baseline_file, tmp_path):
        p = _clean_payload()
        p["entrypoints"]["gpt_train_step"]["trace_count"] = 2
        assert _run(p, baseline_file, tmp_path) == 1

    def test_entrypoint_trace_failure_fails(self, baseline_file, tmp_path):
        p = _clean_payload()
        p["entrypoints"]["gpt_train_step"]["error"] = "Traceback: boom"
        assert _run(p, baseline_file, tmp_path) == 1

    def test_missing_baseline_fails(self, tmp_path):
        assert _run(_clean_payload(), str(tmp_path / "nope.json"),
                    tmp_path) == 1

    def test_improvement_passes_and_never_ratchets_up(self, baseline_file,
                                                      tmp_path):
        p = _clean_payload()
        p["entrypoints"]["gpt_train_step"]["hlo"]["copies"] = 0
        assert _run(p, baseline_file, tmp_path) == 0


class TestCommittedBaseline:
    def test_baseline_is_committed_and_clean(self):
        with open(os.path.join(REPO, "bench_audit_baseline.json")) as f:
            base = json.load(f)["entrypoints"]
        for name in gate.ENTRYPOINTS:
            assert base[name]["host_transfers"] == 0
            assert base[name]["retraces"] == 0
            assert base[name]["donatable_inputs"] == 0
            assert base[name]["fingerprint_unstable"] == 0

    def test_bench_entrypoints_registered(self):
        from paddle_tpu.core import audit
        eps = audit.load_default_entrypoints()
        for name in gate.ENTRYPOINTS:
            assert name in eps
            assert "bench" in eps[name].tags


@pytest.mark.slow
def test_live_audit_matches_committed_baseline():
    """The real trace audit over the bench step paths must pass the gate
    against the committed baseline — i.e. --bench-check is green at this
    commit."""
    assert gate.main([]) == 0
