"""Sharded multi-replica serving: the GSPMD sharding substrate
(ShardingSpec / sidecar / resolve / cache tokens), the sharded Predictor
path, health-stamped checkpoint selection, the health-aware replica
Router, and the 2x4 replica-by-model acceptance run."""
import json
import os
import signal
import threading
import time

import numpy as np
import pytest

import jax
from jax.sharding import Mesh, PartitionSpec

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.core.monitor import StatRegistry
from paddle_tpu.incubate.checkpoint.sharded import (
    _corrupt_first_shard_file, newest_healthy_checkpoint, save_sharded,
    write_health_stamp)
from paddle_tpu.inference import Config, create_predictor
from paddle_tpu.serving import (
    EngineConfig, EngineDraining, NoHealthyReplicas, Replica, Router,
    RouterConfig, ShardingSpec, predictor_replica_factory)
from paddle_tpu.serving import sharding as shmod
from paddle_tpu.serving.cache import default_cache
from paddle_tpu.serving.engine import Engine
from paddle_tpu.serving.replica import DEAD, HEALTHY
from paddle_tpu.static import InputSpec


def _model_mesh(n=4, offset=0):
    devs = jax.devices()[offset:offset + n]
    return Mesh(np.array(devs), ("model",))


def _double(*arrays):
    return [np.asarray(a) * 2.0 for a in arrays]


def _callable_factory(fn=_double, **cfg):
    """Router engine factory over a plain callable (no artifact needed)."""
    cfg.setdefault("max_batch", 8)
    cfg.setdefault("max_batch_delay", 0.005)

    def factory(replica):
        ec = EngineConfig(**cfg)
        ec.stat_prefix = f"serving.replica{replica.replica_id}"
        return Engine(fn, ec, registry=replica.registry)
    return factory


def _mk_router(fn=_double, *, factory=None, **rcfg):
    rcfg.setdefault("num_replicas", 2)
    rcfg.setdefault("health_interval", 0.02)
    return Router(factory or _callable_factory(fn), RouterConfig(**rcfg),
                  registry=StatRegistry())


def _get(port, path):
    import urllib.error
    import urllib.request
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}") as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _post(port, path, payload):
    import urllib.error
    import urllib.request
    body = json.dumps(payload).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body, method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _wait_for(pred, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def _export(tmp_path, sharding=None, in_features=6):
    """jit.save a tiny softmax MLP; optional sharding sidecar rides along."""
    paddle.seed(0)

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(in_features, 16)
            self.fc2 = nn.Linear(16, 5)

        def forward(self, x):
            return nn.functional.softmax(
                self.fc2(nn.functional.relu(self.fc1(x))), axis=-1)

    prefix = str(tmp_path / "served")
    kwargs = {} if sharding is None else {"sharding": sharding}
    paddle.jit.save(Net(), prefix,
                    input_spec=[InputSpec([None, in_features], "float32",
                                          "x")],
                    **kwargs)
    return prefix


# ---------------------------------------------------------------------------
class TestShardingSpec:
    def test_json_roundtrip(self):
        spec = ShardingSpec({"model": 4},
                            inputs=[PartitionSpec("model")],
                            params=[None, PartitionSpec(None, "model")])
        doc = json.loads(json.dumps(spec.to_json_dict()))
        back = ShardingSpec.from_json_dict(doc)
        assert back.mesh_axes == {"model": 4}
        assert back.inputs == [PartitionSpec("model")]
        # None entries (replicated) survive the round trip as None
        assert back.params == [None, PartitionSpec(None, "model")]

    def test_mesh_token_distinguishes_device_subsets(self):
        t0 = shmod.mesh_token(_model_mesh(4, offset=0))
        t1 = shmod.mesh_token(_model_mesh(4, offset=4))
        assert t0 != t1                      # same names+shape, other devices
        assert t0 == shmod.mesh_token(_model_mesh(4, offset=0))

    def test_sidecar_roundtrip_and_malformed(self, tmp_path):
        prefix = str(tmp_path / "m")
        shmod.save_sidecar(prefix, ShardingSpec({"model": 2},
                                                inputs=[["model"]]))
        spec = shmod.load_sidecar(prefix)
        assert spec.mesh_axes == {"model": 2}
        assert spec.inputs == [PartitionSpec("model")]
        with open(shmod.sidecar_path(prefix), "w") as f:
            f.write("{not json")
        with pytest.warns(UserWarning, match="unreadable"):
            assert shmod.load_sidecar(prefix) is None
        assert shmod.load_sidecar(str(tmp_path / "absent")) is None

    def test_resolve_too_few_devices_falls_back(self):
        spec = ShardingSpec({"model": 64})
        with pytest.warns(UserWarning, match="falling back to replicated"):
            assert shmod.resolve(spec) is None

    def test_resolve_unknown_axis_falls_back(self):
        spec = ShardingSpec({"model": 2}, inputs=[PartitionSpec("data")])
        with pytest.warns(UserWarning, match="absent from mesh"):
            assert shmod.resolve(spec, n_inputs=1) is None

    def test_resolve_input_count_drift_falls_back(self):
        spec = ShardingSpec({"model": 2}, inputs=[None, None])
        with pytest.warns(UserWarning, match="falling back to replicated"):
            assert shmod.resolve(spec, n_inputs=1) is None

    def test_resolve_binds_shardings(self):
        spec = ShardingSpec({"model": 4}, inputs=[PartitionSpec("model")])
        rs = shmod.resolve(spec, n_inputs=1, n_params=3)
        assert rs is not None
        assert len(rs.input_shardings) == 1
        assert len(rs.param_shardings) == 3  # filled replicated
        assert rs.token[0] == "sharded"


# ---------------------------------------------------------------------------
class TestShardedPredictor:
    def test_sidecar_autoload_bitwise(self, tmp_path):
        prefix = _export(tmp_path,
                         sharding=ShardingSpec(
                             {"model": 4},
                             inputs=[PartitionSpec("model")]))
        sharded = create_predictor(Config(prefix))
        assert sharded.sharding is not None
        plain = create_predictor(Config(prefix).disable_sharding())
        assert plain.sharding is None
        x = np.random.RandomState(0).randn(8, 6).astype(np.float32)
        ys = sharded.run([x])[0]
        yp = plain.run([x])[0]
        # batch-axis sharding: each device owns whole rows, no reduction
        # is split, so the partitioned run is bitwise-identical
        assert np.array_equal(ys, yp)

    def test_dict_sharding_through_jit_save(self, tmp_path):
        prefix = _export(tmp_path, sharding={"mesh_axes": {"model": 4},
                                             "inputs": [["model"]]})
        spec = shmod.load_sidecar(prefix)
        assert spec.inputs == [PartitionSpec("model")]

    def test_cache_keys_never_collide(self, tmp_path):
        """Unsharded + two replicas over disjoint device subsets, same
        artifact and same input signature: three distinct executables."""
        prefix = _export(tmp_path)
        preds = [
            create_predictor(Config(prefix).disable_sharding()),
            create_predictor(Config(prefix).enable_sharding(
                mesh=_model_mesh(4, offset=0),
                input_specs=[PartitionSpec("model")])),
            create_predictor(Config(prefix).enable_sharding(
                mesh=_model_mesh(4, offset=4),
                input_specs=[PartitionSpec("model")])),
        ]
        x = np.ones((8, 6), np.float32)
        before = default_cache().stats()["misses"]
        outs = [p.run([x])[0] for p in preds]
        assert default_cache().stats()["misses"] == before + 3
        # and a second pass hits every cached executable
        for p in preds:
            p.run([x])
        assert default_cache().stats()["misses"] == before + 3
        assert np.array_equal(outs[0], outs[1])
        assert np.array_equal(outs[0], outs[2])


# ---------------------------------------------------------------------------
class TestNewestHealthyCheckpoint:
    def _mk(self, root, name, step):
        path = str(root / name)
        save_sharded({"w": np.arange(4, dtype=np.float32), "step": step},
                     path)
        return path

    def test_picks_newest_healthy(self, tmp_path):
        p1 = self._mk(tmp_path, "step_100", 100)
        p2 = self._mk(tmp_path, "step_200", 200)
        p3 = self._mk(tmp_path, "step_300", 300)
        assert newest_healthy_checkpoint(str(tmp_path)) == p3
        write_health_stamp(p3, healthy=False, reason="diverged")
        with pytest.warns(UserWarning, match="unhealthy"):
            assert newest_healthy_checkpoint(str(tmp_path)) == p2
        _corrupt_first_shard_file(p2)
        with pytest.warns(UserWarning):
            assert newest_healthy_checkpoint(str(tmp_path)) == p1

    def test_root_may_be_a_checkpoint_dir(self, tmp_path):
        p = self._mk(tmp_path, "only", 1)
        assert newest_healthy_checkpoint(p) == p

    def test_nothing_survives(self, tmp_path):
        assert newest_healthy_checkpoint(str(tmp_path)) is None
        p = self._mk(tmp_path, "step_1", 1)
        write_health_stamp(p, healthy=False)
        with pytest.warns(UserWarning, match="unhealthy"):
            assert newest_healthy_checkpoint(str(tmp_path)) is None


# ---------------------------------------------------------------------------
class TestRouter:
    def test_dispatch_balances(self):
        router = _mk_router()
        try:
            x = np.ones((2, 3), np.float32)
            for _ in range(8):
                y, = router.submit([x]).result(timeout=30)
                assert np.array_equal(y, x * 2.0)
            st = router.stats()
            assert st["total_dispatched"] == 8
            counts = [p["dispatched"] for p in st["replicas"].values()]
            assert counts == [4, 4]          # rotating tie-break
            assert st["balance_factor"] == 1.0
        finally:
            router.drain(timeout=30)

    def test_model_axes_pool_too_small(self):
        with pytest.raises(ValueError, match="devices"):
            _mk_router(num_replicas=4, model_axes={"model": 4})

    def test_draining_router_rejects(self):
        router = _mk_router()
        router.drain(timeout=30)
        with pytest.raises(EngineDraining):
            router.submit([np.ones((1, 2), np.float32)])

    def test_unhealthy_replica_drained_service_continues(self):
        router = _mk_router(auto_resurrect=False)
        try:
            r0, r1 = router.replicas
            r0.mark_unhealthy("test verdict")
            with pytest.warns(UserWarning, match="draining replica 0"):
                assert _wait_for(lambda: r0.state == DEAD)
            assert router.healthz()["status"] == "degraded"
            # traffic keeps flowing through the survivor
            y, = router.submit([np.ones((1, 2), np.float32)]) \
                       .result(timeout=30)
            assert y[0, 0] == 2.0
            assert r1.stats()["dispatched"] >= 1
            r1.mark_unhealthy("test verdict")
            assert _wait_for(lambda: r1.state == DEAD)
            with pytest.raises(NoHealthyReplicas):
                router.submit([np.ones((1, 2), np.float32)])
            assert router.healthz()["status"] == "unhealthy"
        finally:
            router.drain(timeout=30)

    def test_resurrect_boots_from_health_stamped_checkpoint(self, tmp_path):
        p1 = str(tmp_path / "step_1")
        p2 = str(tmp_path / "step_2")
        save_sharded({"w": np.zeros(2, np.float32)}, p1)
        save_sharded({"w": np.ones(2, np.float32)}, p2)
        write_health_stamp(p2, healthy=False, reason="diverged")
        router = _mk_router(restart_backoff=0.02, max_restarts=3,
                            checkpoint_root=str(tmp_path))
        try:
            r0 = router.replicas[0]
            assert r0.boot_checkpoint == p1     # newest healthy, not newest
            r0.mark_unhealthy("sentinel says no")
            with pytest.warns(UserWarning):
                assert _wait_for(lambda: r0.state == DEAD)
                assert _wait_for(lambda: r0.state == HEALTHY)
            st = r0.stats()
            assert st["restarts"] == 1
            assert st["boot_checkpoint"] == p1
            assert router.budget.used == 1
            assert _wait_for(
                lambda: router.healthz()["status"] == "ok")
            y, = router.submit([np.ones((1, 2), np.float32)]) \
                       .result(timeout=30)
            assert y[0, 0] == 2.0
        finally:
            router.drain(timeout=30)

    def test_exhausted_budget_stays_dead(self):
        router = _mk_router(max_restarts=0, auto_resurrect=True)
        try:
            r0 = router.replicas[0]
            r0.mark_unhealthy("bad")
            with pytest.warns(UserWarning, match="budget"):
                assert _wait_for(lambda: r0.state == DEAD)
                time.sleep(0.1)                 # a few sweeps
            assert r0.state == DEAD
            assert r0.stats()["restarts"] == 0
            # direct resurrection is budget-gated too
            assert r0.resurrect() is False
        finally:
            router.drain(timeout=30)

    def test_sigterm_fans_out_drain(self):
        router = _mk_router()
        router.install_drain_signal_handler()
        fut = router.submit([np.ones((1, 2), np.float32)])
        os.kill(os.getpid(), signal.SIGTERM)
        assert router._stopped.wait(timeout=30)
        assert fut.result(timeout=5)[0][0, 0] == 2.0   # in-flight resolved
        assert all(r.state == DEAD for r in router.replicas)
        router.drain(timeout=5)                 # idempotent + uninstalls

    def test_labeled_gauges_and_registry_dedup(self):
        router = _mk_router()
        try:
            assert _wait_for(lambda: len(router.registry.labeled(
                "serving.router.replica_healthy")) == 2)
            from paddle_tpu.observability.metrics import render_prometheus
            regs = router.registries()
            assert len(regs) == 1               # replicas share the registry
            text = render_prometheus(regs[0])
            assert 'replica="0"' in text and 'replica="1"' in text
        finally:
            router.drain(timeout=30)


# ---------------------------------------------------------------------------
class TestLLMReplicaPrefixes:
    def test_stats_do_not_cross_prefix_boundaries(self):
        """serving.llm.replica1 must not swallow serving.llm.replica10
        counters (the trailing-dot prefix fix in LLMEngine.stats)."""
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
        from paddle_tpu.serving.llm import LLMEngine, LLMEngineConfig
        net = GPTForCausalLM(GPTConfig(
            vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
            max_position_embeddings=128, hidden_dropout_prob=0.0,
            attention_dropout_prob=0.0))
        net.eval()
        reg = StatRegistry()
        cfg = LLMEngineConfig(num_slots=4, max_seq=32, warmup=False,
                              stat_prefix="serving.llm.replica1")
        eng = LLMEngine(net, cfg, registry=reg)
        try:
            reg.add("serving.llm.replica10.queued", 7)   # foreign replica
            keys = set(eng.stats()["stats"])
            assert not any(k.startswith("serving.llm.replica10.")
                           for k in keys)
        finally:
            eng.drain(timeout=30)


@pytest.mark.slow
class TestShardedLLMDecode:
    @pytest.mark.timeout_s(240)
    def test_slot_sharded_tokens_identical(self):
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
        from paddle_tpu.serving.llm import LLMEngine, LLMEngineConfig
        paddle.seed(7)
        net = GPTForCausalLM(GPTConfig(
            vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
            max_position_embeddings=128, hidden_dropout_prob=0.0,
            attention_dropout_prob=0.0))
        net.eval()
        prompts = [[1, 2, 3], [4, 5], [6, 7, 8, 9]]

        def run(mesh):
            cfg = LLMEngineConfig(num_slots=8, max_seq=64, warmup=False)
            eng = LLMEngine(net, cfg, mesh=mesh)
            try:
                reqs = [eng.submit(p, max_new_tokens=8) for p in prompts]
                return [r.result(timeout=120)["tokens"] for r in reqs]
            finally:
                eng.drain(timeout=60)

        plain = run(None)
        sharded = run(_model_mesh(4))
        # KV slots sharded over the model axis: every slot's rows live
        # whole on one device, so greedy decode is token-identical
        assert plain == sharded


# ---------------------------------------------------------------------------
class TestHTTPRouter:
    @pytest.fixture()
    def served(self):
        from paddle_tpu.serving.http import make_server
        router = _mk_router()
        srv = make_server(None, port=0, router=router)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        yield router, srv.server_address[1]
        srv.shutdown()
        srv.server_close()
        router.drain(timeout=30)

    def test_endpoints(self, served):
        router, port = served
        code, body = _get(port, "/healthz")
        assert code == 200 and body["status"] == "ok"
        assert len(body["replicas"]) == 2

        x = [[1.0, 2.0], [3.0, 4.0]]
        code, body = _post(port, "/predict", {"inputs": [x]})
        assert code == 200
        assert np.allclose(body["outputs"][0], np.asarray(x) * 2.0)

        code, body = _get(port, "/statsz")
        assert code == 200 and body["router"]["total_dispatched"] >= 1

        import urllib.request
        assert _wait_for(lambda: router.registry.labeled(
            "serving.router.replica_healthy"))
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metricsz") as r:
            text = r.read().decode()
        assert 'paddle_tpu_serving_router_replica_healthy{replica="0"}' \
            in text

    def test_drain_flips_healthz(self, served):
        router, port = served
        router.begin_drain()
        assert router._stopped.wait(timeout=30)
        code, body = _get(port, "/healthz")
        assert code == 503 and body["status"] == "draining"


# ---------------------------------------------------------------------------
class TestAcceptance2x4:
    """The issue's acceptance run: a 2-replica x 4-way-model router serves
    a GSPMD-partitioned predictor bitwise-identically to single-device,
    keeps serving while one replica drains unhealthy, and resurrects it
    from a health-stamped checkpoint."""

    @pytest.mark.timeout_s(240)
    def test_full_cycle(self, tmp_path):
        prefix = _export(tmp_path,
                         sharding=ShardingSpec(
                             {"model": 4},
                             inputs=[PartitionSpec("model")]))
        ckroot = tmp_path / "ckpts"
        ckroot.mkdir()
        good = str(ckroot / "step_10")
        bad = str(ckroot / "step_20")
        save_sharded({"w": np.zeros(2, np.float32)}, good)
        save_sharded({"w": np.ones(2, np.float32)}, bad)
        write_health_stamp(bad, healthy=False, reason="diverged")

        ref = create_predictor(Config(prefix).disable_sharding())
        rng = np.random.RandomState(3)
        sizes = [1, 2, 3, 4, 5, 6, 7, 8] * 2
        payloads = [rng.randn(n, 6).astype(np.float32) for n in sizes]
        serial = [ref.run([x])[0] for x in payloads]

        # batch buckets 4/8: every padded batch divides the 4-way model
        # axis, so the batch-sharded device_put always lands
        ecfg = EngineConfig(batch_buckets=[4, 8], max_batch=8,
                            max_batch_delay=0.01, max_queue=64)
        router = Router(
            predictor_replica_factory(prefix, ecfg),
            RouterConfig(num_replicas=2, model_axes={"model": 4},
                         health_interval=0.05, restart_backoff=0.02,
                         checkpoint_root=str(ckroot)),
            registry=StatRegistry())
        try:
            meshes = [r.mesh for r in router.replicas]
            assert all(m is not None for m in meshes)
            ids = [set(d.id for d in m.devices.flat) for m in meshes]
            assert ids[0].isdisjoint(ids[1])    # 2 x 4 disjoint sub-meshes
            assert all(r.boot_checkpoint == good for r in router.replicas)

            misses_before = default_cache().stats()["misses"]
            futs = [router.submit([x]) for x in payloads]
            for fut, want in zip(futs, serial):
                got, = fut.result(timeout=120)
                assert np.array_equal(got, want)
            # the replicas compiled their own GSPMD executables (distinct
            # sharded cache keys; the reference's unsharded compiles all
            # happened before this window)
            assert default_cache().stats()["misses"] >= misses_before + 2
            st = router.stats()
            assert st["total_dispatched"] == len(payloads)
            counts = [p["dispatched"] for p in st["replicas"].values()]
            assert all(c > 0 for c in counts)

            # one replica turns unhealthy: drained, service continues
            r0 = router.replicas[0]
            r0.mark_unhealthy("sentinel divergence")
            with pytest.warns(UserWarning):
                assert _wait_for(lambda: r0.state == DEAD, timeout=60)
                assert router.healthz()["status"] == "degraded"
                for x, want in zip(payloads[:4], serial[:4]):
                    got, = router.submit([x]).result(timeout=120)
                    assert np.array_equal(got, want)
                # ... and resurrects from the health-stamped checkpoint
                assert _wait_for(lambda: r0.state == HEALTHY, timeout=120)
            assert r0.stats()["restarts"] == 1
            assert r0.boot_checkpoint == good
            assert _wait_for(
                lambda: router.healthz()["status"] == "ok", timeout=60)
            got, = router.submit([payloads[0]]).result(timeout=120)
            assert np.array_equal(got, serial[0])
        finally:
            router.drain(timeout=60)
        with pytest.raises(EngineDraining):
            router.submit([payloads[0]])


# ---------------------------------------------------------------------------
@pytest.mark.slow
class TestServeCLIPort0:
    @pytest.mark.timeout_s(240)
    def test_ephemeral_port_and_replicas(self, tmp_path):
        import subprocess
        import sys
        import urllib.request
        prefix = _export(tmp_path)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.serving", "serve",
             "--model", prefix, "--port", "0", "--replicas", "2",
             "--max-delay-ms", "1"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        try:
            port = None
            for _ in range(200):
                line = proc.stdout.readline()
                if not line:
                    break
                if line.startswith("PADDLE_TPU_SERVING_PORT="):
                    port = int(line.strip().split("=", 1)[1])
                    break
            assert port, "server never printed its port"
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=30) as r:
                body = json.loads(r.read())
            assert body["status"] == "ok"
            assert len(body["replicas"]) == 2
            proc.send_signal(signal.SIGTERM)
            assert proc.wait(timeout=120) == 0
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
