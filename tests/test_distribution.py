"""paddle.distribution parity (reference: python/paddle/distribution.py
Uniform :168, Normal :390, Categorical :640) — densities vs scipy,
sampling vs distribution statistics, and the reference's pinned
Categorical quirk (softmax entropy/kl, sum-normalised probs)."""
import numpy as np
import pytest
import scipy.stats as st

import paddle_tpu as paddle
from paddle_tpu.distribution import Uniform, Normal, Categorical


def test_uniform_density_entropy_sample():
    u = Uniform([0.0], [2.0])
    np.testing.assert_allclose(u.entropy().numpy(), [np.log(2.0)],
                               rtol=1e-6)
    v = paddle.to_tensor(np.array([0.8], np.float32))
    np.testing.assert_allclose(u.log_prob(v).numpy(), [-np.log(2.0)],
                               rtol=1e-6)
    np.testing.assert_allclose(u.probs(v).numpy(), [0.5], rtol=1e-6)
    out = u.probs(paddle.to_tensor(np.array([2.5], np.float32))).numpy()
    np.testing.assert_allclose(out, [0.0])
    paddle.seed(0)
    s = u.sample([5000]).numpy()
    assert s.shape == (5000, 1)
    assert s.min() >= 0 and s.max() < 2
    assert abs(s.mean() - 1.0) < 0.03
    # broadcasting low/high
    u2 = Uniform(3.0, [5.0, 6.0, 7.0])
    assert u2.sample([4]).shape == [4, 3]


def test_normal_matches_scipy():
    n = Normal([0.5], [1.5])
    v = np.array([1.2], np.float32)
    np.testing.assert_allclose(
        n.log_prob(paddle.to_tensor(v)).numpy(),
        st.norm.logpdf(v, 0.5, 1.5), rtol=1e-5)
    np.testing.assert_allclose(
        n.probs(paddle.to_tensor(v)).numpy(),
        st.norm.pdf(v, 0.5, 1.5), rtol=1e-5)
    np.testing.assert_allclose(n.entropy().numpy(),
                               st.norm.entropy(0.5, 1.5), rtol=1e-5)
    m = Normal([1.0], [2.0])
    # analytic KL(N0||N1)
    mu1, s1, mu2, s2 = 0.5, 1.5, 1.0, 2.0
    ref = (np.log(s2 / s1) + (s1 ** 2 + (mu1 - mu2) ** 2) / (2 * s2 ** 2)
           - 0.5)
    np.testing.assert_allclose(n.kl_divergence(m).numpy(), [ref],
                               rtol=1e-5)
    paddle.seed(1)
    s = n.sample([8000]).numpy()
    assert abs(s.mean() - 0.5) < 0.06 and abs(s.std() - 1.5) < 0.06


def test_categorical_reference_quirk():
    # the reference's own docstring example pins both behaviours
    x = np.array([0.5535528, 0.20714243, 0.01162981, 0.51577556,
                  0.36369765, 0.2609165], np.float32)
    y = np.array([0.77663314, 0.90824795, 0.15685187, 0.04279523,
                  0.34468332, 0.7955718], np.float32)
    cat, cat2 = Categorical(x), Categorical(y)
    np.testing.assert_allclose(cat.entropy().numpy(), 1.77528, rtol=1e-4)
    np.testing.assert_allclose(cat.kl_divergence(cat2).numpy(), 0.071952,
                               rtol=1e-3)
    value = paddle.to_tensor(np.array([2, 1, 3], np.int64))
    np.testing.assert_allclose(cat.probs(value).numpy(),
                               [0.00608027, 0.108298, 0.269656],
                               rtol=1e-4)
    np.testing.assert_allclose(cat.log_prob(value).numpy(),
                               [-5.10271, -2.22287, -1.31061], rtol=1e-4)
    paddle.seed(2)
    s = cat.sample([2, 3]).numpy()
    assert s.shape == (2, 3) and s.min() >= 0 and s.max() <= 5
    # empirical frequencies follow sum-normalised probs
    paddle.seed(3)
    big = cat.sample([20000]).numpy()
    p_emp = np.bincount(big, minlength=6) / big.size
    np.testing.assert_allclose(p_emp, x / x.sum(), atol=0.02)


def test_categorical_batched_gather():
    # batched logits [B, K] + value [B]: per-row gather, not a cross
    # product (round-5 review finding)
    logits = np.array([[1.0, 3.0], [2.0, 2.0]], np.float32)
    cat = Categorical(logits)
    v = paddle.to_tensor(np.array([1, 0], np.int64))
    got = cat.probs(v).numpy()
    np.testing.assert_allclose(got, [3.0 / 4.0, 2.0 / 4.0], rtol=1e-6)
    np.testing.assert_allclose(cat.log_prob(v).numpy(),
                               np.log([0.75, 0.5]), rtol=1e-5)
