"""TPU-native PS replacement: mesh-sharded embedding table
(docs/adr/0001-parameter-server.md; reference capability:
distributed/table/common_sparse_table.h:112, the_one_ps.py:434)."""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.fleet import (ShardedEmbedding,
                                          sparse_row_update, make_row_state)


@pytest.fixture(autouse=True)
def _mesh():
    dist.set_mesh(dist.build_mesh({"dp": 8}))
    yield
    dist.set_mesh(None)


class TestShardedEmbedding:
    def test_table_is_sharded_and_lookup_correct(self):
        paddle.seed(0)
        emb = ShardedEmbedding(64, 16)
        # table rows sharded over the mesh: each device holds 8 rows
        shards = emb.weight._data.addressable_shards
        assert len(shards) == 8
        assert shards[0].data.shape == (8, 16)
        ids = paddle.to_tensor(np.array([[0, 13, 63], [5, 5, 42]], np.int32))
        out = emb(ids)
        ref = emb.weight.numpy()[ids.numpy()]
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-6)

    def test_gradients_flow(self):
        paddle.seed(0)
        emb = ShardedEmbedding(32, 8)
        ids = paddle.to_tensor(np.array([1, 3, 1], np.int32))
        loss = emb(ids).sum()
        loss.backward()
        g = emb.weight.grad.numpy()
        assert g[1].sum() == 16.0  # id 1 appears twice, D=8
        assert g[3].sum() == 8.0
        assert np.abs(g[[0, 2, 4]]).sum() == 0

    def test_vocab_not_divisible_raises(self):
        with pytest.raises(ValueError, match="divide"):
            ShardedEmbedding(30, 8)


class TestSparseRowUpdate:
    def test_only_touched_rows_change_and_dups_sum(self):
        rng = np.random.RandomState(0)
        V, D = 16, 4
        t = jnp.asarray(rng.randn(V, D).astype(np.float32))
        m, v = jnp.zeros((V, D)), jnp.zeros((V, D))
        ids = jnp.asarray([2, 2, 7], jnp.int32)
        g = jnp.asarray(rng.randn(3, D).astype(np.float32))
        nt, nm, nv = sparse_row_update(t, m, v, ids, g, lr=0.1, step=1)
        nt, nm, nv = map(np.asarray, (nt, nm, nv))
        untouched = [i for i in range(V) if i not in (2, 7)]
        np.testing.assert_allclose(nt[untouched], np.asarray(t)[untouched])
        assert np.abs(nm[untouched]).sum() == 0
        # row 2 saw the SUM of its two grad rows (segment-sum semantics)
        dense = np.zeros((V, D), np.float32)
        dense[2] = np.asarray(g[0] + g[1])
        dense[7] = np.asarray(g[2])
        expect_m = 0.1 * dense  # (1-beta1) * g
        np.testing.assert_allclose(nm, expect_m, rtol=1e-5, atol=1e-6)
        assert not np.allclose(nt[2], np.asarray(t)[2])

    def test_sharded_state_follows_table(self):
        paddle.seed(0)
        emb = ShardedEmbedding(64, 16)
        m, v = make_row_state(emb.weight)
        assert m.sharding == emb.weight._data.sharding
        ids = jnp.asarray([0, 8, 63], jnp.int32)
        g = jnp.ones((3, 16), jnp.float32)
        nt, nm, nv = sparse_row_update(emb.weight._data, m, v, ids, g,
                                       lr=0.01, step=1)
        assert np.abs(np.asarray(nm)[1]).sum() == 0
        assert np.abs(np.asarray(nm)[8]).sum() > 0
