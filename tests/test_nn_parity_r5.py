"""Round-5 nn-surface additions: export parity vs the reference's
nn/functional __all__, BiRNN vs torch's bidirectional GRU,
BeamSearchDecoder+dynamic_decode vs brute-force enumeration,
HSigmoidLoss/PairwiseDistance layers, inplace functional aliases."""
import re

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_export_parity_nn_and_functional():
    for path, ours in [
            ("/root/reference/python/paddle/nn/__init__.py", nn),
            ("/root/reference/python/paddle/nn/functional/__init__.py", F)]:
        src = open(path).read()
        names = re.findall(r"from \.[\w.]+ import (\w+)", src)
        names += re.findall(r"^\s+'(\w+)',?\s*$", src, re.M)
        missing = sorted(set(n for n in names
                             if not n.startswith("_")
                             and not hasattr(ours, n)))
        assert not missing, (path, missing)


def test_birnn_matches_torch():
    import torch
    paddle.seed(0)
    cf, cb = nn.GRUCell(3, 4), nn.GRUCell(3, 4)
    bi = nn.BiRNN(cf, cb)
    tg = torch.nn.GRU(3, 4, batch_first=True, bidirectional=True)
    for ours, pre in [(cf, ""), (cb, "_reverse")]:
        getattr(tg, "weight_ih_l0" + pre).data = \
            torch.from_numpy(ours.weight_ih.numpy().copy())
        getattr(tg, "weight_hh_l0" + pre).data = \
            torch.from_numpy(ours.weight_hh.numpy().copy())
        getattr(tg, "bias_ih_l0" + pre).data = \
            torch.from_numpy(ours.bias_ih.numpy().copy())
        getattr(tg, "bias_hh_l0" + pre).data = \
            torch.from_numpy(ours.bias_hh.numpy().copy())
    x = np.random.RandomState(0).randn(2, 5, 3).astype(np.float32)
    out, _ = bi(paddle.to_tensor(x))
    ref, _ = tg(torch.from_numpy(x))
    np.testing.assert_allclose(out.numpy(), ref.detach().numpy(),
                               rtol=1e-4, atol=1e-5)


class _TableCell(nn.Layer):
    """Deterministic 'cell': logits depend only on the input token —
    makes exact brute-force enumeration of sequence scores possible."""

    def __init__(self, table):
        super().__init__()
        self._table = paddle.to_tensor(table)

    def forward(self, ids, states):
        from paddle_tpu import ops
        logits = ops.gather(self._table, ids)
        return logits, states


def test_beam_search_decoder_matches_bruteforce():
    rng = np.random.RandomState(3)
    V, T, K = 5, 3, 3
    table = rng.randn(V, V).astype(np.float32) * 2.0
    cell = _TableCell(table)
    dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=V - 1,
                               beam_size=K)
    h0 = paddle.to_tensor(np.zeros((1, 2), np.float32))
    ids, scores = nn.dynamic_decode(dec, inits=h0, max_step_num=T)
    assert tuple(ids.shape) == (1, T, K)

    # brute force: enumerate all V^T sequences, score with log-softmax
    # chain + end-token absorption
    import itertools
    logp = np.log(np.exp(table) / np.exp(table).sum(-1, keepdims=True))
    best = []
    for seq in itertools.product(range(V), repeat=T):
        s, prev, done = 0.0, 0, False
        for tok in seq:
            if done:
                if tok != V - 1:
                    s = -np.inf
                continue
            s += logp[prev, tok]
            prev = tok
            if tok == V - 1:
                done = True
        best.append((s, seq))
    best.sort(key=lambda t: -t[0])
    got_scores = scores.numpy()[0]
    exp_scores = np.array([b[0] for b in best[:K]])
    np.testing.assert_allclose(np.sort(got_scores)[::-1], exp_scores,
                               rtol=1e-4)
    # the top beam's token sequence matches the argmax enumeration
    top_k_col = int(np.argmax(got_scores))
    np.testing.assert_array_equal(ids.numpy()[0, :, top_k_col],
                                  list(best[0][1]))


def test_hsigmoid_layer_and_pairwise_distance():
    paddle.seed(1)
    lay = nn.HSigmoidLoss(8, 6)
    x = np.random.RandomState(0).randn(4, 8).astype(np.float32)
    lab = np.random.RandomState(1).randint(0, 6, (4,)).astype(np.int64)
    out = lay(paddle.to_tensor(x), paddle.to_tensor(lab))
    ref = F.hsigmoid_loss(paddle.to_tensor(x), paddle.to_tensor(lab), 6,
                          lay.weight, lay.bias)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-6)

    pd = nn.PairwiseDistance(p=2.0)
    a = np.random.RandomState(2).randn(3, 4).astype(np.float32)
    b = np.random.RandomState(3).randn(3, 4).astype(np.float32)
    got = pd(paddle.to_tensor(a), paddle.to_tensor(b)).numpy()
    ref = np.linalg.norm(a - b + 1e-6, axis=-1)
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_functional_inplace_aliases_on_tape():
    x = paddle.to_tensor(np.array([0.2, -0.4], np.float32),
                         stop_gradient=False)
    y = x * 3.0
    F.tanh_(y)
    y.sum().backward()
    ref = 3.0 * (1 - np.tanh(np.array([0.6, -1.2])) ** 2)
    np.testing.assert_allclose(x.grad.numpy(), ref, rtol=1e-3, atol=1e-6)
    z = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    F.softmax_(z)
    np.testing.assert_allclose(z.numpy().sum(), 1.0, rtol=1e-6)
    w = paddle.to_tensor(np.array([-1.0, 1.0], np.float32))
    F.elu_(w)
    np.testing.assert_allclose(w.numpy()[1], 1.0)


def test_spectral_norm_functional_alias():
    # paddle.nn.spectral_norm (fluid-style functional; alias of
    # utils_weight_norm.spectral_norm_fn)
    paddle.seed(2)
    w = np.random.RandomState(4).randn(6, 4).astype(np.float32)
    got = nn.spectral_norm(paddle.to_tensor(w), power_iters=50)
    sigma = np.linalg.svd(w, compute_uv=False)[0]
    np.testing.assert_allclose(got.numpy(), w / sigma, rtol=1e-3,
                               atol=1e-4)


def test_rnn_sequence_length_masks_padded_rows():
    """RNN/BiRNN with sequence_length: outputs past a row's length are
    zero, final states freeze at the row's end, backward direction reads
    only the valid prefix (verified vs torch packed sequences for the
    BiRNN in the drive; here the single-direction invariants)."""
    paddle.seed(3)
    cell = nn.GRUCell(3, 4)
    layer = nn.RNN(cell)
    x = np.random.RandomState(5).randn(2, 5, 3).astype(np.float32)
    lens = paddle.to_tensor(np.array([5, 2]))
    out, last = layer(paddle.to_tensor(x), sequence_length=lens)
    o = out.numpy()
    assert np.abs(o[1, 2:]).max() == 0.0          # masked tail
    np.testing.assert_allclose(last.numpy()[1], o[1, 1], rtol=1e-5)
    # row 0 (full length) identical to the unmasked run
    out_full, _ = layer(paddle.to_tensor(x))
    np.testing.assert_allclose(o[0], out_full.numpy()[0], rtol=1e-5)


def test_spectral_norm_functional_deterministic():
    w = np.random.RandomState(6).randn(6, 4).astype(np.float32)
    a = nn.spectral_norm(paddle.to_tensor(w)).numpy()
    b = nn.spectral_norm(paddle.to_tensor(w)).numpy()
    np.testing.assert_array_equal(a, b)           # deterministic
    sigma = np.linalg.svd(w, compute_uv=False)[0]
    np.testing.assert_allclose(a, w / sigma, rtol=1e-3, atol=1e-4)


def test_dynamic_decode_rejects_unknown_kwargs():
    cell = _TableCell(np.eye(4, dtype=np.float32))
    dec = nn.BeamSearchDecoder(cell, 0, 3, 2)
    h0 = paddle.to_tensor(np.zeros((1, 2), np.float32))
    with pytest.raises(TypeError, match="impute_finished|unsupported"):
        nn.dynamic_decode(dec, inits=h0, max_step_num=2,
                          impute_finished=True)
    # output_time_major works
    ids, _ = nn.dynamic_decode(dec, inits=h0, max_step_num=2,
                               output_time_major=True)
    assert tuple(ids.shape) == (2, 1, 2)
