"""Every documented DistributedStrategy flag takes effect or raises/warns —
no silent no-ops (round-3 verdict item 3; reference:
fleet/base/distributed_strategy.py + meta_optimizers/{localsgd_optimizer.py,
fp16_allreduce_optimizer.py, dgc_optimizer.py}, docs/adr/0002-dgc.md)."""
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
import paddle_tpu.distributed as dist
import paddle_tpu.distributed.fleet as fleet
from paddle_tpu.distributed.fleet import DistributedStrategy


@pytest.fixture(autouse=True)
def _clean_state():
    yield
    import paddle_tpu.amp as amp
    amp.disable_operator_amp()
    dist.set_mesh(None)


def _model():
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


class TestFlagErrors:
    def test_dgc_raises_with_adr_pointer(self):
        st = DistributedStrategy()
        st.dgc = True
        with pytest.raises(NotImplementedError, match="0002-dgc"):
            fleet.init(is_collective=True, strategy=st)

    def test_pipeline_without_pp_degree_raises(self):
        st = DistributedStrategy()
        st.pipeline = True
        with pytest.raises(ValueError, match="pp_degree"):
            fleet.init(is_collective=True, strategy=st)

    def test_tensor_parallel_without_degree_raises(self):
        st = DistributedStrategy()
        st.tensor_parallel = True
        with pytest.raises(ValueError, match="tensor_parallel_degree"):
            fleet.init(is_collective=True, strategy=st)

    def test_unknown_field_raises(self):
        st = DistributedStrategy()
        with pytest.raises(AttributeError):
            st.no_such_flag = True


class TestFlagWarnings:
    @pytest.mark.parametrize("field,value,pat", [
        ("nccl_comm_num", 4, "nccl_comm_num"),
        ("fuse_all_reduce_ops", False, "fuse_all_reduce_ops"),
        ("fuse_grad_size_in_MB", 64, "fuse_grad_size"),
        ("find_unused_parameters", True, "find_unused_parameters"),
    ])
    def test_absorbed_flags_warn(self, field, value, pat):
        st = DistributedStrategy()
        setattr(st, field, value)
        with pytest.warns(UserWarning, match=pat):
            fleet.init(is_collective=True, strategy=st)

    def test_recompute_without_checkpoints_warns(self):
        st = DistributedStrategy()
        st.recompute = True
        fleet.init(is_collective=True, strategy=st)
        with pytest.warns(UserWarning, match="checkpoints"):
            fleet.distributed_model(_model())


class TestFlagEffects:
    def test_amp_o1_enables_operator_autocast(self):
        import paddle_tpu.amp as amp
        st = DistributedStrategy()
        st.amp = True
        fleet.init(is_collective=True, strategy=st)
        assert not amp.is_auto_cast_enabled()
        m = fleet.distributed_model(_model())
        assert amp.is_auto_cast_enabled()
        # matmul (white-listed) actually runs in bf16
        x = paddle.to_tensor(np.ones((4, 8), np.float32))
        out = m(x)
        assert str(out._data.dtype) == "bfloat16"

    def test_amp_o2_casts_params(self):
        import paddle_tpu.amp as amp
        st = DistributedStrategy()
        st.amp = True
        st.amp_configs = {"use_pure_fp16": True}
        fleet.init(is_collective=True, strategy=st)
        m = _model()
        fleet.distributed_model(m)
        assert amp.is_auto_cast_enabled()
        assert str(m.parameters()[0]._data.dtype) == "bfloat16"

    def test_recompute_wraps_named_sublayers(self):
        st = DistributedStrategy()
        st.recompute = True
        names = [n for n, _ in _model().named_sublayers()]
        target = names[0]
        st.recompute_configs = {"checkpoints": [target]}
        fleet.init(is_collective=True, strategy=st)
        m = _model()
        fleet.distributed_model(m)
        # the wrapped sublayer gets an instance-level forward; others keep
        # the class method
        overridden = {n for n, s in m.named_sublayers()
                      if "forward" in s.__dict__}
        assert overridden == {target}
        # numerics unchanged
        x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))
        ref = _model()
        ref.set_state_dict(m.state_dict())
        np.testing.assert_allclose(m(x).numpy(), ref(x).numpy(), rtol=1e-6)

    def test_recompute_unknown_checkpoint_raises(self):
        st = DistributedStrategy()
        st.recompute = True
        st.recompute_configs = {"checkpoints": ["nope"]}
        fleet.init(is_collective=True, strategy=st)
        with pytest.raises(ValueError, match="nope"):
            fleet.distributed_model(_model())

    def test_localsgd_wraps_and_averages_every_k(self, monkeypatch):
        st = DistributedStrategy()
        st.localsgd = True
        st.localsgd_configs = {"k_steps": 3, "begin_step": 2}
        fleet.init(is_collective=True, strategy=st)
        m = _model()
        o = fleet.distributed_optimizer(
            opt.SGD(learning_rate=0.1, parameters=m.parameters()), st)
        from paddle_tpu.distributed.fleet.utils import LocalSGDOptimizer
        assert isinstance(o, LocalSGDOptimizer)
        calls = []
        monkeypatch.setattr(o, "_average_params",
                            lambda: calls.append(o._t))
        x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))
        for _ in range(8):
            loss = (m(x) ** 2).mean()
            loss.backward()
            o.step()
            o.clear_grad()
        # begin at step 2, then every 3: steps 2, 5, 8
        assert calls == [2, 5, 8]

    @pytest.mark.slow
    def test_fp16_allreduce_casts_grad_exchange(self, monkeypatch):
        st = DistributedStrategy()
        st.fp16_allreduce = True
        st.hybrid_configs = {"dp_degree": 8}
        fleet.init(is_collective=True, strategy=st)
        dist.set_mesh(dist.build_mesh({"dp": 8}))
        m = fleet.distributed_model(_model())
        from paddle_tpu.distributed.parallel import DataParallel
        assert isinstance(m, DataParallel)
        assert m._bf16_allreduce
        # drive apply_collective_grads with a fake multi-process world and
        # capture the dtype crossing the collective
        seen = []
        import paddle_tpu.distributed.parallel as pmod

        monkeypatch.setattr(pmod.jax, "process_count", lambda: 2)
        monkeypatch.setattr(
            pmod.C, "all_reduce",
            lambda t, op=None, group=None: seen.append(str(t._data.dtype)))
        x = paddle.to_tensor(np.random.randn(8, 8).astype(np.float32))
        (m(x) ** 2).mean().backward()
        m.apply_collective_grads()
        assert seen and all(d == "bfloat16" for d in seen)
        # grads come back f32 for the optimizer
        assert all(str(p._grad.dtype) == "float32"
                   for p in m.parameters() if p._grad is not None)

    def test_gradient_merge_still_effective(self):
        st = DistributedStrategy()
        st.gradient_merge = True
        st.gradient_merge_configs = {"k_steps": 2, "avg": True}
        fleet.init(is_collective=True, strategy=st)
        m = _model()
        o = fleet.distributed_optimizer(
            opt.SGD(learning_rate=0.1, parameters=m.parameters()), st)
        from paddle_tpu.distributed.fleet.utils import GradientMergeOptimizer
        assert isinstance(o, GradientMergeOptimizer)

    def test_serialization_roundtrips_new_fields(self, tmp_path):
        st = DistributedStrategy()
        st.localsgd = True
        st.fp16_allreduce = True
        st.localsgd_configs = {"k_steps": 7}
        p = str(tmp_path / "s.prototxt")
        st.save_to_prototxt(p)
        st2 = DistributedStrategy()
        st2.load_from_prototxt(p)
        assert st2.localsgd and st2.fp16_allreduce
        assert st2.localsgd_configs["k_steps"] == 7

    def test_localsgd_composes_with_gradient_merge(self):
        # GM wraps outside LocalSGD: averages count real updates, not
        # accumulation micro-steps
        st = DistributedStrategy()
        st.localsgd = True
        st.gradient_merge = True
        st.gradient_merge_configs = {"k_steps": 2, "avg": True}
        fleet.init(is_collective=True, strategy=st)
        m = _model()
        o = fleet.distributed_optimizer(
            opt.SGD(learning_rate=0.1, parameters=m.parameters()), st)
        from paddle_tpu.distributed.fleet.utils import (
            GradientMergeOptimizer, LocalSGDOptimizer)
        assert isinstance(o, GradientMergeOptimizer)
        assert isinstance(o._inner, LocalSGDOptimizer)

    def test_distributed_optimizer_validates_strategy(self):
        fleet.init(is_collective=True)
        st = DistributedStrategy()
        st.dgc = True
        m = _model()
        with pytest.raises(NotImplementedError, match="0002-dgc"):
            fleet.distributed_optimizer(
                opt.SGD(learning_rate=0.1, parameters=m.parameters()), st)

    def test_distributed_model_recompute_idempotent(self):
        st = DistributedStrategy()
        st.recompute = True
        target = [n for n, _ in _model().named_sublayers()][0]
        st.recompute_configs = {"checkpoints": [target]}
        fleet.init(is_collective=True, strategy=st)
        m = _model()
        fleet.distributed_model(m)
        first = dict(m.named_sublayers())[target].forward
        fleet.distributed_model(m)
        assert dict(m.named_sublayers())[target].forward is first
