"""Disaggregated LLM fleet: prefix KV reuse, speculative decoding, and
prefill/decode split routing (docs/serving.md "Disaggregated fleet").

Three invariant families:

* **Prefix store** — chain-hash lookup semantics, pin/unpin lifecycle
  (pinned entries survive LRU pressure; every engine exit path unpins),
  and bitwise-identical greedy output on the reuse path, including
  cross-engine reuse between decoders with different ``max_seq``.
* **Speculative decoding** — greedy output is bitwise-identical to the
  plain engine for ANY draft (self-draft and a genuinely different small
  draft), acceptance counters move, the per-tick host traffic stays at
  exactly ONE fetch, and the compiled spec step never retraces after
  warmup.
* **Router disaggregation** — role-aware dispatch, the prefill->decode
  KV handoff over the shared store, availability fallback when a phase
  loses its replicas, and the slow-lane end-to-end claim: a long-prompt
  storm does not degrade inter-token latency on a decode-role replica
  the way it degrades a single mixed engine.
"""
import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.monitor import StatRegistry
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.serving.llm import (ContinuousBatcher, GenerationRequest,
                                    GPTStaticDecoder, LLMEngine,
                                    LLMEngineConfig, PrefixStore,
                                    SamplingParams, chain_hashes)
from paddle_tpu.serving.llm.spec import get_spec_decode_step
from paddle_tpu.serving.request import (PHASE_DECODE, PHASE_PREFILL,
                                        REPLICA_ROLES, DeadlineExceeded)
from paddle_tpu.serving.router import Router, RouterConfig, llm_replica_factory
from paddle_tpu.utils.resilience import Deadline

import jax

VOCAB = 64


def _tiny_model(seed=0, vocab=VOCAB, hidden=32, layers=2, heads=4,
                max_pos=128):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden, num_layers=layers,
                    num_heads=heads, max_position_embeddings=max_pos,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    net = GPTForCausalLM(cfg)
    net.eval()
    return net


def _prompts():
    """Deterministic prompts straddling the 16-token block boundary:
    two short (never cacheable), three long enough to insert/reuse."""
    rng = np.random.RandomState(7)
    return [rng.randint(0, VOCAB, size=n).astype(np.int32)
            for n in (5, 12, 20, 24, 33)]


PROMPTS = _prompts()
MAX_NEW = 10


def _generate_all(engine, prompts=PROMPTS, max_new=MAX_NEW, **kw):
    reqs = [engine.submit(p, max_new_tokens=max_new, **kw) for p in prompts]
    return [r.result(timeout=60)["tokens"] for r in reqs]


@pytest.fixture(scope="module")
def model():
    return _tiny_model()


@pytest.fixture(scope="module")
def baseline(model):
    """Greedy tokens from the plain engine — the bitwise reference every
    prefix/spec variant must reproduce."""
    eng = LLMEngine(model, LLMEngineConfig(num_slots=4, max_seq=64,
                                           warmup=False))
    try:
        return _generate_all(eng)
    finally:
        eng.drain()


# ---------------------------------------------------------------------------
# prefix store unit behavior
# ---------------------------------------------------------------------------

class TestPrefixStoreUnit:
    SIG = (1, 1, 4, "float32")

    def _kv(self, n):
        k = np.arange(1 * n * 1 * 4, dtype=np.float32).reshape(1, n, 1, 4)
        return k, k + 0.5

    def test_chain_hashes_identify_prefixes(self):
        toks = np.arange(40, dtype=np.int32)
        h = chain_hashes(toks, 16)
        assert len(h) == 2                      # 40 // 16 complete blocks
        # the chain over a shorter prefix of the same tokens is a prefix
        # of the longer chain; a different first block changes every link
        assert chain_hashes(toks[:16], 16) == h[:1]
        other = toks.copy()
        other[0] += 1
        assert chain_hashes(other, 16)[0] != h[0]

    def test_lookup_returns_longest_block_prefix(self):
        store = PrefixStore(registry=StatRegistry(), block_tokens=16)
        toks = np.arange(32, dtype=np.int32)
        k, v = self._kv(32)
        entry = store.insert(toks, k, v, self.SIG)
        store.unpin(entry)
        # a prompt sharing only the first block reuses 16 tokens
        probe = np.concatenate([toks[:16], toks[:4] + 7])
        hit, n = store.lookup(probe, probe.size - 1, self.SIG)
        assert hit is entry and n == 16
        store.unpin(hit)
        # max_tokens caps reuse below the full entry
        hit, n = store.lookup(toks, 20, self.SIG)
        assert hit is entry and n == 16
        store.unpin(hit)
        # a mismatched shape signature never hits
        miss, n = store.lookup(toks, 31, (2, 1, 4, "float32"))
        assert miss is None and n == 0

    def test_insert_dedups_and_pins(self):
        store = PrefixStore(registry=StatRegistry(), block_tokens=16)
        toks = np.arange(16, dtype=np.int32)
        k, v = self._kv(16)
        a = store.insert(toks, k, v, self.SIG)
        b = store.insert(toks, k, v, self.SIG)
        assert a is b
        assert store.stats()["entries"] == 1
        assert store.stats()["pinned"] == 1     # refcounted, not boolean
        store.unpin(a)
        assert store.stats()["pinned"] == 1
        store.unpin(b)
        assert store.stats()["pinned"] == 0

    def test_lru_eviction_skips_pinned(self):
        # capacity fits two 512-byte entries; the OLDEST is pinned, so
        # pressure from a third evicts the unpinned middle one instead
        store = PrefixStore(capacity_bytes=1100, block_tokens=16,
                            registry=StatRegistry())
        rng = np.random.RandomState(3)
        toks = [rng.randint(0, VOCAB, size=16).astype(np.int32)
                for _ in range(3)]
        k, v = self._kv(16)
        pinned = store.insert(toks[0], k, v, self.SIG)   # stays pinned
        mid = store.insert(toks[1], k, v, self.SIG)
        store.unpin(mid)
        third = store.insert(toks[2], k, v, self.SIG)
        store.unpin(third)
        st = store.stats()
        assert st["entries"] == 2 and st["bytes"] <= 1100
        assert store.lookup(toks[0], 16, self.SIG)[1] == 16  # survived
        assert store.lookup(toks[1], 16, self.SIG)[1] == 0   # evicted
        store.unpin(pinned)


# ---------------------------------------------------------------------------
# engine-level prefix reuse
# ---------------------------------------------------------------------------

class TestPrefixReuse:
    def test_reuse_is_bitwise_identical(self, model, baseline):
        reg = StatRegistry()
        eng = LLMEngine(model, LLMEngineConfig(num_slots=4, max_seq=64,
                                               warmup=False,
                                               prefix_cache=True),
                        registry=reg)
        try:
            first = _generate_all(eng)          # misses populate the store
            second = _generate_all(eng)         # block-aligned heads hit
        finally:
            eng.drain()
        assert first == baseline
        assert second == baseline
        # three prompts exceed one block (20/24/33 tokens) -> three hits
        # reusing 16 + 16 + 32 cached tokens on the second pass
        assert reg.get("serving.llm.prefix.hits") >= 3
        assert reg.get("serving.llm.prefix.reused_tokens") >= 48
        assert reg.get("serving.llm.prefix.inserts") >= 3
        assert eng.prefix_store.stats()["pinned"] == 0

    def test_cross_engine_reuse_smaller_max_seq(self, model, baseline):
        """An entry exported by a max_seq=64 engine is reusable by a
        max_seq=32 engine — the shape signature excludes max_seq, and the
        shrink guard keeps offset + tail bucket inside the smaller row."""
        store = PrefixStore(registry=StatRegistry())
        reg_a, reg_b = StatRegistry(), StatRegistry()
        prompt = PROMPTS[3]                     # 24 tokens -> 16 cached
        eng_a = LLMEngine(model, LLMEngineConfig(num_slots=2, max_seq=64,
                                                 warmup=False),
                          registry=reg_a, prefix_store=store)
        try:
            tok_a = eng_a.submit(prompt, max_new_tokens=4).result(60)["tokens"]
        finally:
            eng_a.drain()
        assert store.stats()["entries"] == 1
        eng_b = LLMEngine(model, LLMEngineConfig(num_slots=2, max_seq=32,
                                                 warmup=False),
                          registry=reg_b, prefix_store=store)
        try:
            tok_b = eng_b.submit(prompt, max_new_tokens=4).result(60)["tokens"]
        finally:
            eng_b.drain()
        assert tok_b == tok_a == baseline[3][:4]
        assert reg_b.get("serving.llm.prefix.reused_tokens") == 16
        assert store.stats()["pinned"] == 0

    def test_deadline_eviction_unpins(self, model):
        """Mid-stream deadline eviction releases the request's pin — a
        dead consumer can never wedge an entry against eviction. Driven
        through the batcher directly so tick timing is deterministic."""
        reg = StatRegistry()
        store = PrefixStore(registry=reg)
        cfg = LLMEngineConfig(num_slots=2, max_seq=64, warmup=False)
        batcher = ContinuousBatcher(GPTStaticDecoder(model), cfg, reg,
                                    prefix_store=store)
        prompt = PROMPTS[3]
        seed = GenerationRequest(prompt, SamplingParams(max_new_tokens=2))
        batcher.admit(seed)                     # miss -> insert (pinned)
        while batcher.active:
            batcher.tick()
        assert seed.finish_reason == "length"
        assert store.stats()["pinned"] == 0
        doomed = GenerationRequest(prompt, SamplingParams(max_new_tokens=50),
                                   deadline=Deadline(0.03))
        batcher.admit(doomed)                   # hit -> entry pinned again
        assert store.stats()["pinned"] == 1
        time.sleep(0.05)
        batcher.tick()                          # expired -> evicted
        assert batcher.active == 0
        with pytest.raises(DeadlineExceeded):
            doomed.result(timeout=5)
        assert store.stats()["pinned"] == 0
        assert store.stats()["entries"] == 1    # the ENTRY survives
        assert reg.get("serving.llm.evicted_midstream") == 1


# ---------------------------------------------------------------------------
# speculative decoding
# ---------------------------------------------------------------------------

class TestSpeculativeDecoding:
    def test_self_draft_bitwise_with_full_acceptance(self, model, baseline):
        """Draft == target: every proposal verifies, so greedy output is
        the plain engine's bitwise and the acceptance counters saturate."""
        reg = StatRegistry()
        eng = LLMEngine(model, LLMEngineConfig(num_slots=4, max_seq=64,
                                               warmup=False, spec_k=2),
                        registry=reg, draft_model=model)
        try:
            toks = _generate_all(eng)
        finally:
            eng.drain()
        assert toks == baseline
        assert reg.get("serving.llm.spec.ticks") > 0
        assert reg.get("serving.llm.spec.accepted") > 0
        assert reg.get("serving.llm.spec.acceptance_rate") > 0.5

    def test_distinct_draft_bitwise(self, model, baseline):
        """A genuinely different draft (scaled-down config, different
        seed) may propose garbage — verification still makes the greedy
        stream bitwise-identical to the plain engine."""
        paddle.seed(99)
        draft = GPTForCausalLM(GPTConfig(
            vocab_size=VOCAB, hidden_size=32, num_layers=2, num_heads=4,
            max_position_embeddings=128, hidden_dropout_prob=0.0,
            attention_dropout_prob=0.0).draft(2))
        draft.eval()
        reg = StatRegistry()
        eng = LLMEngine(model, LLMEngineConfig(num_slots=4, max_seq=64,
                                               warmup=False, spec_k=3),
                        registry=reg, draft_model=draft)
        try:
            toks = _generate_all(eng)
        finally:
            eng.drain()
        assert toks == baseline
        assert reg.get("serving.llm.spec.ticks") > 0

    def test_spec_with_prefix_reuse_bitwise(self, model, baseline):
        """Both features on at once: the draft cache prefills the full
        prompt even when the target reuses a cached head, and output
        stays bitwise."""
        reg = StatRegistry()
        eng = LLMEngine(model, LLMEngineConfig(num_slots=4, max_seq=64,
                                               warmup=False, spec_k=2,
                                               prefix_cache=True),
                        registry=reg, draft_model=model)
        try:
            first = _generate_all(eng)
            second = _generate_all(eng)
        finally:
            eng.drain()
        assert first == baseline and second == baseline
        assert reg.get("serving.llm.prefix.hits") >= 3

    def test_one_host_fetch_per_tick(self, model, monkeypatch):
        """THE disaggregation budget: admission fetches one [1]-token
        array, and every tick (speculative or fallback) fetches exactly
        one packed array — no hidden host round-trips."""
        reg = StatRegistry()
        eng = LLMEngine(model, LLMEngineConfig(num_slots=2, max_seq=64,
                                               warmup=True, spec_k=2),
                        registry=reg, draft_model=model)
        fetches = {"n": 0}
        real = jax.device_get

        def counting(x):
            fetches["n"] += 1
            return real(x)

        monkeypatch.setattr(jax, "device_get", counting)
        try:
            req = eng.submit(PROMPTS[2], max_new_tokens=9)
            req.result(timeout=60)
        finally:
            eng.drain()                  # worker joined: counters final
            monkeypatch.setattr(jax, "device_get", real)
        ticks = (reg.get("serving.llm.spec.ticks")
                 + reg.get("serving.llm.spec.fallback_ticks"))
        assert ticks > 0
        assert fetches["n"] == 1 + ticks, \
            f"{fetches['n']} fetches for {ticks} ticks + 1 admission"

    def test_spec_step_never_retraces_after_warmup(self, model):
        eng = LLMEngine(model, LLMEngineConfig(num_slots=2, max_seq=64,
                                               warmup=True, spec_k=2),
                        registry=StatRegistry(), draft_model=model)
        try:
            fn = get_spec_decode_step(eng.decoder.spec,
                                      eng._batcher.spec.dspec, 2,
                                      eng.decoder.max_top_k)
            traced = fn.trace_counter["traces"]
            assert traced >= 1               # warmup compiled it
            _generate_all(eng, prompts=PROMPTS[:3], max_new=6)
            _generate_all(eng, prompts=PROMPTS[2:], max_new=6)
            assert fn.trace_counter["traces"] == traced
        finally:
            eng.drain()

    def test_room_guard_falls_back_near_max_seq(self, model):
        """When a slot cannot absorb k+1 candidate rows the tick drops to
        the plain one-token step — output still bitwise, fallback counted."""
        reg_plain, reg_spec = StatRegistry(), StatRegistry()
        prompt = PROMPTS[2]                    # 20 tokens; budget = 12
        plain = LLMEngine(model, LLMEngineConfig(num_slots=2, max_seq=32,
                                                 warmup=False),
                          registry=reg_plain)
        try:
            want = plain.submit(prompt, max_new_tokens=12).result(60)["tokens"]
        finally:
            plain.drain()
        # k=4: full self-draft acceptance advances 5 tokens/tick
        # (1 -> 6 -> 11), landing where pos + k + 1 > max_seq
        eng = LLMEngine(model, LLMEngineConfig(num_slots=2, max_seq=32,
                                               warmup=False, spec_k=4),
                        registry=reg_spec, draft_model=model)
        try:
            got = eng.submit(prompt, max_new_tokens=12).result(60)["tokens"]
        finally:
            eng.drain()
        assert got == want
        assert reg_spec.get("serving.llm.spec.fallback_ticks") > 0
        assert reg_spec.get("serving.llm.spec.ticks") > 0

    def test_spec_requires_draft_model(self, model):
        with pytest.raises(ValueError, match="draft_model"):
            LLMEngine(model, LLMEngineConfig(num_slots=2, max_seq=32,
                                             warmup=False, spec_k=2))

    def test_audit_entrypoint_registered(self):
        from paddle_tpu.core.audit import load_default_entrypoints
        eps = load_default_entrypoints()
        assert "llm_spec_decode_step" in eps
        from tools.check_audit_regression import ENTRYPOINTS
        assert "llm_spec_decode_step" in ENTRYPOINTS
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(repo, "bench_audit_baseline.json")) as f:
            base = json.load(f)
        assert "llm_spec_decode_step" in base["entrypoints"]


# ---------------------------------------------------------------------------
# router disaggregation
# ---------------------------------------------------------------------------

class TestRouterRoles:
    def test_role_taxonomy(self):
        assert PHASE_PREFILL in REPLICA_ROLES
        assert PHASE_DECODE in REPLICA_ROLES
        assert "mixed" in REPLICA_ROLES

    def test_config_validation(self):
        with pytest.raises(ValueError, match="one role per replica"):
            RouterConfig(kind="llm", num_replicas=2, roles=("prefill",))
        with pytest.raises(ValueError, match="invalid roles"):
            RouterConfig(kind="llm", num_replicas=2,
                         roles=("prefill", "verifier"))
        with pytest.raises(ValueError, match="no replica serving"):
            RouterConfig(kind="llm", num_replicas=2,
                         roles=("prefill", "prefill"))
        with pytest.raises(ValueError, match="kind='llm'"):
            RouterConfig(kind="classifier", num_replicas=2,
                         roles=("prefill", "decode"))
        with pytest.raises(ValueError, match="prefill_threshold"):
            RouterConfig(kind="llm", num_replicas=2,
                         roles=("prefill", "decode"), prefill_threshold=0)


@pytest.fixture(scope="module")
def fleet(model):
    """A 2-replica disaggregated fleet sharing ONE prefix store: replica0
    prefills, replica1 decodes; long prompts hand off through the store."""
    reg = StatRegistry()
    store = PrefixStore(capacity_bytes=64 << 20, registry=reg)
    cfg = LLMEngineConfig(num_slots=2, max_seq=64, warmup=False)
    router = Router(
        llm_replica_factory(lambda r: model, cfg,
                            roles=("prefill", "decode"),
                            prefix_store=store),
        RouterConfig(kind="llm", num_replicas=2,
                     roles=("prefill", "decode"), prefill_threshold=32,
                     health_interval=5.0, auto_resurrect=False),
        registry=reg)
    yield router, reg, store
    router.drain(timeout=30)


class TestDisaggRouting:
    def test_short_prompt_goes_to_decode_replica(self, fleet, baseline):
        router, reg, _ = fleet
        toks = router.submit(PROMPTS[0],
                             max_new_tokens=MAX_NEW).result(60)["tokens"]
        assert toks == baseline[0]
        assert reg.get("serving.router.dispatched_role_decode") >= 1
        assert reg.get("serving.router.dispatched_phase_decode") >= 1

    def test_long_prompt_hands_off_kv(self, fleet, baseline):
        router, reg, store = fleet
        prompt = PROMPTS[4]                    # 33 tokens >= threshold 32
        toks = router.submit(prompt,
                             max_new_tokens=MAX_NEW).result(60)["tokens"]
        assert toks == baseline[4]             # bitwise across the handoff
        assert reg.get("serving.router.handoff_prefills") >= 1
        assert reg.get("serving.router.dispatched_role_prefill") >= 1
        assert reg.get("serving.router.dispatched_phase_prefill") >= 1
        # the decode replica reused the prefill replica's exported head
        assert reg.get("serving.llm.replica1.prefix.reused_tokens") >= 32
        assert store.stats()["entries"] >= 1
        assert store.stats()["pinned"] == 0

    def test_observability_surfaces_roles(self, fleet):
        router, reg, _ = fleet
        assert router.stats()["roles"] == ["prefill", "decode"]
        h = router.healthz()
        roles = {r["role"] for r in h["replicas"]}
        assert roles == {"prefill", "decode"}

    def test_phase_fallback_when_decode_drains(self, fleet, baseline):
        """Availability beats placement: with the decode replica
        draining, short prompts relax onto the prefill replica. Runs
        LAST in this class — it degrades the module fleet."""
        router, reg, _ = fleet
        router.replicas[1].engine.begin_drain()
        toks = router.submit(PROMPTS[1],
                             max_new_tokens=MAX_NEW).result(60)["tokens"]
        assert toks == baseline[1]
        assert reg.get("serving.router.phase_fallback") >= 1

    def test_no_shared_store_disables_handoff(self, model, baseline):
        """Roles without a shared store: long prompts are simply served
        end-to-end on the prefill replica — never a broken handoff."""
        reg = StatRegistry()
        cfg = LLMEngineConfig(num_slots=2, max_seq=64, warmup=False)
        router = Router(
            llm_replica_factory(lambda r: model, cfg,
                                roles=("prefill", "decode")),
            RouterConfig(kind="llm", num_replicas=2,
                         roles=("prefill", "decode"), prefill_threshold=32,
                         health_interval=5.0, auto_resurrect=False),
            registry=reg)
        try:
            toks = router.submit(PROMPTS[4],
                                 max_new_tokens=MAX_NEW).result(60)["tokens"]
        finally:
            router.drain(timeout=30)
        assert toks == baseline[4]
        assert reg.get("serving.router.handoff_prefills") == 0
        assert reg.get("serving.router.dispatched_role_prefill") >= 1


class TestHealthzRole:
    def test_llm_healthz_reports_role(self, model):
        from paddle_tpu.serving.http import make_server
        eng = LLMEngine(model, LLMEngineConfig(num_slots=2, max_seq=32,
                                               warmup=False, role="decode"),
                        registry=StatRegistry())
        httpd = make_server(None, port=0, llm_engine=eng)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            port = httpd.server_address[1]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
                body = json.loads(r.read())
            assert body["status"] == "ok"
            assert body["role"] == "decode"
        finally:
            httpd.shutdown()
            httpd.server_close()
            eng.drain()


# ---------------------------------------------------------------------------
# end-to-end: the disaggregation claim itself
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestDisaggE2E:
    def test_decode_loop_never_pays_full_prefill_under_storm(self):
        """The reason the fleet exists: a long prompt degrades resident
        decode streams only through the stall its admission injects into
        the serving loop. In the mixed engine that stall is a FULL
        256-bucket prefill; on a decode-role replica it is the tail
        prefill behind the handed-off KV head. Same model, same traffic
        (2 resident streams + a storm of 16 unique 200-token prompts),
        both topologies.

        The storm is sequential (one long prompt in flight) and the
        comparison uses per-admission stall medians rather than raw
        inter-token tails: CI may pin this suite to a single core, where
        the replicas timeslice against each other and wall-clock
        inter-token isolation is unmeasurable — the stall each admission
        imposes on its own serving loop is host-independent."""
        model = _tiny_model(seed=3, vocab=128, hidden=256, layers=2,
                            heads=4, max_pos=512)
        rng = np.random.RandomState(11)
        longs = [rng.randint(0, 128, size=200).astype(np.int32)
                 for _ in range(16)]
        short = rng.randint(0, 128, size=6).astype(np.int32)
        cfg = LLMEngineConfig(num_slots=4, max_seq=256, warmup=True)

        def drive(submit):
            residents = [submit(short, max_new_tokens=150)
                         for _ in range(2)]
            for p in longs:
                submit(p, max_new_tokens=4).result(timeout=120)
            for r in residents:
                r.result(timeout=120)

        # -- disaggregated fleet ----------------------------------------
        reg_fleet = StatRegistry()
        store = PrefixStore(capacity_bytes=512 << 20, registry=reg_fleet)
        router = Router(
            llm_replica_factory(lambda r: model, cfg,
                                roles=("prefill", "decode"),
                                prefix_store=store),
            RouterConfig(kind="llm", num_replicas=2,
                         roles=("prefill", "decode"), prefill_threshold=64,
                         health_interval=5.0, auto_resurrect=False),
            registry=reg_fleet)
        try:
            drive(router.submit)
        finally:
            router.drain(timeout=60)
        # every long prompt handed off, and every handoff admission on
        # the decode replica reused the full block-aligned head
        # (200 // 16 * 16 = 192 tokens) — it never ran a full prefill
        assert reg_fleet.get("serving.router.handoff_prefills") == 16
        assert reg_fleet.get(
            "serving.llm.replica1.prefix.reused_tokens") == 16 * 192
        fleet_stall = reg_fleet.quantile("serving.llm.replica1.prefill_ms",
                                         0.5)

        # -- single mixed engine, identical traffic ---------------------
        reg_mixed = StatRegistry()
        eng = LLMEngine(model, cfg, registry=reg_mixed)
        try:
            drive(eng.submit)
        finally:
            eng.drain()
        mixed_stall = reg_mixed.quantile("serving.llm.prefill_ms", 0.5)
        # the mixed loop's admission stall is full-prefill sized, and it
        # DID hit the resident streams' inter-token tail
        assert reg_mixed.quantile("serving.llm.intertoken_ms", 0.95) \
            > mixed_stall * 0.8

        assert fleet_stall > 0 and mixed_stall > 0
        assert fleet_stall < 0.7 * mixed_stall, \
            (f"decode-role admission stall p50 {fleet_stall:.2f}ms should "
             f"be well under the mixed engine's full-prefill stall "
             f"{mixed_stall:.2f}ms")
