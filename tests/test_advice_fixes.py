"""Regression tests for the round-1 advisor findings (ADVICE.md):
1. GradScaler.step must not re-unscale after a manual unscale_().
2. AdamW honors apply_decay_param_fun (excluded params get no decay).
3. batch_norm running_var uses the *biased* batch variance
   (reference: operators/batch_norm_op.cc:397).
4. static-mode train step clips grads first, then L2-regularizes —
   same order as dygraph Optimizer.step.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as optim
from paddle_tpu.amp import GradScaler


class TestGradScalerUnscaleOnce:
    def test_manual_unscale_then_step_divides_once(self):
        p = paddle.Parameter(np.zeros((3,), np.float32))
        opt = optim.SGD(learning_rate=1.0, parameters=[p])
        scaler = GradScaler(init_loss_scaling=1024.0)
        # simulate backward of a scaled loss: grad = scale * true_grad
        true_grad = np.array([1.0, 2.0, 3.0], np.float32)
        p._grad = paddle.to_tensor(true_grad * 1024.0)._data
        scaler.unscale_(opt)
        np.testing.assert_allclose(np.asarray(p._grad), true_grad, rtol=1e-6)
        scaler.step(opt)  # must NOT divide by the scale again
        scaler.update()
        np.testing.assert_allclose(p.numpy(), -true_grad, rtol=1e-5)

    def test_two_optimizers_one_scaler(self):
        pa = paddle.Parameter(np.zeros((2,), np.float32))
        pb = paddle.Parameter(np.zeros((2,), np.float32))
        oa = optim.SGD(learning_rate=1.0, parameters=[pa])
        ob = optim.SGD(learning_rate=1.0, parameters=[pb])
        scaler = GradScaler(init_loss_scaling=4.0)
        pa._grad = paddle.to_tensor(np.array([4.0, 4.0], np.float32))._data
        pb._grad = paddle.to_tensor(np.array([8.0, 8.0], np.float32))._data
        scaler.unscale_(oa)
        scaler.unscale_(ob)
        scaler.step(oa)
        scaler.step(ob)  # must not re-unscale ob's grads
        scaler.update()
        np.testing.assert_allclose(pa.numpy(), [-1.0, -1.0])
        np.testing.assert_allclose(pb.numpy(), [-2.0, -2.0])

    def test_double_unscale_raises(self):
        p = paddle.Parameter(np.zeros((2,), np.float32))
        opt = optim.SGD(learning_rate=1.0, parameters=[p])
        scaler = GradScaler(init_loss_scaling=4.0)
        p._grad = paddle.to_tensor(np.array([4.0, 4.0], np.float32))._data
        scaler.unscale_(opt)
        with pytest.raises(RuntimeError):
            scaler.unscale_(opt)

    def test_step_without_manual_unscale_still_unscales(self):
        p = paddle.Parameter(np.zeros((2,), np.float32))
        opt = optim.SGD(learning_rate=1.0, parameters=[p])
        scaler = GradScaler(init_loss_scaling=8.0)
        p._grad = paddle.to_tensor(np.array([8.0, 16.0], np.float32))._data
        scaler.step(opt)
        scaler.update()
        np.testing.assert_allclose(p.numpy(), [-1.0, -2.0], rtol=1e-6)


class TestAdamWDecayMask:
    def test_apply_decay_param_fun_excludes(self):
        w = paddle.Parameter(np.full((4,), 2.0, np.float32))
        b = paddle.Parameter(np.full((4,), 2.0, np.float32))
        w.name, b.name = "linear_w", "linear_b"
        opt = optim.AdamW(learning_rate=0.1, parameters=[w, b],
                          weight_decay=0.5,
                          apply_decay_param_fun=lambda n: n.endswith("_w"))
        g = np.full((4,), 0.01, np.float32)
        w._grad = paddle.to_tensor(g)._data
        b._grad = paddle.to_tensor(g)._data
        opt.step()
        # identical grads, identical init: only the decayed param shrinks more
        assert float(w.numpy()[0]) < float(b.numpy()[0])
        # the excluded param must match plain Adam exactly
        b2 = paddle.Parameter(np.full((4,), 2.0, np.float32))
        adam = optim.Adam(learning_rate=0.1, parameters=[b2])
        b2._grad = paddle.to_tensor(g)._data
        adam.step()
        np.testing.assert_allclose(b.numpy(), b2.numpy(), rtol=1e-6)

    def test_lr_ratio_scales_update(self):
        p1 = paddle.Parameter(np.full((2,), 1.0, np.float32))
        p2 = paddle.Parameter(np.full((2,), 1.0, np.float32))
        p1.name, p2.name = "a", "b"
        opt = optim.AdamW(learning_rate=0.1, parameters=[p1, p2],
                          weight_decay=0.0,
                          lr_ratio=lambda p: 0.5 if p.name == "b" else 1.0)
        g = np.full((2,), 1.0, np.float32)
        p1._grad = paddle.to_tensor(g)._data
        p2._grad = paddle.to_tensor(g)._data
        opt.step()
        d1 = 1.0 - float(p1.numpy()[0])
        d2 = 1.0 - float(p2.numpy()[0])
        np.testing.assert_allclose(d2, d1 * 0.5, rtol=1e-5)

    def test_non_float_weight_decay_raises(self):
        p = paddle.Parameter(np.zeros((2,), np.float32))
        with pytest.raises(TypeError):
            optim.AdamW(parameters=[p], weight_decay="0.01")


class TestBatchNormRunningVarBiased:
    def test_running_var_uses_biased_batch_var(self):
        bn = nn.BatchNorm1D(3, momentum=0.9)
        x = np.random.RandomState(0).randn(8, 3).astype(np.float32)
        bn.train()
        bn(paddle.to_tensor(x))
        biased_var = x.var(axis=0)  # ddof=0
        expected = 0.9 * np.ones(3, np.float32) + 0.1 * biased_var
        np.testing.assert_allclose(bn._variance.numpy(), expected,
                                   rtol=1e-5, atol=1e-6)


class TestStaticClipOrderParity:
    def test_static_matches_dygraph_with_clip_and_decay(self):
        rng = np.random.RandomState(1)
        W0 = rng.randn(4, 2).astype(np.float32)
        b0 = np.zeros(2, np.float32)
        X = rng.randn(16, 4).astype(np.float32)
        Y = rng.randn(16, 2).astype(np.float32)

        def make_opt(params):
            return optim.Momentum(
                learning_rate=0.1, momentum=0.9, parameters=params,
                weight_decay=0.1,
                grad_clip=paddle.ClipGradByGlobalNorm(0.05))

        # dygraph
        lin_d = nn.Linear(4, 2)
        lin_d.weight.set_value(W0)
        lin_d.bias.set_value(b0)
        opt_d = make_opt(lin_d.parameters())
        for _ in range(3):
            loss = paddle.mean((lin_d(paddle.to_tensor(X))
                                - paddle.to_tensor(Y)) ** 2)
            loss.backward()
            opt_d.step()
            opt_d.clear_grad()

        # static
        paddle.enable_static()
        try:
            main = paddle.static.Program()
            startup = paddle.static.Program()
            with paddle.static.program_guard(main, startup):
                x = paddle.static.data("x", [None, 4], "float32")
                y = paddle.static.data("y", [None, 2], "float32")
                lin_s = nn.Linear(4, 2)
                loss = paddle.mean((lin_s(x) - y) ** 2)
                opt_s = make_opt([])
                opt_s._parameter_list = lin_s.parameters()
                opt_s.minimize(loss)
            exe = paddle.static.Executor()
            exe.run(startup)
            lin_s.weight.set_value(W0)
            lin_s.bias.set_value(b0)
            for _ in range(3):
                exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        finally:
            paddle.disable_static()

        np.testing.assert_allclose(lin_s.weight.numpy(), lin_d.weight.numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(lin_s.bias.numpy(), lin_d.bias.numpy(),
                                   rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# Round-2 advisor findings (ADVICE.md round 2):
# 5. send/recv must not silently route via rank 0 — explicit endpoints only.
# 6. scatter over an arbitrary-rank group indexes by *group* rank and leaves
#    non-members untouched.
# 7. HybridCommunicateGroup raises on degree/device-count mismatch.
# 8. The eager op cache keys default-bound lambda args.
# 9. multiclass_nms honors normalized=False (+1 extent) and nms_eta decay.
# ---------------------------------------------------------------------------
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu.distributed as dist
import paddle_tpu.ops as ops


class TestCollectiveRouting:
    def _spmd(self, fn, in_specs, out_specs):
        return jax.shard_map(fn, mesh=dist.get_mesh(),
                             in_specs=in_specs, out_specs=out_specs)

    def test_send_recv_require_explicit_endpoints(self):
        dist.set_mesh(dist.build_mesh({"dp": 8}))
        try:
            x = jnp.arange(8.0, dtype=jnp.float32)
            with pytest.raises(NotImplementedError):
                self._spmd(lambda v: dist.send(v, dst=3), P("dp"), P("dp"))(x)
            with pytest.raises(NotImplementedError):
                self._spmd(lambda v: dist.recv(v, src=3), P("dp"), P("dp"))(x)
            # explicit endpoints route correctly (not via rank 0)
            out = self._spmd(lambda v: dist.send(v, dst=5, src=2),
                             P("dp"), P("dp"))(x)
            expected = np.arange(8.0, dtype=np.float32)
            expected[5] = 2.0
            np.testing.assert_allclose(np.asarray(out), expected)
        finally:
            dist.set_mesh(None)

    def test_scatter_subgroup_group_rank_and_mask(self):
        dist.set_mesh(dist.build_mesh({"dp": 8}))
        try:
            g = dist.new_group(ranks=[2, 3])
            parts = [jnp.full((2,), 100.0, jnp.float32),
                     jnp.full((2,), 200.0, jnp.float32)]
            x = np.tile(np.arange(8.0, dtype=np.float32)[:, None], (1, 2))

            def fn(v):
                return dist.scatter(v[0], tensor_list=parts, src=2, group=g)[None]
            out = np.asarray(self._spmd(fn, P("dp", None), P("dp", None))(
                jnp.asarray(x)))
            expected = x.copy()
            expected[2] = 100.0  # group rank 0
            expected[3] = 200.0  # group rank 1
            np.testing.assert_allclose(out, expected)
        finally:
            dist.set_mesh(None)

    def test_scatter_full_mesh(self):
        dist.set_mesh(dist.build_mesh({"dp": 8}))
        try:
            parts = [jnp.full((2,), 10.0 * r, jnp.float32) for r in range(8)]
            x = np.zeros((8, 2), np.float32)

            def fn(v):
                return dist.scatter(v[0], tensor_list=parts, src=0)[None]
            out = np.asarray(self._spmd(fn, P("dp", None), P("dp", None))(
                jnp.asarray(x)))
            np.testing.assert_allclose(
                out, np.arange(8.0)[:, None] * 10.0 * np.ones((1, 2)))
        finally:
            dist.set_mesh(None)


class TestTopologyMismatchRaises:
    def test_degree_device_mismatch(self):
        from paddle_tpu.distributed.topology import HybridCommunicateGroup
        with pytest.raises(ValueError):
            HybridCommunicateGroup(dp_degree=3, mp_degree=2)  # 6 != 8 devices


class TestEagerCacheDefaults:
    def test_default_bound_lambda_values_keyed(self):
        from paddle_tpu.ops.dispatch import apply

        def make(c):
            return lambda x, c=c: x * c

        t = paddle.to_tensor(np.ones(2, np.float32))
        r1 = apply("_test_mul_const", make(2.0), t)
        r2 = apply("_test_mul_const", make(3.0), t)
        np.testing.assert_allclose(r1.numpy(), [2.0, 2.0])
        np.testing.assert_allclose(r2.numpy(), [3.0, 3.0])


class TestNMSNormalizedEta:
    def test_unnormalized_plus_one_extent(self):
        # pixel boxes touching at a corner: iou = 0 normalized, 1/7 with +1
        bboxes = np.array([[[0, 0, 1, 1], [1, 1, 2, 2]]], np.float32)
        scores = np.zeros((1, 2, 2), np.float32)
        scores[0, 1] = [0.9, 0.8]
        kw = dict(score_threshold=0.1, nms_top_k=2, keep_top_k=2,
                  nms_threshold=0.1, background_label=0)
        _, counts_norm = ops.multiclass_nms(
            paddle.to_tensor(bboxes), paddle.to_tensor(scores),
            normalized=True, **kw)
        _, counts_pix = ops.multiclass_nms(
            paddle.to_tensor(bboxes), paddle.to_tensor(scores),
            normalized=False, **kw)
        assert int(counts_norm.numpy()[0]) == 2   # iou 0 < 0.1: both kept
        assert int(counts_pix.numpy()[0]) == 1    # iou 1/7 > 0.1: suppressed

    def test_nms_eta_decays_threshold(self):
        # iou(A,B) ~ 0.65 < 0.7: B survives at eta=1; after keeping A with
        # eta=0.5 the threshold drops to 0.35 and B is suppressed
        bboxes = np.array([[[0.0, 0.0, 1.0, 1.0],
                            [0.2121, 0.0, 1.2121, 1.0]]], np.float32)
        scores = np.zeros((1, 2, 2), np.float32)
        scores[0, 1] = [0.9, 0.8]
        kw = dict(score_threshold=0.1, nms_top_k=2, keep_top_k=2,
                  nms_threshold=0.7, background_label=0)
        _, c_plain = ops.multiclass_nms(
            paddle.to_tensor(bboxes), paddle.to_tensor(scores),
            nms_eta=1.0, **kw)
        _, c_eta = ops.multiclass_nms(
            paddle.to_tensor(bboxes), paddle.to_tensor(scores),
            nms_eta=0.5, **kw)
        assert int(c_plain.numpy()[0]) == 2
        assert int(c_eta.numpy()[0]) == 1

    def test_iou_similarity_unnormalized(self):
        a = np.array([[0, 0, 1, 1]], np.float32)
        b = np.array([[1, 1, 2, 2]], np.float32)
        got = ops.iou_similarity(paddle.to_tensor(a), paddle.to_tensor(b),
                                 box_normalized=False).numpy()
        np.testing.assert_allclose(got, [[1.0 / 7.0]], rtol=1e-6)


class TestTensorTo:
    """Round-3 (VERDICT weak #9): Tensor.to must really cast dtypes."""

    def test_to_dtype_casts(self):
        t = paddle.to_tensor(np.ones(3, np.float32))
        assert t.to("bfloat16").dtype == paddle.bfloat16 if hasattr(
            paddle, "bfloat16") else str(t.to("bfloat16")._data.dtype) == "bfloat16"
        assert str(t.to("int32")._data.dtype) == "int32"

    def test_to_device_identity(self):
        t = paddle.to_tensor(np.ones(3, np.float32))
        out = t.to("cpu")
        np.testing.assert_allclose(out.numpy(), t.numpy())
        assert str(out._data.dtype) == "float32"


class TestMoeBf16SlotCounting:
    """Round-3 advisor (medium): capacity-slot positions must be counted
    in int32 — a bf16 cumsum can't represent integers past 256, so >256
    local tokens routed to one expert silently collided into the same
    slot."""

    @pytest.mark.slow
    def test_bf16_over_256_tokens_no_collision(self):
        import jax.numpy as jnp
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.fleet import moe_ffn
        dist.set_mesh(dist.build_mesh({"ep": 8}))
        try:
            rng = np.random.RandomState(0)
            D, F, E, T = 16, 32, 8, 320  # 320 local tokens > 256
            # positive inputs so the linear gate really sends EVERY token
            # to expert 0 (zero-mean inputs would flip sign per token)
            x = (np.abs(rng.randn(8, T, D)) + 0.1).astype(np.float32)
            wg = np.zeros((D, E), np.float32)
            wg[:, 0] = 100.0 / D          # every token -> expert 0
            w1 = rng.randn(E, D, F).astype(np.float32) * 0.1
            w2 = rng.randn(E, F, D).astype(np.float32) * 0.1
            out, _ = moe_ffn(jnp.asarray(x, jnp.bfloat16),
                             jnp.asarray(wg, jnp.bfloat16),
                             jnp.asarray(w1, jnp.bfloat16),
                             jnp.asarray(w2, jnp.bfloat16),
                             capacity_factor=float(E))  # capacity = T
            got = np.asarray(out, np.float32).reshape(-1, D)
            # dense reference (all tokens through expert 0, gate prob 1)
            xt = x.reshape(-1, D)
            h = xt @ w1[0]
            h = 0.5 * h * (1 + np.tanh(np.sqrt(2 / np.pi)
                                       * (h + 0.044715 * h ** 3)))
            ref = h @ w2[0]
            # bf16 tolerance; slot collisions would give O(1) errors on
            # most rows (summed/zeroed tokens), not 1e-1 rounding
            err = np.abs(got - ref).max()
            assert err < 0.15, err
            # and no dropped (all-zero) rows at full capacity
            assert (np.abs(got).sum(-1) < 1e-6).sum() == 0
        finally:
            dist.set_mesh(None)


class TestRound5NceLogUniformRange:
    """nce_op.h constructs LogUniformSampler(num_total_classes - 1):
    probabilities normalised by log(C) with support [0, C-2] — not the
    sample_logits sampler's LogUniformSampler(C) (round-5 advisor
    finding, nn/functional/sampled.py)."""

    def test_prob_normalisation_and_support(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.nn.functional.sampled import (
            _log_uniform_prob, _sample_classes)
        C = 50
        # NCE sampler: probs over [0, C-2] sum to 1 under log(C) norm
        p_nce = np.asarray(_log_uniform_prob(jnp.arange(C - 1), C - 1))
        np.testing.assert_allclose(p_nce.sum(), 1.0, rtol=1e-6)
        # sample_logits sampler keeps the full [0, C-1] support
        p_sl = np.asarray(_log_uniform_prob(jnp.arange(C), C))
        np.testing.assert_allclose(p_sl.sum(), 1.0, rtol=1e-6)
        # and the two disagree (the old code used C for both)
        assert abs(p_nce[0] - p_sl[0]) > 1e-4
        # sampled negatives for NCE never include class C-1
        key = jax.random.PRNGKey(0)
        s, p = _sample_classes(key, (512,), C, "log_uniform",
                               range_max=C - 1)
        assert int(np.max(np.asarray(s))) <= C - 2
        np.testing.assert_allclose(
            np.asarray(p),
            np.asarray(_log_uniform_prob(s, C - 1)), rtol=1e-6)

    def test_nce_runs_and_matches_numpy_prob(self):
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F
        rng = np.random.RandomState(0)
        N, D, C = 6, 8, 20
        x = rng.randn(N, D).astype(np.float32)
        lab = rng.randint(0, C, (N, 1)).astype(np.int64)
        w = rng.randn(C, D).astype(np.float32)
        out = F.nce(paddle.to_tensor(x), paddle.to_tensor(lab),
                    paddle.to_tensor(w), num_total_classes=C,
                    sampler="log_uniform", seed=7)
        assert out.shape == [N, 1]
        assert np.isfinite(out.numpy()).all()
