"""Numpy-reference tests for the round-4 op batch: segment reductions,
hierarchical sigmoid, NCE, class_center_sample, sample_logits/sampling_id,
and the position-sensitive ROI pooling family.

Reference semantics being pinned: segment_pool_op.cc:22,
hierarchical_sigmoid_op.cc + math/matrix_bit_code.h SimpleCode,
nce_op.h:80, class_center_sample_op.cu, sample_logits_op.cc,
psroi_pool_op.cc:79, prroi_pool_op.cc, deformable_psroi_pooling_op.cc.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
import paddle_tpu.incubate as incubate

from op_test import check_grad


# -- segment reductions -------------------------------------------------------

def _np_segment(data, ids, n, kind):
    out = np.zeros((n,) + data.shape[1:], data.dtype)
    for s in range(n):
        rows = data[ids == s]
        if rows.size == 0:
            continue
        if kind == "sum":
            out[s] = rows.sum(0)
        elif kind == "mean":
            out[s] = rows.mean(0)
        elif kind == "max":
            out[s] = rows.max(0)
        elif kind == "min":
            out[s] = rows.min(0)
    return out


@pytest.mark.parametrize("kind", ["sum", "mean", "max", "min"])
def test_segment_ops_numpy(kind):
    rng = np.random.RandomState(0)
    data = rng.randn(10, 3).astype(np.float32)
    ids = np.array([0, 0, 1, 1, 1, 3, 3, 5, 5, 5], np.int32)  # 2,4 empty
    fn = getattr(incubate, f"segment_{kind}")
    got = fn(paddle.to_tensor(data), paddle.to_tensor(ids)).numpy()
    np.testing.assert_allclose(got, _np_segment(data, ids, 6, kind),
                               rtol=1e-5, atol=1e-5)


def test_segment_pool_dispatch_and_grad():
    rng = np.random.RandomState(1)
    data = rng.randn(6, 2).astype(np.float32)
    ids = np.array([0, 0, 1, 2, 2, 2], np.int32)
    got = paddle.ops.segment_pool(paddle.to_tensor(data),
                                  paddle.to_tensor(ids), "MEAN").numpy()
    np.testing.assert_allclose(got, _np_segment(data, ids, 3, "mean"),
                               rtol=1e-5)
    check_grad(lambda d: incubate.segment_sum(d, paddle.to_tensor(ids)),
               [data])


def test_segment_requires_static_num_segments_under_jit():
    ids = paddle.to_tensor(np.array([0, 1], np.int32))
    data = paddle.to_tensor(np.ones((2, 2), np.float32))

    @paddle.jit.to_static
    def f(d, i):
        return incubate.segment_sum(d, i)
    with pytest.raises(ValueError, match="num_segments"):
        f(data, ids)


# -- hierarchical sigmoid -----------------------------------------------------

def _np_hsigmoid(x, label, C, W, b):
    """Literal SimpleCode walk (matrix_bit_code.h:106)."""
    N = x.shape[0]
    out = np.zeros((N, 1), np.float64)
    for n in range(N):
        code = int(label[n]) + C
        length = code.bit_length() - 1
        for bit in range(length):
            idx = (code >> (bit + 1)) - 1
            t = float((code >> bit) & 1)
            pre = float(x[n] @ W[idx] + b[idx])
            pre = np.clip(pre, -40, 40)
            out[n, 0] += np.log1p(np.exp(pre)) - t * pre
    return out


@pytest.mark.parametrize("C", [2, 7, 10, 16])
def test_hsigmoid_loss_numpy(C):
    rng = np.random.RandomState(2)
    x = rng.randn(5, 6).astype(np.float32)
    lab = rng.randint(0, C, (5,)).astype(np.int64)
    W = rng.randn(C - 1, 6).astype(np.float32)
    b = rng.randn(C - 1).astype(np.float32)
    got = F.hsigmoid_loss(paddle.to_tensor(x), paddle.to_tensor(lab), C,
                          paddle.to_tensor(W), paddle.to_tensor(b)).numpy()
    np.testing.assert_allclose(got, _np_hsigmoid(x, lab, C, W, b),
                               rtol=1e-4, atol=1e-4)


def test_hsigmoid_custom_path():
    rng = np.random.RandomState(3)
    x = rng.randn(3, 4).astype(np.float32)
    lab = np.array([0, 1, 2], np.int64)
    W = rng.randn(5, 4).astype(np.float32)
    # custom tree: each row's path, -1 padded
    pt = np.array([[0, 2, -1], [0, 3, 4], [1, -1, -1]], np.int64)
    pc = np.array([[1, 0, 0], [0, 1, 1], [1, 0, 0]], np.float32)
    got = F.hsigmoid_loss(paddle.to_tensor(x), paddle.to_tensor(lab), 6,
                          paddle.to_tensor(W), path_table=paddle.to_tensor(pt),
                          path_code=paddle.to_tensor(pc)).numpy()
    exp = np.zeros((3, 1))
    for n in range(3):
        for l in range(3):
            if pt[n, l] < 0:
                continue
            pre = np.clip(float(x[n] @ W[pt[n, l]]), -40, 40)
            exp[n, 0] += np.log1p(np.exp(pre)) - pc[n, l] * pre
    np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-4)


def test_hsigmoid_grad():
    rng = np.random.RandomState(4)
    x = rng.randn(3, 5).astype(np.float32)
    lab = paddle.to_tensor(np.array([1, 3, 0], np.int64))
    W = rng.randn(7, 5).astype(np.float32)
    check_grad(lambda xx, ww: F.hsigmoid_loss(xx, lab, 8, ww), [x, W])


# -- NCE ----------------------------------------------------------------------

def test_nce_numpy_uniform():
    """Recompute the reference cost formula (nce_op.h:196-206) in numpy on
    the same sampled negatives the op draws from its seeded key."""
    import jax
    rng = np.random.RandomState(5)
    N, D, C, k = 4, 6, 9, 5
    x = rng.randn(N, D).astype(np.float32)
    lab = rng.randint(0, C, (N, 1)).astype(np.int64)
    W = rng.randn(C, D).astype(np.float32)
    b = rng.randn(C).astype(np.float32)
    seed = 77
    got = F.nce(paddle.to_tensor(x), paddle.to_tensor(lab),
                paddle.to_tensor(W), bias=paddle.to_tensor(b),
                num_neg_samples=k, num_total_classes=C, sampler="uniform",
                seed=seed).numpy()
    neg = np.asarray(jax.random.randint(jax.random.PRNGKey(seed), (N, k), 0, C))
    exp = np.zeros((N, 1))
    for i in range(N):
        classes = np.concatenate([lab[i], neg[i]])
        for j, c in enumerate(classes):
            o = 1.0 / (1.0 + np.exp(-(x[i] @ W[c] + b[c])))
            bq = (1.0 / C) * k
            exp[i, 0] += (-np.log(o / (o + bq)) if j < 1
                          else -np.log(bq / (o + bq)))
    np.testing.assert_allclose(got, exp, rtol=1e-3, atol=1e-3)


def test_nce_samplers_and_grad():
    rng = np.random.RandomState(6)
    x = rng.randn(3, 4).astype(np.float32)
    lab = paddle.to_tensor(np.array([[0], [2], [4]], np.int64))
    W = rng.randn(6, 4).astype(np.float32)
    for sampler, kw in [("log_uniform", {}),
                        ("custom_dist", {"custom_dist": np.full(6, 1 / 6)})]:
        out = F.nce(paddle.to_tensor(x), lab, paddle.to_tensor(W),
                    num_neg_samples=3, num_total_classes=6, sampler=sampler,
                    seed=9, **kw)
        assert out.shape == [3, 1]
        assert np.all(np.isfinite(out.numpy()))
    check_grad(lambda xx: F.nce(xx, lab, paddle.to_tensor(W),
                                num_neg_samples=3, num_total_classes=6,
                                seed=9), [x])


# -- class_center_sample ------------------------------------------------------

def test_class_center_sample_contract():
    paddle.seed(11)
    lab = np.array([3, 17, 3, 9, 40, 9], np.int64)
    rl, centers = F.class_center_sample(paddle.to_tensor(lab), 50, 8)
    centers = centers.numpy()
    rl = rl.numpy()
    assert centers.shape == (8,)
    # every positive class is sampled, list is sorted unique
    for c in {3, 17, 9, 40}:
        assert c in centers
    assert np.all(np.diff(centers) > 0)
    # remapped labels point at the right centers
    np.testing.assert_array_equal(centers[rl], lab)


def test_class_center_sample_validates():
    with pytest.raises(ValueError):
        F.class_center_sample(paddle.to_tensor(np.zeros(2, np.int64)), 4, 9)


# -- sampling_id / sample_logits ---------------------------------------------

def test_sampling_id():
    p = np.zeros((3, 5), np.float32)
    p[0, 2] = p[1, 0] = p[2, 4] = 1.0  # deterministic rows
    out = F.sampling_id(paddle.to_tensor(p), seed=3).numpy()
    np.testing.assert_array_equal(out, [2, 0, 4])


def test_sample_logits_subtract_log_q_and_hits():
    import jax
    rng = np.random.RandomState(7)
    N, C, S = 3, 20, 6
    logits = rng.randn(N, C).astype(np.float32)
    lab = rng.randint(0, C, (N, 1)).astype(np.int64)
    seed = 13
    s_logits, s_label = F.sample_logits(paddle.to_tensor(logits),
                                        paddle.to_tensor(lab), S, uniq=False,
                                        seed=seed)
    s_logits = s_logits.numpy()
    assert s_logits.shape == (N, 1 + S)
    np.testing.assert_array_equal(s_label.numpy(),
                                  np.zeros((N, 1), np.int64))
    # column 0 is the true logit minus log q(true)
    u = np.asarray(jax.random.uniform(jax.random.PRNGKey(seed), (N, S)))
    q_true = np.log((lab[:, 0] + 2.0) / (lab[:, 0] + 1.0)) / np.log(C + 1.0)
    np.testing.assert_allclose(
        s_logits[:, 0], logits[np.arange(N), lab[:, 0]] - np.log(q_true),
        rtol=1e-4)


# -- position-sensitive ROI pooling -------------------------------------------

def _np_psroi(feat, rois, bidx, oc, scale, ph, pw):
    N, C, H, W = feat.shape
    R = rois.shape[0]
    out = np.zeros((R, oc, ph, pw), np.float32)
    for r in range(R):
        x1 = round(rois[r, 0]) * scale
        y1 = round(rois[r, 1]) * scale
        x2 = round(rois[r, 2] + 1) * scale
        y2 = round(rois[r, 3] + 1) * scale
        rh = max(y2 - y1, 0.1)
        rw = max(x2 - x1, 0.1)
        bh, bw = rh / ph, rw / pw
        for c in range(oc):
            for i in range(ph):
                for j in range(pw):
                    hs = int(np.clip(np.floor(i * bh + y1), 0, H))
                    he = int(np.clip(np.ceil((i + 1) * bh + y1), 0, H))
                    ws = int(np.clip(np.floor(j * bw + x1), 0, W))
                    we = int(np.clip(np.ceil((j + 1) * bw + x1), 0, W))
                    cin = (c * ph + i) * pw + j
                    region = feat[bidx[r], cin, hs:he, ws:we]
                    if region.size:
                        out[r, c, i, j] = region.sum() / region.size
    return out


def test_psroi_pool_numpy():
    rng = np.random.RandomState(8)
    oc, ph, pw = 3, 2, 2
    feat = rng.randn(2, oc * ph * pw, 10, 10).astype(np.float32)
    rois = np.array([[0, 0, 9, 9], [2, 3, 8, 7], [1, 1, 4, 4]], np.float32)
    rois_num = np.array([2, 1], np.int32)
    bidx = np.array([0, 0, 1])
    got = paddle.ops.psroi_pool(paddle.to_tensor(feat),
                                paddle.to_tensor(rois), oc, 0.5, ph, pw,
                                rois_num=paddle.to_tensor(rois_num)).numpy()
    np.testing.assert_allclose(got, _np_psroi(feat, rois, bidx, oc, 0.5,
                                              ph, pw), rtol=1e-4, atol=1e-4)


def test_prroi_pool_matches_dense_integration():
    """PrRoI = exact integral of the bilinear surface; check against a fine
    Riemann sum of numpy bilinear interpolation."""
    rng = np.random.RandomState(9)
    feat = rng.randn(1, 2, 8, 8).astype(np.float32)
    rois = np.array([[1.2, 0.7, 6.3, 5.9]], np.float32)
    ph = pw = 2
    got = paddle.ops.prroi_pool(paddle.to_tensor(feat),
                                paddle.to_tensor(rois), ph, pw, 1.0).numpy()

    def bilin(c, y, x):
        y0, x0 = int(np.floor(y)), int(np.floor(x))
        y0 = np.clip(y0, 0, 7); x0 = np.clip(x0, 0, 7)
        y1, x1 = min(y0 + 1, 7), min(x0 + 1, 7)
        ay, ax = y - y0, x - x0
        f = feat[0, c]
        v = (f[y0, x0] * (1 - ay) * (1 - ax) + f[y0, x1] * (1 - ay) * ax
             + f[y1, x0] * ay * (1 - ax) + f[y1, x1] * ay * ax)
        # outside [0, H-1] the triangle kernel decays to 0 over 1px
        if y < 0 or y > 7:
            v *= max(0.0, 1 - min(abs(y - 0), abs(y - 7)))
        return v

    x1, y1, x2, y2 = rois[0]
    bh, bw = (y2 - y1) / ph, (x2 - x1) / pw
    K = 30
    exp = np.zeros((1, 2, ph, pw), np.float32)
    for c in range(2):
        for i in range(ph):
            for j in range(pw):
                ys = y1 + i * bh + (np.arange(K) + 0.5) / K * bh
                xs = x1 + j * bw + (np.arange(K) + 0.5) / K * bw
                acc = 0.0
                for y in ys:
                    for x in xs:
                        acc += bilin(c, y, x)
                exp[0, c, i, j] = acc / (K * K)
    np.testing.assert_allclose(got, exp, rtol=2e-2, atol=2e-2)


def test_deformable_psroi_zero_trans_and_shift():
    rng = np.random.RandomState(10)
    gs = 2
    oc = 2
    feat = rng.randn(1, oc * gs * gs, 12, 12).astype(np.float32)
    rois = np.array([[1, 1, 9, 9]], np.float32)
    zero_tr = np.zeros((1, 2, 2, 2), np.float32)
    a = paddle.ops.deformable_psroi_pooling(
        paddle.to_tensor(feat), paddle.to_tensor(rois),
        paddle.to_tensor(zero_tr), no_trans=False, spatial_scale=1.0,
        group_size=gs, pooled_height=2, pooled_width=2, part_size=2,
        sample_per_part=2).numpy()
    b = paddle.ops.deformable_psroi_pooling(
        paddle.to_tensor(feat), paddle.to_tensor(rois), None, no_trans=True,
        spatial_scale=1.0, group_size=gs, pooled_height=2, pooled_width=2,
        part_size=2, sample_per_part=2).numpy()
    np.testing.assert_allclose(a, b, rtol=1e-5)
    # a constant shift moves the sampled region
    tr = np.full((1, 2, 2, 2), 0.25, np.float32)
    c = paddle.ops.deformable_psroi_pooling(
        paddle.to_tensor(feat), paddle.to_tensor(rois), paddle.to_tensor(tr),
        no_trans=False, spatial_scale=1.0, group_size=gs, pooled_height=2,
        pooled_width=2, part_size=2, sample_per_part=2).numpy()
    assert not np.allclose(a, c)


def test_class_center_sample_rejects_too_many_positives():
    lab = np.arange(10, dtype=np.int64)     # 10 distinct positives
    with pytest.raises(ValueError, match="distinct positive"):
        F.class_center_sample(paddle.to_tensor(lab), 50, 8)


def test_sample_logits_uniq_draws_distinct_negatives():
    rng = np.random.RandomState(18)
    N, C, S = 4, 12, 10
    logits = rng.randn(N, C).astype(np.float32)
    lab = rng.randint(0, C, (N, 1)).astype(np.int64)
    s_logits, _ = F.sample_logits(paddle.to_tensor(logits),
                                  paddle.to_tensor(lab), S, uniq=True,
                                  remove_accidental_hits=False, seed=4)
    assert s_logits.shape == [N, 1 + S]
    # with replacement, 10 draws from 12 classes would collide w.h.p.;
    # uniq must not: recover the sampled classes from the adjusted logits
    import jax, jax.numpy as jnp
    logp = np.log(np.log((np.arange(C) + 2) / (np.arange(C) + 1))
                  / np.log(C + 1))
    g = np.asarray(jax.random.gumbel(jax.random.PRNGKey(4), (N, C)))
    neg = np.argsort(-(logp[None] + g), axis=1)[:, :S]
    for row in neg:
        assert len(set(row.tolist())) == S
