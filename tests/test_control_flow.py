"""Control flow tests across all three modes (reference analogs:
unittests/test_cond.py, test_while_loop_op.py, test_case.py,
test_switch_case.py; dygraph_to_static/test_ifelse.py, test_loop.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as optim
from paddle_tpu import ops
from paddle_tpu.jit import to_static, InputSpec


class TestEagerCond:
    def test_concrete_pred_dispatch(self):
        x = paddle.to_tensor(np.array([2.0], np.float32))
        out = ops.cond(paddle.mean(x) > 1.0, lambda: x * 2, lambda: x - 1)
        np.testing.assert_allclose(out.numpy(), [4.0])
        out = ops.cond(paddle.mean(x) > 3.0, lambda: x * 2, lambda: x - 1)
        np.testing.assert_allclose(out.numpy(), [1.0])

    def test_grad_through_taken_branch(self):
        x = paddle.to_tensor(np.array([3.0], np.float32), stop_gradient=False)
        out = ops.cond(x.sum() > 0, lambda: x * x, lambda: x)
        out.backward()
        np.testing.assert_allclose(x.grad.numpy(), [6.0])

    def test_case_and_switch_case(self):
        x = paddle.to_tensor(np.float32(5.0))
        out = ops.case([(x > 10.0, lambda: x * 0),
                        (x > 3.0, lambda: x * 2)],
                       default=lambda: x)
        assert float(out.numpy()) == 10.0
        idx = paddle.to_tensor(np.int32(1))
        out = ops.switch_case(idx, {0: lambda: x + 1, 1: lambda: x + 2},
                              default=lambda: x)
        assert float(out.numpy()) == 7.0


class TestEagerWhile:
    def test_while_accumulate(self):
        i = paddle.to_tensor(np.float32(0.0))
        s = paddle.to_tensor(np.float32(0.0))
        i2, s2 = ops.while_loop(lambda i, s: i < 5.0,
                                lambda i, s: [i + 1.0, s + i],
                                [i, s])
        assert float(i2.numpy()) == 5.0
        assert float(s2.numpy()) == 10.0  # 0+1+2+3+4

    def test_while_grad_through_tape(self):
        w = paddle.to_tensor(np.float32(1.5), stop_gradient=False)
        x = paddle.to_tensor(np.float32(1.0))
        cnt = paddle.to_tensor(np.float32(0.0))

        def body(c, v):
            return [c + 1.0, v * w]

        _, y = ops.while_loop(lambda c, v: c < 3.0, body, [cnt, x])
        y.backward()  # y = w^3, dy/dw = 3 w^2
        np.testing.assert_allclose(float(w.grad.numpy()), 3 * 1.5 ** 2,
                                   rtol=1e-5)


class TestToStaticControlFlow:
    def test_cond_in_to_static(self):
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            def forward(self, x):
                h = self.fc(x)
                return ops.cond(paddle.mean(h) > 0,
                                lambda: h * 2.0, lambda: -h)

        net = Net()
        st = to_static(Net())
        st.set_state_dict(net.state_dict())
        for scale in (3.0, -3.0):
            x = paddle.to_tensor(
                np.full((2, 4), scale, np.float32))
            eager = net(x).numpy()
            static = st(x).numpy()
            np.testing.assert_allclose(static, eager, atol=1e-5)

    def test_while_in_to_static(self):
        def fn(x):
            i = paddle.to_tensor(np.float32(0.0))

            def body(i, v):
                return [i + 1.0, v * 2.0]
            _, out = ops.while_loop(lambda i, v: i < 4.0, body, [i, x])
            return out

        st = to_static(fn)
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        np.testing.assert_allclose(st(x).numpy(), [16.0, 32.0])

    def test_cond_train_step(self):
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 1)

            def forward(self, x):
                h = self.fc(x)
                return ops.cond(paddle.mean(h) > 100.0,
                                lambda: h * 0.0, lambda: h)

        net = to_static(Net())
        opt = optim.SGD(learning_rate=0.1,
                        parameters=net.parameters())
        X = np.random.RandomState(0).rand(8, 4).astype(np.float32)
        loss0 = None
        for _ in range(5):
            loss = paddle.mean(net(paddle.to_tensor(X)) ** 2)
            loss.backward()
            opt.step()
            opt.clear_grad()
            loss0 = loss0 if loss0 is not None else float(loss.numpy())
        assert float(loss.numpy()) < loss0


class TestStaticProgramControlFlow:
    def test_static_cond(self):
        paddle.enable_static()
        try:
            main = paddle.static.Program()
            startup = paddle.static.Program()
            with paddle.static.program_guard(main, startup):
                x = paddle.static.data("x", [3], "float32")
                out = paddle.static.nn.cond(
                    paddle.mean(x) > 0.0, lambda: x * 2.0, lambda: x - 1.0)
            exe = paddle.static.Executor()
            pos, = exe.run(main, feed={"x": np.array([1, 2, 3], np.float32)},
                           fetch_list=[out])
            np.testing.assert_allclose(pos, [2, 4, 6])
            neg, = exe.run(main, feed={"x": -np.array([1, 2, 3], np.float32)},
                           fetch_list=[out])
            np.testing.assert_allclose(neg, [-2, -3, -4])
        finally:
            paddle.disable_static()

    def test_static_while(self):
        paddle.enable_static()
        try:
            main = paddle.static.Program()
            startup = paddle.static.Program()
            with paddle.static.program_guard(main, startup):
                x = paddle.static.data("x", [2], "float32")
                i = paddle.zeros([], "float32")
                i2, out = paddle.static.nn.while_loop(
                    lambda i, v: i < 3.0,
                    lambda i, v: [i + 1.0, v * 2.0],
                    [i, x])
            exe = paddle.static.Executor()
            res, = exe.run(main, feed={"x": np.array([1, 5], np.float32)},
                           fetch_list=[out])
            np.testing.assert_allclose(res, [8.0, 40.0])
        finally:
            paddle.disable_static()

    def test_static_cond_with_params(self):
        paddle.enable_static()
        try:
            main = paddle.static.Program()
            startup = paddle.static.Program()
            with paddle.static.program_guard(main, startup):
                x = paddle.static.data("x", [None, 4], "float32")
                lin = nn.Linear(4, 2)
                h = lin(x)
                out = paddle.static.nn.cond(
                    paddle.mean(h) > 1e6, lambda: h * 0.0, lambda: h + 1.0)
            exe = paddle.static.Executor()
            exe.run(startup)
            X = np.random.RandomState(0).rand(3, 4).astype(np.float32)
            res, = exe.run(main, feed={"x": X}, fetch_list=[out])
            expected = X @ lin.weight.numpy() + lin.bias.numpy() + 1.0
            np.testing.assert_allclose(res, expected, atol=1e-5)
        finally:
            paddle.disable_static()


class TestArrayOps:
    def test_array_write_read(self):
        arr = ops.create_array()
        x = paddle.to_tensor(np.float32(3.0))
        i = paddle.to_tensor(np.int64(0))
        ops.array_write(x, i, arr)
        got = ops.array_read(arr, i)
        assert float(got.numpy()) == 3.0
        assert int(ops.array_length(arr).numpy()) == 1

    def test_increment(self):
        x = paddle.to_tensor(np.array([1.0], np.float32))
        ops.increment(x, 2.0)
        np.testing.assert_allclose(x.numpy(), [3.0])

    def test_static_cond_passthrough_branch(self):
        # select between two existing tensors (review regression)
        paddle.enable_static()
        try:
            main = paddle.static.Program()
            startup = paddle.static.Program()
            with paddle.static.program_guard(main, startup):
                x = paddle.static.data("x", [1], "float32")
                y = paddle.static.data("y", [1], "float32")
                out = paddle.static.nn.cond(x[0] < y[0],
                                            lambda: x, lambda: y)
            exe = paddle.static.Executor()
            res, = exe.run(main, feed={"x": np.array([1.0], np.float32),
                                       "y": np.array([5.0], np.float32)},
                           fetch_list=[out])
            np.testing.assert_allclose(res, [1.0])
        finally:
            paddle.disable_static()

    def test_static_while_with_tensor_loop_var(self):
        # graph counter + eager Tensor accumulator (review regression)
        paddle.enable_static()
        try:
            main = paddle.static.Program()
            startup = paddle.static.Program()
            with paddle.static.program_guard(main, startup):
                i = paddle.zeros([1], "float32")
                acc = paddle.to_tensor(np.array([0.0], np.float32))
                i2, acc2 = paddle.static.nn.while_loop(
                    lambda i, a: i[0] < 3.0,
                    lambda i, a: [i + 1.0, a + 2.0],
                    [i, acc])
            exe = paddle.static.Executor()
            res, = exe.run(main, feed={}, fetch_list=[acc2])
            np.testing.assert_allclose(res, [6.0])
        finally:
            paddle.disable_static()
