"""Numpy-reference tests for the round-5 op tail (VERDICT weak-spot 1:
impl ops with no numeric test). Discipline per the reference's
op_test.py: every op checked against an independently-written numpy (or
torch CPU oracle) implementation of the REFERENCE op's documented
semantics; gradients via tests/op_test.py check_grad where meaningful.

Part 1: activations, binary/comparison/logical elementwise, reductions,
shape/indexing ops, loss functions, norm/vision functional ops."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.ops as ops
import paddle_tpu.nn.functional as F
from op_test import check_output, check_grad


def _rng(seed=0):
    return np.random.RandomState(seed)


def T(a):
    return paddle.to_tensor(a)


# ---------------------------------------------------------------------------
# activations (reference: operators/activation_op.cc kernels)

def _np_sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


ACTIVATIONS = [
    # (callable, numpy reference, input transform)
    (paddle.acos, np.arccos, lambda x: np.clip(x, -0.99, 0.99)),
    (paddle.cosh, np.cosh, None),
    (paddle.sinh, np.sinh, None),
    (paddle.reciprocal, lambda x: 1.0 / x, lambda x: x + 2.0),
    (paddle.lgamma, lambda x: np.vectorize(__import__("math").lgamma)(x),
     lambda x: np.abs(x) + 0.5),
    (paddle.log10, np.log10, lambda x: np.abs(x) + 0.1),
    (paddle.log2, np.log2, lambda x: np.abs(x) + 0.1),
    (paddle.logsigmoid, lambda x: x - np.logaddexp(0, x), None),
    (ops.brelu, lambda x: np.clip(x, -1.0, 1.0), None),
    (ops.hard_shrink, lambda x: np.where(np.abs(x) > 0.5, x, 0.0), None),
    (ops.hard_sigmoid, lambda x: np.clip(x / 6.0 + 0.5, 0, 1), None),
    (ops.hard_swish, lambda x: x * np.clip(x + 3, 0, 6) / 6.0, None),
    (ops.leaky_relu, lambda x: np.where(x >= 0, x, 0.01 * x), None),
    (ops.relu6, lambda x: np.clip(x, 0, 6), None),
    (ops.mish, lambda x: x * np.tanh(np.log1p(np.exp(x))), None),
    (ops.silu, lambda x: x * _np_sigmoid(x), None),
    (ops.swish, lambda x: x * _np_sigmoid(x), None),
    (ops.selu, lambda x: 1.0507009873554805 * np.where(
        x > 0, x, 1.6732632423543772 * (np.exp(x) - 1)), None),
    (ops.softplus, lambda x: np.logaddexp(0, x), None),
    (ops.softshrink, lambda x: np.where(x > 0.5, x - 0.5,
                                        np.where(x < -0.5, x + 0.5, 0.0)),
     None),
    (ops.softsign, lambda x: x / (1 + np.abs(x)), None),
    (paddle.stanh, lambda x: 1.7159 * np.tanh(0.67 * x), None),
    (paddle.soft_relu, lambda x: np.log1p(np.exp(np.clip(x, -40, 40))),
     None),
    (ops.tanh_shrink, lambda x: x - np.tanh(x), None),
    (ops.thresholded_relu, lambda x: np.where(x > 1.0, x, 0.0), None),
]


@pytest.mark.parametrize("op_fn,np_fn,dom",
                         ACTIVATIONS,
                         ids=[a[0].__name__ for a in ACTIVATIONS])
def test_activation_forward(op_fn, np_fn, dom):
    x = _rng(1).randn(3, 5).astype(np.float32) * 2.0
    if dom is not None:
        x = dom(x).astype(np.float32)
    check_output(op_fn, np_fn, [x], atol=1e-5, rtol=1e-4)


@pytest.mark.parametrize("op_fn", [ops.silu, ops.mish, ops.softplus,
                                   ops.hard_swish, paddle.stanh])
def test_activation_grad(op_fn):
    x = _rng(2).randn(4, 3).astype(np.float32)
    check_grad(op_fn, [x])


def test_prelu_and_maxout():
    x = _rng(3).randn(2, 4, 3, 3).astype(np.float32)
    w = np.array([0.25, 0.1, 0.5, 0.9], np.float32)
    got = ops.prelu(T(x), T(w)).numpy()
    ref = np.where(x >= 0, x, x * w.reshape(1, 4, 1, 1))
    np.testing.assert_allclose(got, ref, rtol=1e-6)
    # maxout: groups of channels reduced by max (maxout_op.cc)
    got = ops.maxout(T(x), groups=2).numpy()
    ref = x.reshape(2, 2, 2, 3, 3).max(axis=2)
    np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_conj():
    x = (_rng(4).randn(3, 2) + 1j * _rng(5).randn(3, 2)).astype(np.complex64)
    np.testing.assert_allclose(paddle.conj(T(x)).numpy(), np.conj(x))


# ---------------------------------------------------------------------------
# elementwise binary + comparisons + logicals

BINARY = [
    (ops.elementwise_add, np.add),
    (ops.elementwise_sub, np.subtract),
    (ops.elementwise_mul, np.multiply),
    (ops.elementwise_div, np.divide),
    (ops.elementwise_max, np.maximum),
    (ops.elementwise_min, np.minimum),
    (ops.elementwise_pow, np.power),
    (ops.elementwise_mod, np.mod),
    (ops.elementwise_floordiv, np.floor_divide),
]


@pytest.mark.parametrize("op_fn,np_fn", BINARY,
                         ids=[b[0].__name__ for b in BINARY])
def test_elementwise_binary(op_fn, np_fn):
    r = _rng(6)
    x = (r.rand(3, 4).astype(np.float32) + 0.5) * 2
    y = (r.rand(3, 4).astype(np.float32) + 0.5)
    check_output(op_fn, np_fn, [x, y], rtol=1e-5)
    # broadcasting across a trailing axis
    yb = (r.rand(4).astype(np.float32) + 0.5)
    check_output(op_fn, np_fn, [x, yb], rtol=1e-5)


def test_comparisons_and_logicals():
    r = _rng(7)
    x = r.randint(0, 3, (4, 5)).astype(np.float32)
    y = r.randint(0, 3, (4, 5)).astype(np.float32)
    np.testing.assert_array_equal(paddle.greater_equal(T(x), T(y)).numpy(),
                                  x >= y)
    np.testing.assert_array_equal(paddle.less_than(T(x), T(y)).numpy(),
                                  x < y)
    np.testing.assert_array_equal(paddle.not_equal(T(x), T(y)).numpy(),
                                  x != y)
    assert bool(paddle.equal_all(T(x), T(x)).numpy())
    assert not bool(paddle.equal_all(T(x), T(x + 1)).numpy())
    a = x > 1
    b = y > 1
    np.testing.assert_array_equal(paddle.logical_and(T(a), T(b)).numpy(),
                                  a & b)
    np.testing.assert_array_equal(paddle.logical_or(T(a), T(b)).numpy(),
                                  a | b)
    np.testing.assert_array_equal(paddle.logical_xor(T(a), T(b)).numpy(),
                                  a ^ b)
    np.testing.assert_array_equal(paddle.logical_not(T(a)).numpy(), ~a)


def test_matmul_v2_and_dot_addmm_kron():
    r = _rng(8)
    a = r.randn(3, 4).astype(np.float32)
    b = r.randn(4, 5).astype(np.float32)
    np.testing.assert_allclose(ops.matmul_v2(T(a), T(b)).numpy(), a @ b,
                               rtol=1e-5)
    np.testing.assert_allclose(
        ops.matmul_v2(T(a), T(a), transpose_y=True).numpy(), a @ a.T,
        rtol=1e-5)
    v = r.randn(6).astype(np.float32)
    w = r.randn(6).astype(np.float32)
    np.testing.assert_allclose(paddle.dot(T(v), T(w)).numpy(), v @ w,
                               rtol=1e-5)
    inp = r.randn(3, 5).astype(np.float32)
    np.testing.assert_allclose(
        paddle.addmm(T(inp), T(a), T(b), beta=0.5, alpha=2.0).numpy(),
        0.5 * inp + 2.0 * (a @ b), rtol=1e-5)
    np.testing.assert_allclose(paddle.kron(T(a), T(b)).numpy(),
                               np.kron(a, b), rtol=1e-5)


# ---------------------------------------------------------------------------
# reductions + norms + arg ops

def test_reductions_and_norms():
    r = _rng(9)
    x = r.randn(3, 4, 5).astype(np.float32)
    b = x > 0
    np.testing.assert_array_equal(ops.reduce_all(T(b), axis=1).numpy(),
                                  b.all(axis=1))
    np.testing.assert_array_equal(ops.reduce_any(T(b), axis=1).numpy(),
                                  b.any(axis=1))
    np.testing.assert_allclose(ops.reduce_max(T(x), axis=2).numpy(),
                               x.max(axis=2), rtol=1e-6)
    np.testing.assert_allclose(ops.reduce_min(T(x), axis=0).numpy(),
                               x.min(axis=0), rtol=1e-6)
    np.testing.assert_allclose(paddle.frobenius_norm(T(x[0])).numpy(),
                               np.linalg.norm(x[0]), rtol=1e-5)
    np.testing.assert_allclose(paddle.l1_norm(T(x)).numpy(),
                               np.abs(x).sum(), rtol=1e-5)
    np.testing.assert_array_equal(ops.arg_max(T(x), axis=1).numpy(),
                                  x.argmax(axis=1))
    np.testing.assert_array_equal(ops.arg_min(T(x), axis=-1).numpy(),
                                  x.argmin(axis=-1))


def test_clip_by_norm():
    x = _rng(10).randn(4, 3).astype(np.float32) * 3
    n = np.linalg.norm(x)
    got = ops.clip_by_norm(T(x), 1.5).numpy()
    np.testing.assert_allclose(got, x * 1.5 / n, rtol=1e-5)
    small = x * 0.01
    np.testing.assert_allclose(ops.clip_by_norm(T(small), 1e3).numpy(),
                               small, rtol=1e-6)


# ---------------------------------------------------------------------------
# shape / indexing ops

def test_expand_family_and_fill_like():
    r = _rng(11)
    x = r.randn(1, 3).astype(np.float32)
    np.testing.assert_allclose(
        ops.expand_v2(T(x), [4, 3]).numpy(), np.broadcast_to(x, (4, 3)))
    y = r.randn(4, 3).astype(np.float32)
    np.testing.assert_allclose(paddle.expand_as(T(x), T(y)).numpy(),
                               np.broadcast_to(x, (4, 3)))
    np.testing.assert_allclose(ops.expand_as_v2(T(x), T(y)).numpy(),
                               np.broadcast_to(x, (4, 3)))
    np.testing.assert_allclose(paddle.full_like(T(y), 7.0).numpy(),
                               np.full_like(y, 7.0))


def test_meshgrid_unbind_unstack_diag_embed():
    a = np.arange(3, dtype=np.float32)
    b = np.arange(4, dtype=np.float32)
    ga, gb = paddle.meshgrid(T(a), T(b))
    ra, rb = np.meshgrid(a, b, indexing="ij")
    np.testing.assert_array_equal(ga.numpy(), ra)
    np.testing.assert_array_equal(gb.numpy(), rb)
    x = _rng(12).randn(3, 4).astype(np.float32)
    parts = paddle.unbind(T(x), axis=0)
    assert len(parts) == 3
    np.testing.assert_array_equal(parts[1].numpy(), x[1])
    parts = paddle.unstack(T(x), axis=1)
    assert len(parts) == 4
    np.testing.assert_array_equal(parts[2].numpy(), x[:, 2])
    v = _rng(13).randn(2, 3).astype(np.float32)
    got = paddle.diag_embed(T(v)).numpy()
    ref = np.zeros((2, 3, 3), np.float32)
    for i in range(2):
        ref[i] = np.diag(v[i])
    np.testing.assert_array_equal(got, ref)


def test_strided_slice_index_sample_multiplex():
    r = _rng(14)
    x = r.randn(6, 8).astype(np.float32)
    got = paddle.strided_slice(T(x), axes=[0, 1], starts=[1, 0],
                               ends=[5, 8], strides=[2, 3]).numpy()
    np.testing.assert_array_equal(got, x[1:5:2, 0:8:3])
    idx = r.randint(0, 8, (6, 4)).astype(np.int64)
    got = paddle.index_sample(T(x), T(idx)).numpy()
    np.testing.assert_array_equal(got, np.take_along_axis(x, idx, axis=1))
    ins = [r.randn(4, 3).astype(np.float32) for _ in range(3)]
    sel = np.array([2, 0, 1, 2], np.int64).reshape(-1, 1)
    got = paddle.multiplex([T(i) for i in ins], T(sel)).numpy()
    ref = np.stack([ins[int(s)][j] for j, s in enumerate(sel[:, 0])])
    np.testing.assert_array_equal(got, ref)


def test_scatter_nd_add_where_index_histogram():
    x = np.zeros((4, 3), np.float32)
    index = np.array([[1], [3], [1]], np.int64)
    updates = np.ones((3, 3), np.float32)
    got = paddle.scatter_nd_add(T(x), T(index), T(updates)).numpy()
    ref = x.copy()
    for i, u in zip(index[:, 0], updates):
        ref[i] += u
    np.testing.assert_array_equal(got, ref)
    c = np.array([[True, False], [False, True]])
    got = paddle.where_index(T(c)).numpy()
    np.testing.assert_array_equal(got, np.argwhere(c))
    data = np.array([0.0, 1.0, 1.5, 2.9, 3.0], np.float32)
    got = paddle.histogram(T(data), bins=3, min=0, max=3).numpy()
    np.testing.assert_array_equal(got, np.histogram(data, 3, (0, 3))[0])


def test_space_depth_pixel_shuffle_shuffle_channel_unfold():
    r = _rng(15)
    x = r.randn(1, 2, 4, 4).astype(np.float32)
    got = paddle.space_to_depth(T(x), 2).numpy()
    assert got.shape == (1, 8, 2, 2)
    # inverse relationship with pixel_shuffle (depth_to_space)
    back = paddle.pixel_shuffle(T(got), 2).numpy()
    assert back.shape == x.shape
    xc = r.randn(1, 6, 2, 2).astype(np.float32)
    got = ops.shuffle_channel(T(xc), 3).numpy()
    ref = xc.reshape(1, 3, 2, 2, 2).transpose(0, 2, 1, 3, 4).reshape(xc.shape)
    np.testing.assert_array_equal(got, ref)
    # unfold == im2col (torch oracle)
    import torch
    xt = r.randn(2, 3, 6, 6).astype(np.float32)
    got = paddle.unfold(T(xt), [2, 2], strides=2).numpy()
    ref = torch.nn.functional.unfold(torch.from_numpy(xt), (2, 2),
                                     stride=2).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_one_hot_is_empty_bernoulli_shapes():
    lab = np.array([0, 2, 1], np.int64)
    got = ops.one_hot_v2(T(lab), 4).numpy()
    np.testing.assert_array_equal(got, np.eye(4, dtype=np.float32)[lab])
    assert not bool(ops.is_empty(T(lab)).numpy())
    assert bool(ops.is_empty(T(np.zeros((0, 3)))).numpy())


# ---------------------------------------------------------------------------
# losses

def test_losses_numpy_refs():
    r = _rng(16)
    p = r.rand(6, 1).astype(np.float32) * 0.8 + 0.1
    y = (r.rand(6, 1) > 0.5).astype(np.float32)
    # log_loss (log_loss_op.cc)
    ref = -y * np.log(p + 1e-4) - (1 - y) * np.log(1 - p + 1e-4)
    np.testing.assert_allclose(F.log_loss(T(p), T(y)).numpy(), ref,
                               rtol=1e-5)
    # hinge_loss (hinge_loss_op.cc): max(1 - pred*(2y-1), 0)
    pred = r.randn(6, 1).astype(np.float32)
    ref = np.maximum(1 - pred * (2 * y - 1), 0)
    np.testing.assert_allclose(F.hinge_loss(T(pred), T(y)).numpy(), ref,
                               rtol=1e-5)
    # kldiv_loss (kldiv_loss_op.cc): target * (log(target) - input)
    x = np.log(r.rand(4, 5).astype(np.float32) + 0.1)
    t = r.rand(4, 5).astype(np.float32) + 0.1
    ref = (t * (np.log(t) - x)).mean()
    np.testing.assert_allclose(
        ops.kldiv_loss(T(x), T(t), reduction="mean").numpy(), ref,
        rtol=1e-5)
    # nll_loss (nll_loss_op.cc)
    logp = np.log(r.rand(5, 3).astype(np.float32) + 0.05)
    lab = r.randint(0, 3, (5,)).astype(np.int64)
    ref = -logp[np.arange(5), lab].mean()
    np.testing.assert_allclose(F.nll_loss(T(logp), T(lab)).numpy(), ref,
                               rtol=1e-5)
    # label_smooth (label_smooth_op.cc)
    onehot = np.eye(4, dtype=np.float32)[r.randint(0, 4, (6,))]
    ref = onehot * 0.9 + 0.1 / 4
    np.testing.assert_allclose(F.label_smooth(T(onehot)).numpy(), ref,
                               rtol=1e-5)
    # sigmoid_focal_loss (sigmoid_focal_loss_op.cc semantics, v2 API)
    logit = r.randn(6, 1).astype(np.float32)
    lbl = (r.rand(6, 1) > 0.5).astype(np.float32)
    pr = _np_sigmoid(logit)
    ce = -lbl * np.log(pr) - (1 - lbl) * np.log(1 - pr)
    pt = pr * lbl + (1 - pr) * (1 - lbl)
    alpha_t = 0.25 * lbl + 0.75 * (1 - lbl)
    ref = (alpha_t * (1 - pt) ** 2.0 * ce).sum()
    np.testing.assert_allclose(
        ops.sigmoid_focal_loss(T(logit), T(lbl)).numpy(), ref, rtol=1e-4)


# ---------------------------------------------------------------------------
# norm / vision functional (torch CPU oracle where numpy is painful)

def test_instance_norm_and_lrn():
    import torch
    r = _rng(17)
    x = r.randn(2, 3, 4, 5).astype(np.float32)
    got = F.instance_norm(T(x)).numpy()
    ref = torch.nn.functional.instance_norm(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
    # lrn_op.cc: div = (k + alpha * sum)^beta — alpha NOT divided by
    # size; torch divides by n, so scale its alpha up to compare
    got = F.local_response_norm(T(x), size=3, alpha=1e-2, beta=0.75,
                                k=1.0).numpy()
    ref = torch.nn.functional.local_response_norm(
        torch.from_numpy(x), 3, alpha=1e-2 * 3, beta=0.75, k=1.0).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_conv3d_family():
    import torch
    r = _rng(18)
    x = r.randn(1, 2, 5, 6, 6).astype(np.float32)
    w = r.randn(4, 2, 3, 3, 3).astype(np.float32) * 0.2
    b = r.randn(4).astype(np.float32)
    got = F.conv3d(T(x), T(w), T(b), stride=2, padding=1).numpy()
    ref = torch.nn.functional.conv3d(torch.from_numpy(x),
                                     torch.from_numpy(w),
                                     torch.from_numpy(b), stride=2,
                                     padding=1).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)
    wt = r.randn(2, 3, 3, 3, 3).astype(np.float32) * 0.2
    got = F.conv3d_transpose(T(x), T(wt), stride=2).numpy()
    ref = torch.nn.functional.conv_transpose3d(torch.from_numpy(x),
                                               torch.from_numpy(wt),
                                               stride=2).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)
    got = F.max_pool3d(T(x), 2, stride=2).numpy()
    ref = torch.nn.functional.max_pool3d(torch.from_numpy(x), 2,
                                         stride=2).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_grid_sample_and_affine_grid():
    import torch
    r = _rng(19)
    x = r.randn(2, 3, 5, 5).astype(np.float32)
    grid = (r.rand(2, 4, 4, 2).astype(np.float32) * 2 - 1) * 0.9
    got = ops.grid_sample(T(x), T(grid)).numpy()
    ref = torch.nn.functional.grid_sample(
        torch.from_numpy(x), torch.from_numpy(grid), mode="bilinear",
        padding_mode="zeros", align_corners=True).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
    theta = r.randn(2, 2, 3).astype(np.float32)
    got = ops.affine_grid(T(theta), [2, 3, 4, 5]).numpy()
    ref = torch.nn.functional.affine_grid(
        torch.from_numpy(theta), [2, 3, 4, 5], align_corners=True).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_temporal_shift():
    # temporal_shift_op.cc: shift 1/4 channels fwd, 1/4 back along time
    r = _rng(20)
    N, TT, C, H, W = 2, 4, 8, 2, 2
    x = r.randn(N * TT, C, H, W).astype(np.float32)
    got = F.temporal_shift(T(x), seg_num=TT, shift_ratio=0.25).numpy()
    xr = x.reshape(N, TT, C, H, W)
    ref = np.zeros_like(xr)
    c1 = C // 4
    ref[:, :-1, :c1] = xr[:, 1:, :c1]              # shift left (future)
    ref[:, 1:, c1:2 * c1] = xr[:, :-1, c1:2 * c1]  # shift right (past)
    ref[:, :, 2 * c1:] = xr[:, :, 2 * c1:]
    np.testing.assert_allclose(got, ref.reshape(x.shape), rtol=1e-6)
