"""The op-coverage audit is CI: every reference catalog op must map to an
implementation / absorption / ADR with import-checked targets (VERDICT r3
item 6)."""
import io
import os
import subprocess
import sys

import numpy as np

import paddle_tpu as paddle


def test_coverage_audit_no_blanks():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "op_coverage.py"),
         "--check"],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr
    assert "blanks=0" in r.stdout


def test_coverage_doc_exists_and_counts():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    doc = open(os.path.join(repo, "docs", "op_coverage.md")).read()
    assert "| reference op | status | mapping |" in doc
    # the >=470 bar from VERDICT r3 item 6
    import re
    m = re.search(r"Implemented \+ absorbed = (\d+) / (\d+)", doc)
    assert m and int(m.group(1)) >= 470, m.group(0) if m else doc[:200]


def test_static_assert_and_print():
    import paddle_tpu.static as st
    t = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    assert st.nn.Assert(paddle.to_tensor(True)) is not None
    try:
        st.nn.Assert(paddle.to_tensor(False), data=[t])
        assert False, "Assert(False) must raise"
    except AssertionError:
        pass
    out = st.nn.Print(t, message="dbg")
    np.testing.assert_allclose(out.numpy(), t.numpy())


def test_image_io_roundtrip(tmp_path):
    from PIL import Image
    arr = (np.random.RandomState(0).rand(8, 10, 3) * 255).astype(np.uint8)
    p = tmp_path / "x.png"
    Image.fromarray(arr).save(p)
    raw = paddle.vision.read_file(str(p))
    assert raw.dtype == "uint8" and raw.ndim == 1
    img = paddle.vision.decode_jpeg(raw, mode="rgb")
    assert img.shape == [3, 8, 10]
    np.testing.assert_array_equal(np.transpose(img.numpy(), (1, 2, 0)), arr)
    hwc = paddle.vision.image_load(str(p))
    np.testing.assert_array_equal(hwc, arr)
