"""Pipeline parallel tests (reference analogs:
unittests/test_parallel_dygraph_pipeline_layer.py,
hybrid_parallel_pp_layer.py — stage partitioning; pipeline loss parity)."""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as optim
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.fleet import (PipelineLayer, PipelineParallel,
                                          LayerDesc, DistributedStrategy)
from paddle_tpu.distributed.fleet import pipeline_engine as PE


class TestPipelineLayer:
    def test_uniform_partition(self):
        layers = [nn.Linear(4, 4) for _ in range(6)]
        pl = PipelineLayer(layers=layers, num_stages=2)
        assert pl._stage_bounds == [0, 3, 6]
        assert pl.stages_uniform()

    def test_layer_desc_and_seg_method(self):
        descs = ([LayerDesc(nn.Linear, 4, 4) for _ in range(4)]
                 + [LayerDesc(nn.ReLU)])
        pl = PipelineLayer(layers=descs, num_stages=2,
                           seg_method="layer:Linear")
        assert pl._stage_bounds[0] == 0 and pl._stage_bounds[-1] == 5
        # forward equals applying all layers in order
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(2, 4).astype(np.float32))
        out = pl(x)
        ref = x
        for l in pl._all_layers:
            ref = l(ref)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-6)

    def test_forward_matches_sequential(self):
        paddle.seed(0)
        layers = [nn.Linear(8, 8) for _ in range(4)]
        pl = PipelineLayer(layers=layers, num_stages=4)
        x = paddle.to_tensor(np.random.RandomState(1)
                             .randn(3, 8).astype(np.float32))
        ref = x
        for l in layers:
            ref = l(ref)
        np.testing.assert_allclose(pl(x).numpy(), ref.numpy(), atol=1e-5)


class TestPipelineParallelSchedule:
    def _make(self, use_pp, k=4):
        paddle.seed(11)
        layers = [nn.Linear(8, 8), nn.Tanh(), nn.Linear(8, 8), nn.Tanh(),
                  nn.Linear(8, 1)]
        loss_fn = nn.MSELoss()
        pl = PipelineLayer(layers=layers, num_stages=2, loss_fn=loss_fn)
        st = DistributedStrategy()
        st.pipeline_configs = {"accumulate_steps": k if use_pp else 1}
        pp = PipelineParallel(pl, None, st)
        opt = optim.SGD(learning_rate=0.1, parameters=pp.parameters())
        return pp, opt

    def test_microbatch_schedule_matches_full_batch(self):
        rng = np.random.RandomState(0)
        X = rng.randn(8, 8).astype(np.float32)
        Y = rng.randn(8, 1).astype(np.float32)

        pp1, opt1 = self._make(use_pp=False)
        loss_full = pp1.train_batch((X, Y), opt1)

        pp4, opt4 = self._make(use_pp=True, k=4)
        loss_micro = pp4.train_batch((X, Y), opt4)

        np.testing.assert_allclose(float(loss_micro.numpy()),
                                   float(loss_full.numpy()), rtol=1e-5)
        for p1, p4 in zip(pp1.parameters(), pp4.parameters()):
            np.testing.assert_allclose(p4.numpy(), p1.numpy(), atol=1e-6)

    def test_train_batch_converges(self):
        pp, opt = self._make(use_pp=True, k=2)
        rng = np.random.RandomState(3)
        X = rng.randn(8, 8).astype(np.float32)
        Y = (X.sum(axis=1, keepdims=True) * 0.1).astype(np.float32)
        losses = [float(pp.train_batch((X, Y), opt).numpy())
                  for _ in range(10)]
        assert losses[-1] < losses[0] * 0.5

    def test_eval_batch(self):
        pp, _ = self._make(use_pp=True)
        X = np.ones((4, 8), np.float32)
        Y = np.zeros((4, 1), np.float32)
        loss = pp.eval_batch((X, Y))
        assert np.isfinite(float(loss.numpy()))


class TestCompiledGPipeEngine:
    def test_gpipe_apply_matches_sequential(self):
        dist.set_mesh(dist.build_mesh({"pp": 8}))
        try:
            rng = np.random.RandomState(0)
            S, M, mb, d = 8, 4, 2, 16
            Ws = [rng.randn(d, d).astype(np.float32) * 0.1 for _ in range(S)]
            bs = [rng.randn(d).astype(np.float32) * 0.1 for _ in range(S)]
            stacked = {"w": jnp.stack(Ws), "b": jnp.stack(bs)}

            def block(params, x):
                return jnp.tanh(x @ params["w"] + params["b"])

            x = rng.randn(M, mb, d).astype(np.float32)
            out = PE.gpipe_apply(block, stacked, jnp.asarray(x))

            ref = x.copy()
            for s in range(S):
                ref = np.tanh(ref @ Ws[s] + bs[s])
            np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)
        finally:
            dist.set_mesh(None)

    def test_gpipe_grads_flow(self):
        dist.set_mesh(dist.build_mesh({"pp": 8}))
        try:
            rng = np.random.RandomState(1)
            S, M, mb, d = 8, 2, 2, 8
            stacked = {"w": jnp.asarray(
                rng.randn(S, d, d).astype(np.float32) * 0.1)}

            def block(params, x):
                return jnp.tanh(x @ params["w"])

            x = jnp.asarray(rng.randn(M, mb, d).astype(np.float32))

            def loss_fn(params):
                return jnp.mean(PE.gpipe_apply(block, params, x) ** 2)

            g = jax.grad(loss_fn)(stacked)
            assert np.isfinite(np.asarray(g["w"])).all()
            assert float(jnp.abs(g["w"]).sum()) > 0
            # every stage receives gradient signal
            per_stage = np.asarray(jnp.abs(g["w"]).sum(axis=(1, 2)))
            assert (per_stage > 0).all()
        finally:
            dist.set_mesh(None)

    def test_split_microbatches(self):
        x = jnp.arange(24.0).reshape(8, 3)
        mb = PE.split_microbatches(x, 4)
        assert mb.shape == (4, 2, 3)
        with pytest.raises(ValueError):
            PE.split_microbatches(x, 3)
