"""Pipeline parallel tests (reference analogs:
unittests/test_parallel_dygraph_pipeline_layer.py,
hybrid_parallel_pp_layer.py — stage partitioning; pipeline loss parity)."""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as optim
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.fleet import (PipelineLayer, PipelineParallel,
                                          LayerDesc, DistributedStrategy)
from paddle_tpu.distributed.fleet import pipeline_engine as PE


class TestPipelineLayer:
    def test_uniform_partition(self):
        layers = [nn.Linear(4, 4) for _ in range(6)]
        pl = PipelineLayer(layers=layers, num_stages=2)
        assert pl._stage_bounds == [0, 3, 6]
        assert pl.stages_uniform()

    def test_layer_desc_and_seg_method(self):
        descs = ([LayerDesc(nn.Linear, 4, 4) for _ in range(4)]
                 + [LayerDesc(nn.ReLU)])
        pl = PipelineLayer(layers=descs, num_stages=2,
                           seg_method="layer:Linear")
        assert pl._stage_bounds[0] == 0 and pl._stage_bounds[-1] == 5
        # forward equals applying all layers in order
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(2, 4).astype(np.float32))
        out = pl(x)
        ref = x
        for l in pl._all_layers:
            ref = l(ref)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-6)

    def test_forward_matches_sequential(self):
        paddle.seed(0)
        layers = [nn.Linear(8, 8) for _ in range(4)]
        pl = PipelineLayer(layers=layers, num_stages=4)
        x = paddle.to_tensor(np.random.RandomState(1)
                             .randn(3, 8).astype(np.float32))
        ref = x
        for l in layers:
            ref = l(ref)
        np.testing.assert_allclose(pl(x).numpy(), ref.numpy(), atol=1e-5)


class TestPipelineParallelSchedule:
    def _make(self, use_pp, k=4):
        paddle.seed(11)
        layers = [nn.Linear(8, 8), nn.Tanh(), nn.Linear(8, 8), nn.Tanh(),
                  nn.Linear(8, 1)]
        loss_fn = nn.MSELoss()
        pl = PipelineLayer(layers=layers, num_stages=2, loss_fn=loss_fn)
        st = DistributedStrategy()
        st.pipeline_configs = {"accumulate_steps": k if use_pp else 1}
        pp = PipelineParallel(pl, None, st)
        opt = optim.SGD(learning_rate=0.1, parameters=pp.parameters())
        return pp, opt

    @pytest.mark.slow
    def test_microbatch_schedule_matches_full_batch(self):
        rng = np.random.RandomState(0)
        X = rng.randn(8, 8).astype(np.float32)
        Y = rng.randn(8, 1).astype(np.float32)

        pp1, opt1 = self._make(use_pp=False)
        loss_full = pp1.train_batch((X, Y), opt1)

        pp4, opt4 = self._make(use_pp=True, k=4)
        loss_micro = pp4.train_batch((X, Y), opt4)

        np.testing.assert_allclose(float(loss_micro.numpy()),
                                   float(loss_full.numpy()), rtol=1e-5)
        for p1, p4 in zip(pp1.parameters(), pp4.parameters()):
            np.testing.assert_allclose(p4.numpy(), p1.numpy(), atol=1e-6)

    def test_train_batch_converges(self):
        pp, opt = self._make(use_pp=True, k=2)
        rng = np.random.RandomState(3)
        X = rng.randn(8, 8).astype(np.float32)
        Y = (X.sum(axis=1, keepdims=True) * 0.1).astype(np.float32)
        losses = [float(pp.train_batch((X, Y), opt).numpy())
                  for _ in range(10)]
        assert losses[-1] < losses[0] * 0.5

    def test_eval_batch(self):
        pp, _ = self._make(use_pp=True)
        X = np.ones((4, 8), np.float32)
        Y = np.zeros((4, 1), np.float32)
        loss = pp.eval_batch((X, Y))
        assert np.isfinite(float(loss.numpy()))


class TestCompiledGPipeEngine:
    def test_gpipe_apply_matches_sequential(self):
        dist.set_mesh(dist.build_mesh({"pp": 8}))
        try:
            rng = np.random.RandomState(0)
            S, M, mb, d = 8, 4, 2, 16
            Ws = [rng.randn(d, d).astype(np.float32) * 0.1 for _ in range(S)]
            bs = [rng.randn(d).astype(np.float32) * 0.1 for _ in range(S)]
            stacked = {"w": jnp.stack(Ws), "b": jnp.stack(bs)}

            def block(params, x):
                return jnp.tanh(x @ params["w"] + params["b"])

            x = rng.randn(M, mb, d).astype(np.float32)
            out = PE.gpipe_apply(block, stacked, jnp.asarray(x))

            ref = x.copy()
            for s in range(S):
                ref = np.tanh(ref @ Ws[s] + bs[s])
            np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)
        finally:
            dist.set_mesh(None)

    @pytest.mark.slow
    def test_gpipe_grads_flow(self):
        dist.set_mesh(dist.build_mesh({"pp": 8}))
        try:
            rng = np.random.RandomState(1)
            S, M, mb, d = 8, 2, 2, 8
            stacked = {"w": jnp.asarray(
                rng.randn(S, d, d).astype(np.float32) * 0.1)}

            def block(params, x):
                return jnp.tanh(x @ params["w"])

            x = jnp.asarray(rng.randn(M, mb, d).astype(np.float32))

            def loss_fn(params):
                return jnp.mean(PE.gpipe_apply(block, params, x) ** 2)

            g = jax.grad(loss_fn)(stacked)
            assert np.isfinite(np.asarray(g["w"])).all()
            assert float(jnp.abs(g["w"]).sum()) > 0
            # every stage receives gradient signal
            per_stage = np.asarray(jnp.abs(g["w"]).sum(axis=(1, 2)))
            assert (per_stage > 0).all()
        finally:
            dist.set_mesh(None)

    def test_split_microbatches(self):
        x = jnp.arange(24.0).reshape(8, 3)
        mb = PE.split_microbatches(x, 4)
        assert mb.shape == (4, 2, 3)
        with pytest.raises(ValueError):
            PE.split_microbatches(x, 3)


class TestHeterogeneousPipeline:
    """Round-3: embed → blocks → head inside the compiled pipe
    (VERDICT weak #3 — no more shape-preserving restriction)."""

    def _mesh4(self):
        import paddle_tpu.distributed as dist
        return dist.build_mesh({"pp": 4}, jax.devices()[:4])

    def test_gpipe_blocks_matches_sequential(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.fleet import pipeline_engine as PE
        mesh = self._mesh4()
        dist.set_mesh(mesh)
        try:
            rng = np.random.RandomState(0)
            S, M, mb, seq, d, V = 4, 8, 2, 6, 16, 32
            emb = {"tok": jnp.asarray(rng.randn(V, d) * 0.1, jnp.float32)}
            blocks = {"w1": jnp.asarray(rng.randn(S, d, 2 * d) * 0.1,
                                        jnp.float32),
                      "w2": jnp.asarray(rng.randn(S, 2 * d, d) * 0.1,
                                        jnp.float32)}
            head = {"wo": jnp.asarray(rng.randn(d, V) * 0.1, jnp.float32)}

            def embed_fn(p, ids):
                return p["tok"][ids]

            def block_fn(p, h):
                return h + jax.nn.gelu(h @ p["w1"]) @ p["w2"]

            def head_fn(p, h):
                return h @ p["wo"]

            xs = jnp.asarray(rng.randint(0, V, (M, mb, seq)), jnp.int32)
            out = PE.gpipe_blocks(embed_fn, block_fn, head_fn, emb, blocks,
                                  head, xs, mesh=mesh)
            h = np.asarray(emb["tok"])[np.asarray(xs)]
            for s in range(S):
                w1 = np.asarray(blocks["w1"][s])
                w2 = np.asarray(blocks["w2"][s])
                g = h @ w1
                g = 0.5 * g * (1 + np.tanh(np.sqrt(2 / np.pi)
                                           * (g + 0.044715 * g ** 3)))
                h = h + g @ w2
            ref = h @ np.asarray(head["wo"])
            np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-3,
                                       atol=2e-4)
        finally:
            dist.set_mesh(None)

    @pytest.mark.slow
    def test_gpipe_blocks_grads_match_sequential(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.fleet import pipeline_engine as PE
        mesh = self._mesh4()
        dist.set_mesh(mesh)
        try:
            rng = np.random.RandomState(1)
            S, M, mb, seq, d, V = 4, 4, 2, 5, 8, 16
            emb = {"tok": jnp.asarray(rng.randn(V, d) * 0.1, jnp.float32)}
            blocks = {"w": jnp.asarray(rng.randn(S, d, d) * 0.1,
                                       jnp.float32)}
            head = {"wo": jnp.asarray(rng.randn(d, V) * 0.1, jnp.float32)}
            xs = jnp.asarray(rng.randint(0, V, (M, mb, seq)), jnp.int32)
            ys = jnp.asarray(rng.randint(0, V, (M, mb, seq)), jnp.int32)

            def embed_fn(p, ids):
                return p["tok"][ids]

            def block_fn(p, h):
                return h + jnp.tanh(h @ p["w"])

            def head_fn(p, h, labels):
                lo = jax.nn.log_softmax(h @ p["wo"])
                return -jnp.mean(jnp.take_along_axis(
                    lo, labels[..., None], axis=-1))

            def loss_pipe(e, b, hd):
                return jnp.mean(PE.gpipe_blocks(
                    embed_fn, block_fn, head_fn, e, b, hd, xs, mesh=mesh,
                    head_takes_input=True))

            # labels == inputs here so head sees aligned ids
            def loss_seq(e, b, hd):
                h = e["tok"][xs]
                for s in range(S):
                    h = h + jnp.tanh(h @ b["w"][s])
                lo = jax.nn.log_softmax(h @ hd["wo"])
                return -jnp.mean(jnp.take_along_axis(
                    lo, xs[..., None], axis=-1))

            l1, g1 = jax.value_and_grad(loss_pipe, argnums=(0, 1, 2))(
                emb, blocks, head)
            l2, g2 = jax.value_and_grad(loss_seq, argnums=(0, 1, 2))(
                emb, blocks, head)
            np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
            for a, b_ in zip(jax.tree_util.tree_leaves(g1),
                             jax.tree_util.tree_leaves(g2)):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                           rtol=2e-4, atol=2e-5)
        finally:
            dist.set_mesh(None)

    def test_signature_mismatch_raises(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.fleet import pipeline_engine as PE
        mesh = self._mesh4()
        dist.set_mesh(mesh)
        try:
            d = 8
            xs = jnp.zeros((4, 2, d), jnp.float32)
            blocks = {"w": jnp.zeros((4, d, 2 * d), jnp.float32)}
            with pytest.raises(ValueError, match="preserve"):
                PE.gpipe_blocks(lambda p, x: x,
                                lambda p, h: h @ p["w"],  # d -> 2d: bad
                                lambda p, h: h,
                                {}, blocks, {}, xs, mesh=mesh)
        finally:
            dist.set_mesh(None)

    @pytest.mark.slow
    def test_pipeline_layer_compiled_heterogeneous(self):
        import paddle_tpu as paddle
        import paddle_tpu.optimizer as optim
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.fleet.pipeline_parallel import (
            PipelineLayer, PipelineParallel)
        S = 4
        dist.set_mesh(dist.build_mesh({"pp": S}, jax.devices()[:S]))
        try:
            paddle.seed(0)
            V, d = 32, 16

            class Embed(nn.Layer):
                def __init__(self):
                    super().__init__()
                    self.emb = nn.Embedding(V, d)

                def forward(self, ids):
                    return self.emb(ids)

            class Block(nn.Layer):
                def __init__(self):
                    super().__init__()
                    self.fc = nn.Linear(d, d)

                def forward(self, h):
                    return h + nn.functional.tanh(self.fc(h))

            class Head(nn.Layer):
                def __init__(self):
                    super().__init__()
                    self.out = nn.Linear(d, V)

                def forward(self, h):
                    return self.out(h)

            class CE(nn.Layer):
                def forward(self, logits, labels):
                    from paddle_tpu import ops
                    return nn.functional.cross_entropy(
                        ops.reshape(logits, [-1, V]),
                        ops.reshape(labels, [-1]))

            pl = PipelineLayer([Embed(), Block(), Block(), Block(), Block(),
                                Head()], num_stages=S, loss_fn=CE())
            assert not pl.stages_uniform()  # heterogeneous by construction
            pp = PipelineParallel(pl)
            pp._accumulate_steps = 4
            opt = optim.AdamW(learning_rate=5e-3,
                              parameters=pl.parameters())
            rng = np.random.RandomState(0)
            ids = rng.randint(0, V, (8, 6)).astype(np.int32)
            labels = rng.randint(0, V, (8, 6)).astype(np.int64)

            sd = {k: v.numpy().copy() for k, v in pl.state_dict().items()}
            losses = [float(pp.train_batch_compiled(
                (paddle.to_tensor(ids), paddle.to_tensor(labels)),
                opt).numpy()) for _ in range(4)]
            assert losses[-1] < losses[0]

            # first compiled step == first eager-schedule step
            pl.set_state_dict({k: paddle.to_tensor(v)
                               for k, v in sd.items()})
            opt2 = optim.AdamW(learning_rate=5e-3,
                               parameters=pl.parameters())
            l0 = pp.train_batch(
                (paddle.to_tensor(ids), paddle.to_tensor(labels)), opt2)
            np.testing.assert_allclose(losses[0], float(l0.numpy()),
                                       rtol=2e-5)
        finally:
            dist.set_mesh(None)
