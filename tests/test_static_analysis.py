"""Tier-1 tests for the static-analysis framework (tools/analyze).

Covers the analyzer core (suppression parsing, baseline add/expire
semantics, JSON schema), one positive + one negative fixture per rule,
and the two acceptance gates from the issue:

- the repo-wide run exits 0 against the checked-in baseline;
- seeding ``if x.item():`` into a jit-reachable function in a scratch
  copy of the tree exits 1 with PTA001 at the right file:line.
"""
import json
import os
import shutil
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.analyze.core import (Finding, Project, filter_noqa,  # noqa: E402
                                load_baseline, run_rules, split_findings,
                                write_baseline)
from tools.analyze.rules import ALL_RULES, rules_by_code  # noqa: E402

RULES = rules_by_code()


def _mini(tmp_path, files):
    """Materialize {relpath: source} under tmp_path, return a Project."""
    roots = set()
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        roots.add(rel.split("/")[0])
    py_roots = sorted(r for r in roots if r != "tools")
    return Project(str(tmp_path), py_roots)


def _run(tmp_path, files, codes):
    project = _mini(tmp_path, files)
    findings = run_rules(project, [RULES[c] for c in codes])
    return project, findings


def _driver(args, cwd=REPO):
    proc = subprocess.run([sys.executable, "-m", "tools.analyze"] + args,
                          cwd=cwd, capture_output=True, text=True)
    return proc


# -- PTA001 tracer safety -----------------------------------------------------

JIT_POS = """\
    import jax

    @jax.jit
    def entry(x):
        return helper(x)

    def helper(x):
        if x.item():
            return x
        return x
"""


def test_pta001_flags_host_call_reachable_from_jit(tmp_path):
    _, findings = _run(tmp_path, {"paddle_tpu/a.py": JIT_POS}, ["PTA001"])
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "PTA001" and f.path == "paddle_tpu/a.py"
    assert f.line == 8  # the `if x.item():` line
    assert "branches on a host-forced" in f.message
    assert "jit-reachable" in f.message


def test_pta001_ignores_same_code_without_jit_root(tmp_path):
    src = JIT_POS.replace("    @jax.jit\n", "")
    _, findings = _run(tmp_path, {"paddle_tpu/a.py": src}, ["PTA001"])
    assert findings == []


def test_pta001_function_passed_to_trace_wrapper_is_a_root(tmp_path):
    src = """\
        import jax

        def step(x):
            return float(x)

        compiled = jax.jit(step)
    """
    _, findings = _run(tmp_path, {"paddle_tpu/a.py": src}, ["PTA001"])
    assert len(findings) == 1
    assert "float() on parameter-derived value" in findings[0].message


# -- PTA002 host sync in hot paths --------------------------------------------

SYNC_SRC = """\
    import numpy as np

    def op(x):
        return np.asarray(x)

    def op2(x):
        return x.numpy()
"""


def test_pta002_flags_syncs_in_ops_dir(tmp_path):
    _, findings = _run(tmp_path, {"paddle_tpu/ops/m.py": SYNC_SRC},
                       ["PTA002"])
    assert {f.line for f in findings} == {4, 7}
    assert all(f.rule == "PTA002" for f in findings)


def test_pta002_ignores_cold_paths_and_literals(tmp_path):
    _, cold = _run(tmp_path, {"paddle_tpu/vision/m.py": SYNC_SRC},
                   ["PTA002"])
    assert cold == []
    lit = """\
        import numpy as np

        def op():
            return np.asarray([1, 2, 3])
    """
    _, findings = _run(tmp_path, {"paddle_tpu/ops/m.py": lit}, ["PTA002"])
    assert findings == []


# -- PTA003 silent except -----------------------------------------------------

SWALLOW = """\
    def f():
        try:
            g()
        except Exception:
            pass
"""


def test_pta003_flags_swallow_in_checked_dirs(tmp_path):
    _, findings = _run(tmp_path, {"paddle_tpu/utils/x.py": SWALLOW},
                       ["PTA003"])
    assert len(findings) == 1 and "swallows" in findings[0].message


def test_pta003_ignores_handled_and_unchecked(tmp_path):
    handled = SWALLOW.replace("        pass\n", "        raise\n")
    _, findings = _run(tmp_path, {"paddle_tpu/utils/x.py": handled},
                       ["PTA003"])
    assert findings == []
    _, findings = _run(tmp_path, {"paddle_tpu/ops/x.py": SWALLOW},
                       ["PTA003"])
    assert findings == []


# -- PTA004 op registry <-> catalog -------------------------------------------

OPS_MOD = '''\
    """Ops. reference: operators/foo_op.cc"""
    from .dispatch import apply

    def foo(x):
        return apply("foo", lambda a: a, x)

    def bar(x):
        return apply("bar", lambda a: a, x)
'''


def test_pta004_unlisted_and_stale(tmp_path):
    files = {
        "paddle_tpu/ops/m.py": OPS_MOD,
        "tools/op_catalog.txt": "bar\nghost\n",
    }
    _, findings = _run(tmp_path, files, ["PTA004"])
    anchors = {f.anchor for f in findings}
    assert "unlisted:foo" in anchors       # registered, not in catalog
    assert "stale:ghost" in anchors        # cataloged, claimed by nothing
    assert not any(a.startswith(("unlisted:bar", "stale:bar"))
                   for a in anchors)


def test_pta004_native_claims(tmp_path):
    files = {
        "paddle_tpu/ops/m.py": OPS_MOD,
        "tools/op_catalog.txt": "bar\n# native: foo\n# native: gone\n",
    }
    _, findings = _run(tmp_path, files, ["PTA004"])
    anchors = {f.anchor for f in findings}
    assert "unlisted:foo" not in anchors   # claimed by the native line
    assert "stale-native:gone" in anchors  # claim with no op behind it


def test_pta004_catalog_hygiene(tmp_path):
    files = {
        "paddle_tpu/ops/m.py": OPS_MOD,
        "tools/op_catalog.txt": "foo\nbar\nbar\n",  # unsorted + duplicate
    }
    _, findings = _run(tmp_path, files, ["PTA004"])
    anchors = {f.anchor for f in findings}
    assert "sort:bar" in anchors and "dup:bar" in anchors


def test_pta004_missing_reference_docstring(tmp_path):
    files = {
        "paddle_tpu/ops/m.py": 'def foo(x):\n    return x\n',
        "tools/op_catalog.txt": "foo\n",
    }
    _, findings = _run(tmp_path, files, ["PTA004"])
    assert any(f.anchor == "no-reference-line" for f in findings)


# -- PTA005 api hygiene -------------------------------------------------------

def test_pta005_mutable_default(tmp_path):
    src = """\
        from __future__ import annotations

        def f(x, acc=[]):
            return acc
    """
    _, findings = _run(tmp_path, {"paddle_tpu/api.py": src}, ["PTA005"])
    assert len(findings) == 1 and "mutable default" in findings[0].message


def test_pta005_future_annotations_and_clean(tmp_path):
    src = """\
        def f(x: int) -> int:
            return x
    """
    _, findings = _run(tmp_path, {"paddle_tpu/api.py": src}, ["PTA005"])
    assert len(findings) == 1
    assert "__future__" in findings[0].message
    clean = "from __future__ import annotations\n\n\ndef f(x: int) -> int:\n    return x\n"
    _, findings = _run(tmp_path, {"paddle_tpu/api.py": clean}, ["PTA005"])
    assert findings == []


# -- suppression (noqa) -------------------------------------------------------

def test_noqa_parsing_and_filtering(tmp_path):
    src = """\
        import numpy as np

        def op(x):
            a = np.asarray(x)  # noqa: PTA002 -- semantically required
            b = np.asarray(x)  # noqa
            c = np.asarray(x)  # noqa: PTA001
            return a, b, c
    """
    project, findings = _run(tmp_path, {"paddle_tpu/ops/m.py": src},
                             ["PTA002"])
    kept, suppressed = filter_noqa(project, findings)
    assert len(suppressed) == 2      # targeted code + bare noqa
    assert len(kept) == 1            # wrong-code noqa does not suppress
    assert kept[0].line == 6


# -- PTA000 syntax errors -----------------------------------------------------

def test_syntax_error_reported_as_pta000(tmp_path):
    _, findings = _run(tmp_path, {"paddle_tpu/broken.py": "def f(:\n"},
                       ["PTA003"])
    assert len(findings) == 1 and findings[0].rule == "PTA000"


# -- baseline semantics -------------------------------------------------------

def test_baseline_add_expire_and_count_semantics(tmp_path):
    f1 = Finding("PTA002", "a.py", 3, 0, "m", anchor="x.numpy()")
    f2 = Finding("PTA002", "a.py", 9, 0, "m", anchor="x.numpy()")  # same fp
    f3 = Finding("PTA001", "b.py", 1, 0, "m", anchor="bool(x)")
    assert f1.fingerprint == f2.fingerprint != f3.fingerprint

    bl_path = str(tmp_path / "bl.json")
    write_baseline(bl_path, [f1, f3])
    baseline = load_baseline(bl_path)

    # same findings -> all baselined, nothing new or expired
    new, baselined, expired = split_findings([f1, f3], baseline)
    assert new == [] and len(baselined) == 2 and expired == []

    # a second occurrence of the same fingerprint is NEW (count=1 recorded)
    new, baselined, expired = split_findings([f1, f2, f3], baseline)
    assert new == [f2] and expired == []

    # a fixed finding expires its baseline entry
    new, baselined, expired = split_findings([f1], baseline)
    assert new == [] and expired == [f3.fingerprint]


def test_baseline_is_line_number_independent(tmp_path):
    a = Finding("PTA002", "a.py", 3, 0, "m", anchor="x.numpy()")
    moved = Finding("PTA002", "a.py", 30, 4, "m", anchor="x.numpy()")
    assert a.fingerprint == moved.fingerprint


# -- driver: exit codes, JSON schema, rule selection --------------------------

def test_driver_json_schema_and_exit_codes(tmp_path):
    (tmp_path / "paddle_tpu" / "ops").mkdir(parents=True)
    (tmp_path / "paddle_tpu" / "ops" / "m.py").write_text(
        "import numpy as np\n\n\ndef op(x):\n    return np.asarray(x)\n")

    proc = _driver(["--root", str(tmp_path), "--baseline", "none",
                    "--json", "paddle_tpu"])
    assert proc.returncode == 1, proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["version"] == 1
    assert set(payload["counts"]) == {"total", "new", "gating", "baselined",
                                      "suppressed",
                                      "expired_baseline_entries"}
    assert payload["counts"]["new"] >= 1
    for f in payload["findings"]:
        assert set(f) == {"rule", "path", "line", "col", "message",
                          "severity", "fingerprint", "status"}
        assert f["severity"] in ("error", "warning")

    # write a baseline, then the same tree is clean (exit 0)
    proc = _driver(["--root", str(tmp_path), "--baseline", "bl.json",
                    "--write-baseline", "paddle_tpu"])
    assert proc.returncode == 0, proc.stderr
    proc = _driver(["--root", str(tmp_path), "--baseline", "bl.json",
                    "paddle_tpu"])
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_driver_rule_selection(tmp_path):
    (tmp_path / "paddle_tpu" / "ops").mkdir(parents=True)
    (tmp_path / "paddle_tpu" / "ops" / "m.py").write_text(
        "import numpy as np\n\n\ndef op(x):\n    return np.asarray(x)\n")
    proc = _driver(["--root", str(tmp_path), "--baseline", "none",
                    "--rule", "PTA003", "--json", "paddle_tpu"])
    assert proc.returncode == 0  # PTA002 finding filtered out
    assert json.loads(proc.stdout)["rules"] == ["PTA003"]

    proc = _driver(["--root", str(tmp_path), "--baseline", "none",
                    "--rule", "PTA999", "paddle_tpu"])
    assert proc.returncode != 0 and "unknown rule" in proc.stderr


def test_all_rules_have_distinct_codes():
    codes = [r.code for r in ALL_RULES]
    assert len(codes) == len(set(codes)) == 14
    assert codes == sorted(codes)


def test_trace_tier_rules_are_not_in_the_default_selection():
    """PTA009/PTA010/PTA012/PTA014 compile registered entrypoints —
    they must only run when named explicitly via --only/--rule."""
    import argparse

    from tools.analyze.__main__ import select_rules

    ns = argparse.Namespace(only=None, skip=[])
    default_codes = {r.code for r in select_rules(ns)}
    assert "PTA008" in default_codes
    assert "PTA009" not in default_codes
    assert "PTA010" not in default_codes
    assert "PTA012" not in default_codes
    assert "PTA014" not in default_codes
    assert "PTA011" in default_codes   # the SPMD lint is AST-tier
    assert "PTA013" in default_codes   # the Pallas lint is AST-tier
    for r in ALL_RULES:
        assert r.tier in ("ast", "trace"), r.code
        assert (r.tier == "trace") == (r.code in ("PTA009", "PTA010",
                                                  "PTA012", "PTA014"))

    ns = argparse.Namespace(only=["PTA009,PTA010"], skip=["PTA010"])
    assert [r.code for r in select_rules(ns)] == ["PTA009"]


def test_only_flag_comma_and_repeat_forms(tmp_path):
    (tmp_path / "paddle_tpu" / "ops").mkdir(parents=True)
    (tmp_path / "paddle_tpu" / "ops" / "m.py").write_text(
        "import numpy as np\n\n\ndef op(x):\n    return np.asarray(x)\n")
    proc = _driver(["--root", str(tmp_path), "--baseline", "none",
                    "--only", "PTA002,PTA003", "--json", "paddle_tpu"])
    assert json.loads(proc.stdout)["rules"] == ["PTA002", "PTA003"]
    proc = _driver(["--root", str(tmp_path), "--baseline", "none",
                    "--only", "PTA002", "--skip", "PTA002", "--json",
                    "paddle_tpu"])
    assert proc.returncode == 0
    assert json.loads(proc.stdout)["rules"] == []


# -- acceptance gates ---------------------------------------------------------

def test_repo_wide_run_is_clean_against_checked_in_baseline():
    proc = _driver(["paddle_tpu"])
    assert proc.returncode == 0, proc.stdout + proc.stderr


SEEDED = """\
import jax


@jax.jit
def _seeded_entry(x):
    return _seeded_helper(x)


def _seeded_helper(x):
    if x.item():
        return x
    return x
"""


def test_seeded_tracer_leak_in_scratch_copy_fails_the_gate(tmp_path):
    """Copy the tree, seed `if x.item():` into a jit-reachable function,
    and check the gate fails with PTA001 at exactly that file:line."""
    scratch = tmp_path / "scratch"
    shutil.copytree(os.path.join(REPO, "paddle_tpu"),
                    str(scratch / "paddle_tpu"),
                    ignore=shutil.ignore_patterns("__pycache__"))
    (scratch / "tools" / "analyze").mkdir(parents=True)
    for rel in ("tools/op_catalog.txt", "tools/op_coverage.py",
                "tools/analyze/baseline.json"):
        shutil.copy(os.path.join(REPO, rel), str(scratch / rel))
    (scratch / "paddle_tpu" / "_seeded_check.py").write_text(SEEDED)

    proc = _driver(["--root", str(scratch), "--json", "paddle_tpu"])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    new = [f for f in payload["findings"] if f["status"] == "new"]
    seeded = [f for f in new if f["path"] == "paddle_tpu/_seeded_check.py"]
    assert len(seeded) == 1, new
    assert seeded[0]["rule"] == "PTA001"
    assert seeded[0]["line"] == 10  # the `if x.item():` line
    # the seed can also pull existing methods named `item` into the
    # reachable set (name-based over-approximation); nothing else may leak
    assert all(f["rule"] == "PTA001" for f in new), new


# -- PTA008 recompile risk ----------------------------------------------------

SHAPE_BRANCH = """\
    import jax

    @jax.jit
    def entry(x):
        if x.shape[0] > 8:
            return x * 2
        return x

    def helper(d):
        # rank dispatch in a shared helper is deliberate — not flagged
        if d.ndim == 3:
            return d[0]
        return d
"""


def test_pta008_flags_shape_branch_in_jit_entry_only(tmp_path):
    _, fs = _run(tmp_path, {"paddle_tpu/m.py": SHAPE_BRANCH}, ["PTA008"])
    assert len(fs) == 1
    assert fs[0].severity == "warning"
    assert "x.shape" in fs[0].message and "entry" in fs[0].message


SHAPE_WHILE = """\
    import jax

    @jax.jit
    def entry(x):
        return helper(x)

    def helper(x):
        while x.shape[0] > 1:
            x = x[::2]
        return x
"""


def test_pta008_while_on_shape_is_an_error_anywhere_reachable(tmp_path):
    _, fs = _run(tmp_path, {"paddle_tpu/m.py": SHAPE_WHILE}, ["PTA008"])
    assert len(fs) == 1
    assert fs[0].severity == "error"
    assert "unrolls at trace time" in fs[0].message


JIT_IN_LOOP = """\
    import jax

    def sweep(fns, x):
        outs = []
        for f in fns:
            outs.append(jax.jit(f)(x))
        return outs

    def fallback(f, x):
        while True:  # single-pass "try" idiom — not flagged
            g = jax.jit(f)
            break
        return g(x)
"""


def test_pta008_jit_in_loop_error_but_single_pass_idiom_ok(tmp_path):
    _, fs = _run(tmp_path, {"paddle_tpu/m.py": JIT_IN_LOOP}, ["PTA008"])
    assert len(fs) == 1
    assert fs[0].severity == "error"
    assert "fresh traced function every iteration" in fs[0].message
    assert fs[0].line == 6


STATIC_ARGS = """\
    import jax

    def make(f, n):
        return jax.jit(f, static_argnums=tuple(range(n)))  # computed

    g = jax.jit(lambda x, cfg: x, static_argnums=(1,))


    def call():
        return g(1.0, {"k": 2})  # unhashable dict in a static slot
"""


def test_pta008_static_argnums_hygiene(tmp_path):
    _, fs = _run(tmp_path, {"paddle_tpu/m.py": STATIC_ARGS}, ["PTA008"])
    assert len(fs) == 2
    assert all(f.severity == "error" for f in fs)
    msgs = " | ".join(f.message for f in fs)
    assert "computed static_argnums" in msgs
    assert "unhashable dict" in msgs


SCALAR_FEED = """\
    import jax

    @jax.jit
    def step(tok):
        return tok + 1

    def decode_loop(tok, n):
        for _ in range(n):
            tok = step(tok)
            cur = int(tok.item())  # device sync every token
        return cur

    def config_loop(cfgs, x):
        for c in cfgs:
            x = step(x)
            scale = float(c)  # host float of a python config — fine
        return x, scale
"""


def test_pta008_scalar_feed_loop_flags_item_not_config_floats(tmp_path):
    _, fs = _run(tmp_path, {"paddle_tpu/m.py": SCALAR_FEED}, ["PTA008"])
    assert len(fs) == 1
    assert fs[0].severity == "warning"
    assert ".item()" in fs[0].message or "int()" in fs[0].message
    assert fs[0].line == 10


def test_pta008_repo_run_is_clean():
    proc = _driver(["--only", "PTA008", "--strict", "--baseline", "none",
                    "paddle_tpu", "tools"])
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- noqa justification policing (PTA005) -------------------------------------

NOQA_HOT = """\
    def f(x):
        a = x.numpy()  # noqa: PTA002 -- boundary: converting for host metrics
        b = x.numpy()  # noqa: PTA002
        c = x.numpy()  # noqa
        return a, b, c
"""


def test_pta005_requires_justified_noqa_in_hot_paths(tmp_path):
    _, fs = _run(tmp_path, {"paddle_tpu/ops/m.py": NOQA_HOT},
                 ["PTA005"])
    project = _mini(tmp_path, {"paddle_tpu/ops/m.py": NOQA_HOT})
    findings = run_rules(project, [RULES["PTA005"]])
    kept, suppressed = filter_noqa(project, findings)
    # line 2 is justified; line 3 (bare code) and line 4 (blanket) are
    # PTA005 findings that the noqa comments themselves cannot suppress
    assert len(kept) == 2, [f.message for f in kept]
    assert {f.line for f in kept} == {3, 4}
    assert all(f.rule == "PTA005" for f in kept)


def test_pta005_noqa_policing_only_in_hot_prefixes(tmp_path):
    project = _mini(tmp_path, {"paddle_tpu/utils/m.py": NOQA_HOT})
    findings = run_rules(project, [RULES["PTA005"]])
    kept, _ = filter_noqa(project, findings)
    assert kept == []
