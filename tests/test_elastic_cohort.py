"""Chaos end-to-end for cohort re-formation (docs/fault_tolerance.md,
"Surviving host loss").

A real 2-process CPU training job (``launch --elastic --step_deadline``,
DataParallel over the 2-device global mesh, per-epoch checkpoints through
TrainEpochRange) loses a host mid-step:

* ``collective_hang:3:hang`` wedges rank 0 inside its 3rd guarded step —
  the in-process stand-in for "my peer was SIGKILLed mid-allreduce". Rank 1
  then blocks inside a *real* collective (its dp gradient allreduce needs
  both processes), so its watchdog converts a genuinely hung XLA collective
  into exit 121 within the configured deadline.
* The cohort supervisor treats the 121s as one host-loss event: tears down
  the whole generation, bumps ``PADDLE_TPU_COHORT_GEN``, respawns, and the
  new generation restores from the newest committed multi-host checkpoint.
* Acceptance: the resumed run's final model state is **bit-identical** to
  an uninterrupted run at the same world size.

The shrink variant hard-kills rank 1 (``host_kill:3:crash``) under
``--shrink_on_loss``: generation 1 is a 1-process world whose restore
re-shards the 2-host checkpoint onto the smaller world.

Unit-level semantics (heartbeat, watchdog, supervisor state machine) live
in tests/test_elastic_runtime.py; this file is the end-to-end proof.
"""
import glob
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Guarded-step deadline for the e2e: must clear the WORST honest epoch
# (first-epoch XLA compile + checkpoint commit can take tens of seconds on
# a loaded CI box) while staying far under the 3600s injected hang, so a
# firing is unambiguous evidence of the hang, never of a slow compile.
DEADLINE_S = 30.0

# 6 epochs, committed every epoch. The chaos fires on the 3rd guarded
# epoch (index 2), so epochs 0-1 are committed when the world wedges and
# the resumed generation re-runs epochs 2-5 exactly.
TRAIN_SCRIPT = """
    import json, os, sys
    ckpt_dir, out_dir = sys.argv[1], sys.argv[2]
    nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    rank = os.environ.get("PADDLE_TRAINER_ID", "0")
    gen = os.environ.get("PADDLE_TPU_COHORT_GEN", "0")
    chaos = os.environ.get("TEST_COHORT_CHAOS", "")
    if chaos and gen == "0":
        spec = {"hang": {"0": "collective_hang:3:hang"},
                "kill": {"1": "host_kill:3:crash",
                         "0": "collective_hang:3:hang"}}[chaos].get(rank)
        if spec:
            os.environ["PADDLE_TPU_FAULT_SPEC"] = spec
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={2 // nprocs}")
    os.environ.pop("JAX_PLATFORMS", None)
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, REPO)
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    import paddle_tpu.optimizer as optim
    from paddle_tpu.incubate.checkpoint import TrainEpochRange

    dist.init_parallel_env()
    world = dist.get_world_size()
    assert jax.device_count() == 2, jax.device_count()
    dist.set_mesh(dist.build_mesh({"dp": 2}))

    paddle.seed(42)
    net = paddle.nn.Sequential(
        paddle.nn.Linear(8, 16), paddle.nn.ReLU(),
        paddle.nn.Linear(16, 4))
    net = dist.DataParallel(net)
    opt = optim.Momentum(learning_rate=0.1, momentum=0.9,
                         parameters=net.parameters())
    ce = paddle.nn.CrossEntropyLoss()

    rng = np.random.RandomState(7)           # same global data everywhere
    X = rng.randn(6, 8, 8).astype(np.float32)
    Y = rng.randint(0, 4, (6, 8)).astype(np.int64)

    r = TrainEpochRange(6, "job_cohort", model=net, optimizer=opt,
                        checkpoint_path=ckpt_dir, keep_last=16)
    losses = []
    for epoch in r:
        if world > 1:
            lo = int(rank) * (8 // world)
            xb = dist.build_global_batch(X[epoch, lo:lo + 8 // world])
            yb = dist.build_global_batch(Y[epoch, lo:lo + 8 // world])
        else:
            xb = dist.shard_batch(paddle.to_tensor(X[epoch]))
            yb = dist.shard_batch(paddle.to_tensor(Y[epoch]))
        loss = ce(net(xb), yb)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(
            loss._data if hasattr(loss, "_data") else loss)))
    print("COHORT_LOSSES " + json.dumps(losses), flush=True)
    state = {k: np.asarray(v.numpy()) for k, v in net.state_dict().items()}
    np.savez(os.path.join(out_dir, f"state_g{gen}_r{rank}.npz"), **state)
    print(f"TRAIN DONE gen={gen} world={world} "
          f"restored={r.restored_epoch}", flush=True)
"""


def _write_script(tmp_path):
    p = tmp_path / "cohort_train.py"
    p.write_text("REPO = " + repr(REPO) + "\n"
                 + textwrap.dedent(TRAIN_SCRIPT))
    return str(p)


def _launch(script, ckpt_dir, out_dir, log_dir, start_port, chaos="",
            extra_args=(), timeout=600):
    env = {k: v for k, v in os.environ.items()
           if k not in ("PADDLE_TPU_FAULT_SPEC", "TEST_COHORT_CHAOS",
                        "PADDLE_TPU_COHORT_GEN")}
    if chaos:
        env["TEST_COHORT_CHAOS"] = chaos
    env["PADDLE_TPU_RESTART_BACKOFF"] = "0.05"
    os.makedirs(out_dir, exist_ok=True)
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--start_port", str(start_port),
         "--log_dir", log_dir, "--elastic",
         "--step_deadline", str(DEADLINE_S),
         "--grace_period", "8", *extra_args, script,
         str(ckpt_dir), str(out_dir)],
        cwd=REPO, capture_output=True, text=True, timeout=timeout, env=env)


def _workerlogs(log_dir, n=2):
    out = {}
    for rank in range(n):
        p = os.path.join(log_dir, f"workerlog.{rank}")
        out[rank] = open(p).read() if os.path.exists(p) else "(none)"
    return out


def _losses(text):
    got = None
    for line in text.splitlines():
        if line.startswith("COHORT_LOSSES "):
            got = json.loads(line[len("COHORT_LOSSES "):])
    return got


def _state(out_dir, gen, rank):
    path = os.path.join(out_dir, f"state_g{gen}_r{rank}.npz")
    assert os.path.exists(path), sorted(os.listdir(out_dir))
    with np.load(path) as z:
        return {k: z[k] for k in z.files}


@pytest.mark.slow
@pytest.mark.timeout_s(900)
def test_host_loss_watchdog_reform_bit_identical(tmp_path):
    script = _write_script(tmp_path)

    # uninterrupted reference at the same world size
    clean = _launch(script, tmp_path / "ckpt_clean", tmp_path / "out_clean",
                    str(tmp_path / "logs_clean"), start_port=12731)
    clean_logs = _workerlogs(str(tmp_path / "logs_clean"))
    assert clean.returncode == 0, (clean.stderr[-3000:], clean_logs)
    # the reference must be genuinely uninterrupted — a reform here means
    # the deadline is tighter than an honest epoch on this machine
    assert "re-forming" not in clean.stderr, clean.stderr[-3000:]
    ref_losses = _losses(clean_logs[0])
    assert ref_losses is not None and len(ref_losses) == 6

    # chaos run: rank 0's 3rd guarded step hangs "mid-allreduce"; rank 1
    # wedges inside the real dp collective and its watchdog must fire
    chaos = _launch(script, tmp_path / "ckpt", tmp_path / "out",
                    str(tmp_path / "logs"), start_port=12741, chaos="hang")
    logs = _workerlogs(str(tmp_path / "logs"))
    assert chaos.returncode == 0, (chaos.stderr[-3000:], logs)

    # the supervisor re-formed exactly once, on the host-lost exit code
    assert "re-forming" in chaos.stderr, chaos.stderr[-3000:]
    assert "generation 1 up" in chaos.stderr
    assert "TRAIN DONE gen=1 world=2" in logs[0], logs[0][-1500:]
    # the resumed generation restored the last committed epoch, it did not
    # retrain from scratch
    assert "restored=1" in logs[0]

    # the watchdog's terminal path dumped a flight record before exit 121
    dumps = glob.glob(os.path.join(str(tmp_path / "logs"),
                                   "flight_*.jsonl"))
    assert dumps, "no watchdog flight dump landed in the log dir"
    header = json.loads(open(dumps[0]).readline())
    assert header["schema"] == "paddle-tpu-flight/2"
    assert header["process_count"] == 2
    fired = [json.loads(line) for d in dumps for line in open(d)
             if '"distributed.watchdog_fired"' in line]
    assert fired and all(f["elapsed_s"] >= DEADLINE_S for f in fired)

    # the acceptance bar: bit-identical final state vs the clean run
    for rank in ("0", "1"):
        got = _state(str(tmp_path / "out"), 1, rank)
        want = _state(str(tmp_path / "out_clean"), 0, rank)
        assert sorted(got) == sorted(want)
        for k in want:
            np.testing.assert_array_equal(
                got[k], want[k],
                err_msg=f"rank {rank} param {k} diverged after reform")
    # and the resumed loss curve is the clean curve's tail
    resumed = _losses(logs[0])
    np.testing.assert_allclose(resumed, ref_losses[-len(resumed):],
                               rtol=1e-6, atol=1e-7)


@pytest.mark.slow
@pytest.mark.timeout_s(900)
def test_shrink_to_fit_reforms_smaller_world(tmp_path):
    script = _write_script(tmp_path)
    res = _launch(script, tmp_path / "ckpt", tmp_path / "out",
                  str(tmp_path / "logs"), start_port=12751, chaos="kill",
                  extra_args=("--shrink_on_loss",))
    logs = _workerlogs(str(tmp_path / "logs"))
    assert res.returncode == 0, (res.stderr[-3000:], logs)
    assert "shrink-to-fit" in res.stderr, res.stderr[-3000:]
    # generation 1 is a 1-process world: the 2-host checkpoint re-sharded
    # onto it, training resumed from the last committed epoch
    assert "TRAIN DONE gen=1 world=1" in logs[0], logs[0][-1500:]
    assert "restored=1" in logs[0]
    state = _state(str(tmp_path / "out"), 1, "0")
    assert state  # the re-sharded restore produced a full state dict
    # the resumed generation ran exactly the un-committed epochs (2..5)
    # and stayed numerically sane through the re-sharded restore
    losses = _losses(logs[0])
    assert losses is not None and len(losses) == 4
    assert all(np.isfinite(losses))
