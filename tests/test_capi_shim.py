"""End-to-end C API test: export an artifact, build the shim + the C
smoke driver, run the driver as a plain native binary (no Python on its
command line), and compare its printed outputs against the Python
Predictor (reference parity: capi_exp + go/paddle/predictor.go usage)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.inference import Config, create_predictor
from paddle_tpu.inference.capi import build_capi, header_path
from paddle_tpu.static import InputSpec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _export(tmp_path, n, d):
    paddle.seed(3)

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(d, 3)

        def forward(self, x):
            return nn.functional.softmax(self.fc(x), axis=-1)

    net = Net()
    prefix = str(tmp_path / "model")
    paddle.jit.save(net, prefix, input_spec=[InputSpec([n, d], "float32", "x")])
    return prefix


@pytest.mark.slow
@pytest.mark.timeout_s(300)
def test_c_driver_matches_python(tmp_path):
    n, d = 2, 4
    prefix = _export(tmp_path, n, d)

    so = build_capi()
    exe = str(tmp_path / "capi_smoke")
    subprocess.run(
        ["gcc", "-O2", os.path.join(REPO, "csrc", "capi_smoke.c"),
         "-I", os.path.dirname(header_path()), "-o", exe,
         so],
        check=True, capture_output=True, text=True)

    env = {**os.environ,
           "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
           "PTC_FORCE_CPU": "1"}
    r = subprocess.run([exe, prefix, str(n), str(d)], capture_output=True,
                       text=True, timeout=240, env=env)
    assert r.returncode == 0, (r.stdout, r.stderr)
    lines = r.stdout.strip().splitlines()
    assert "n_inputs 1" in lines[0]
    assert "rerun ok" in r.stdout and "done" in r.stdout
    assert "prerun guard ok" in r.stdout and "bounds guard ok" in r.stdout

    # parse the printed output tensor
    data_line = next(l for l in lines if l.startswith("data"))
    got = np.array([float(v) for v in data_line.split()[1:]],
                   np.float32).reshape(n, 3)

    # python-side reference on the same deterministic input
    x = ((np.arange(n * d) % 7) * 0.25 - 0.5).astype(np.float32).reshape(n, d)
    pred = create_predictor(Config(prefix))
    ref = pred.run([x])[0]
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
