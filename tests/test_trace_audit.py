"""Tier-1 tests for the trace-level audit (tools/analyze/trace +
PTA009/PTA010).

Three layers:

- pure passes against hand-built jaxprs/HLO text (no registry, fast);
- seeded :class:`AuditSpec` fixtures proving each trace check fires on
  its bug class (retrace, host transfer, captured large constant, missed
  donation) and stays quiet on the corrected program;
- the acceptance negatives: the repo's REGISTERED entrypoints — the
  PR-6 static decode step, serving predict, the donated-buffer Executor
  train step — audit clean with exactly one trace each.
"""
import json
import os
import subprocess
import sys

import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import jax                                              # noqa: E402
import jax.numpy as jnp                                 # noqa: E402

from paddle_tpu.core.audit import (AuditSpec,           # noqa: E402
                                   load_default_entrypoints)
from tools.analyze import trace as trace_mod            # noqa: E402
from tools.analyze.trace import (EntrypointStats,       # noqa: E402
                                 TraceReport, audit_spec, passes,
                                 run_audit)
from tools.analyze.rules.pta009_trace_fusion import (   # noqa: E402
    RULE as PTA009)
from tools.analyze.rules.pta010_retrace_sentinel import (  # noqa: E402
    RULE as PTA010)


# -- pure passes --------------------------------------------------------------

HLO_SNIPPET = """\
HloModule jit_step

%fused_computation (param_0: f32[4,2]) -> f32[4,2] {
  %param_0 = f32[4,2]{1,0} parameter(0)
  ROOT %multiply.1 = f32[4,2]{1,0} multiply(%param_0, %param_0)
}

ENTRY %main (p0: f32[4,2]) -> f32[4,2] {
  %p0 = f32[4,2]{1,0} parameter(0)
  %copy.2 = f32[4,2]{0,1} copy(%p0)
  %fusion.1 = f32[4,2]{1,0} fusion(%copy.2), kind=kLoop
  %custom-call.3 = f32[4,2]{1,0} custom-call(%fusion.1), custom_call_target="x"
  ROOT %copy.4 = f32[4,2]{1,0} copy(%custom-call.3)
}
"""


def test_parse_hlo_stats_counts_opcodes():
    stats = passes.parse_hlo_stats(HLO_SNIPPET)
    assert stats["copies"] == 2
    assert stats["fusions"] == 1
    assert stats["custom_calls"] == 1
    # parameter(...) lines count as instructions too
    assert stats["instructions"] >= 6
    assert stats["host_transfers"] == 0


def test_scan_transfers_sees_device_put_and_callbacks():
    def with_dp(x):
        return jax.device_put(x) + 1.0

    def with_cb(x):
        y = jax.pure_callback(
            lambda a: a, jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return y + 1.0

    def clean(x):
        return x * 2.0 + 1.0

    x = jnp.zeros((3,))
    assert passes.scan_transfers(jax.make_jaxpr(with_dp)(x)) \
        == ["device_put"]
    assert "pure_callback" in passes.scan_transfers(
        jax.make_jaxpr(with_cb)(x))
    assert passes.scan_transfers(jax.make_jaxpr(clean)(x)) == []


def test_scan_large_consts_flags_captured_tensor_in_loop_body():
    big = jnp.ones((200, 200))  # 40000 elements > 16384 threshold

    def leaky(x):
        def body(c, _):
            return c + big.sum(), None
        out, _ = jax.lax.scan(body, x, None, length=3)
        return out

    def fixed(x, table):
        def body(c, _):
            return c + table.sum(), None
        out, _ = jax.lax.scan(body, x, None, length=3)
        return out

    hits = passes.scan_large_consts(jax.make_jaxpr(leaky)(jnp.asarray(0.)))
    assert len(hits) == 1
    assert hits[0]["control_flow"] == "scan"
    assert hits[0]["elements"] == 40000
    # same table passed as an ARGUMENT is not a captured const
    assert passes.scan_large_consts(
        jax.make_jaxpr(fixed)(jnp.asarray(0.), big)) == []


def test_donation_opportunities_matches_in_out_avals():
    def train_ish(params, x):
        g = x.sum()
        return [p - 0.1 * g for p in params]

    closed = jax.make_jaxpr(train_ish)(
        [jnp.zeros((4, 4)), jnp.zeros((4,))], jnp.ones((8,)))
    don = passes.donation_opportunities(closed)
    assert don["donatable_inputs"] == 2
    assert don["total_inputs"] == 3
    assert don["donatable_bytes"] == (16 + 4) * 4


# -- seeded AuditSpec fixtures -----------------------------------------------

def test_retrace_fixture_fires_and_stable_spec_does_not():
    # BUG under test: the arg shape depends on the variant, so the second
    # call misses the jit cache — the class of bug PR 6 fixed by hand
    leaky = AuditSpec(
        fn=lambda x: x * 2.0,
        make_args=lambda v: (jnp.zeros((4 + v, 3), jnp.float32),))
    st = audit_spec("retrace_fixture", leaky)
    assert st.error == ""
    assert st.trace_count == 2
    assert not st.fingerprint_stable

    stable = AuditSpec(
        fn=lambda x: x * 2.0,
        make_args=lambda v: (jnp.full((4, 3), float(v), jnp.float32),))
    st = audit_spec("stable_fixture", stable)
    assert st.error == ""
    assert st.trace_count == 1
    assert st.fingerprint_stable
    assert st.hlo["instructions"] > 0


def test_host_transfer_fixture_recorded_in_stats():
    spec = AuditSpec(
        fn=lambda x: jax.device_put(x) + 1.0,
        make_args=lambda v: (jnp.full((3,), float(v)),))
    st = audit_spec("transfer_fixture", spec)
    assert st.error == ""
    assert st.transfers == ["device_put"]


def test_donation_check_only_runs_for_undonated_train_specs():
    def step(params, x):
        return [p - 0.1 * x.sum() for p in params]

    def make_args(v):
        return ([jnp.zeros((4, 4)), jnp.zeros((4,))],
                jnp.full((8,), float(v)))

    undonated = audit_spec("train_fixture",
                           AuditSpec(fn=step, make_args=make_args),
                           tags=("train",))
    assert undonated.donation["donatable_inputs"] == 2
    donated = audit_spec(
        "train_fixture_donated",
        AuditSpec(fn=step, make_args=make_args,
                  jit_kwargs={"donate_argnums": (0,)}),
        tags=("train",))
    assert donated.donation is None
    untagged = audit_spec("infer_fixture",
                          AuditSpec(fn=step, make_args=make_args))
    assert untagged.donation is None


def test_broken_factory_is_reported_not_raised():
    class _Exploding:
        name = "boom"
        tags = ()
        path = "paddle_tpu/x.py"
        line = 1

        def build(self):
            raise RuntimeError("factory exploded")

    st = trace_mod.audit_entrypoint("boom", _Exploding())
    assert "factory exploded" in st.error
    assert st.trace_count == -1


# -- rule synthesis: stats -> findings ----------------------------------------

def _report_with(**overrides):
    st = EntrypointStats(name="ep", tags=("train",),
                         path="paddle_tpu/x.py", line=7)
    st.trace_count = 1
    st.fingerprints = ["aa", "aa"]
    for k, v in overrides.items():
        setattr(st, k, v)
    return TraceReport(platform="cpu", entrypoint_stats={"ep": st})


def _findings(rule, report, monkeypatch):
    monkeypatch.setattr(trace_mod, "_LAST", report)
    return rule.finalize(None)


def test_pta010_findings_from_stats(monkeypatch):
    fs = _findings(PTA010, _report_with(trace_count=3), monkeypatch)
    assert len(fs) == 1 and fs[0].severity == "error"
    assert "traced 3x" in fs[0].message
    assert fs[0].path == "paddle_tpu/x.py" and fs[0].line == 7
    assert fs[0].anchor == "trace:ep:retrace"

    fs = _findings(PTA010, _report_with(fingerprints=["aa", "bb"],
                                        fingerprint_stable=False),
                   monkeypatch)
    assert len(fs) == 1 and "different programs" in fs[0].message

    assert _findings(PTA010, _report_with(), monkeypatch) == []


def test_pta009_findings_from_stats(monkeypatch):
    fs = _findings(PTA009, _report_with(transfers=["device_put"] * 2),
                   monkeypatch)
    assert len(fs) == 1 and fs[0].severity == "error"
    assert "2 `device_put`" in fs[0].message

    fs = _findings(
        PTA009,
        _report_with(donation={"donatable_inputs": 4, "total_inputs": 6,
                               "donatable_bytes": 2 << 20}),
        monkeypatch)
    assert len(fs) == 1 and fs[0].severity == "warning"
    assert "donate_argnums" in fs[0].message

    fs = _findings(
        PTA009,
        _report_with(hlo={"instructions": 100, "copies": 30,
                          "fusions": 5, "custom_calls": 0,
                          "host_transfers": 0}),
        monkeypatch)
    assert len(fs) == 1 and fs[0].severity == "warning"
    assert "splitting fusions" in fs[0].message
    # below the 20% ratio or the 50-instruction floor: quiet
    assert _findings(
        PTA009,
        _report_with(hlo={"instructions": 100, "copies": 10}),
        monkeypatch) == []
    assert _findings(
        PTA009,
        _report_with(hlo={"instructions": 20, "copies": 19}),
        monkeypatch) == []

    fs = _findings(
        PTA009,
        _report_with(large_consts=[{"control_flow": "while",
                                    "elements": 65536,
                                    "dtype": "float32",
                                    "shape": [256, 256]}]),
        monkeypatch)
    assert len(fs) == 1 and "65536 elements" in fs[0].message

    assert _findings(PTA009, _report_with(), monkeypatch) == []


def test_rules_surface_runner_import_failure(monkeypatch):
    broken = TraceReport(platform="unavailable", entrypoint_stats={},
                         error="Traceback ...\nModuleNotFoundError: jax")
    for rule in (PTA009, PTA010):
        fs = _findings(rule, broken, monkeypatch)
        assert len(fs) == 1 and fs[0].severity == "error"
        assert "ModuleNotFoundError: jax" in fs[0].message


# -- acceptance: the registered entrypoints audit clean -----------------------

ACCEPTANCE_ENTRYPOINTS = ("llm_decode_step", "serving_predict",
                          "executor_train_step")


def test_default_registry_names_and_sites():
    eps = load_default_entrypoints()
    assert set(ACCEPTANCE_ENTRYPOINTS) <= set(eps)
    assert {"hapi_train_step", "llm_prefill"} <= set(eps)
    for ep in eps.values():
        assert ep.path.startswith("paddle_tpu/"), ep
        assert ep.line > 0


def test_registered_entrypoints_trace_once_and_stay_on_device():
    report = run_audit(names=list(ACCEPTANCE_ENTRYPOINTS))
    assert report.error == ""
    assert set(report.entrypoint_stats) == set(ACCEPTANCE_ENTRYPOINTS)
    for name, st in report.entrypoint_stats.items():
        assert st.error == "", f"{name}: {st.error}"
        assert st.trace_count == 1, \
            f"{name} traced {st.trace_count}x — jit cache key unstable"
        assert st.fingerprint_stable, name
        assert st.transfers == [], name
        assert st.large_consts == [], name
        assert st.hlo["instructions"] > 0, name
    payload = report.stats_payload()
    assert payload["version"] == 1
    assert json.dumps(payload)  # must serialize as-is for --trace-report


@pytest.mark.slow
def test_driver_trace_tier_end_to_end(tmp_path):
    """`--only PTA009,PTA010 --trace-report` over the real repo: exits 0
    and writes a payload covering every registered entrypoint."""
    out = tmp_path / "trace_audit.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.analyze", "--strict",
         "--only", "PTA009,PTA010", "--trace-report", str(out),
         "paddle_tpu"],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(out.read_text())
    assert set(payload["entrypoints"]) >= set(ACCEPTANCE_ENTRYPOINTS)
    for name, st in payload["entrypoints"].items():
        assert st["trace_count"] == 1, (name, st)
