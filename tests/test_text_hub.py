"""Text datasets (synthetic-archive fixtures) + hub + download utils
(reference: python/paddle/text/datasets/, hapi/hub.py,
utils/download.py)."""
import io
import os
import tarfile
import zipfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.text import (Imdb, Imikolov, Movielens, UCIHousing,
                             Conll05st, WMT16)
from paddle_tpu.hapi import hub
from paddle_tpu.utils.download import DownloadError, _md5check


def _add_text(tf, name, text):
    data = text.encode()
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tf.addfile(info, io.BytesIO(data))


class TestUCIHousing:
    def test_split_and_normalize(self, tmp_path):
        rng = np.random.RandomState(0)
        table = rng.rand(50, 14) * 10
        f = tmp_path / "housing.data"
        np.savetxt(f, table)
        tr = UCIHousing(data_file=str(f), mode="train")
        te = UCIHousing(data_file=str(f), mode="test")
        assert len(tr) == 40 and len(te) == 10
        x, y = tr[0]
        assert x.shape == (13,) and y.shape == (1,)
        # features are normalized, targets are raw
        allx = np.stack([tr[i][0] for i in range(len(tr))])
        assert np.abs(allx).max() <= 1.0 + 1e-6


class TestImdb:
    def _make_archive(self, tmp_path):
        f = tmp_path / "aclImdb.tar.gz"
        texts = {"train/pos/0.txt": "good movie great fun " * 3,
                 "train/neg/0.txt": "bad movie awful bad " * 3,
                 "test/pos/0.txt": "great good",
                 "test/neg/0.txt": "awful bad"}
        with tarfile.open(f, "w:gz") as t:
            for name, txt in texts.items():
                _add_text(t, f"aclImdb/{name}", txt)
        return str(f)

    def test_vocab_and_labels(self, tmp_path):
        path = self._make_archive(tmp_path)
        ds = Imdb(data_file=path, mode="train", cutoff=1)
        assert "<unk>" in ds.word_idx
        assert len(ds) == 2
        docs = {tuple(d.tolist()): int(l[0]) for d, l in
                [ds[i] for i in range(len(ds))]}
        labels = sorted(docs.values())
        assert labels == [0, 1]
        te = Imdb(data_file=path, mode="test", cutoff=1)
        assert len(te) == 2


class TestImikolov:
    def _make_archive(self, tmp_path):
        f = tmp_path / "simple-examples.tgz"
        with tarfile.open(f, "w:gz") as t:
            _add_text(t, "./simple-examples/data/ptb.train.txt",
                      "the cat sat\nthe dog sat\n" * 5)
            _add_text(t, "./simple-examples/data/ptb.test.txt",
                      "the cat ran\n")
        return str(f)

    def test_ngram_and_seq(self, tmp_path):
        path = self._make_archive(tmp_path)
        ng = Imikolov(data_file=path, data_type="NGRAM", window_size=2,
                      mode="train", min_word_freq=1)
        assert len(ng) > 0
        assert ng[0].shape == (2,)
        seq = Imikolov(data_file=path, data_type="SEQ", mode="test",
                       min_word_freq=1)
        src, trg = seq[0]
        assert len(src) == len(trg)


class TestMovielens:
    def test_parse(self, tmp_path):
        f = tmp_path / "ml-1m.zip"
        with zipfile.ZipFile(f, "w") as z:
            z.writestr("ml-1m/movies.dat",
                       "1::Toy Story (1995)::Animation|Comedy\n"
                       "2::Jumanji (1995)::Adventure\n")
            z.writestr("ml-1m/users.dat",
                       "1::M::25::10::48067\n2::F::35::3::55117\n")
            z.writestr("ml-1m/ratings.dat",
                       "1::1::5::978300760\n2::2::3::978302109\n")
        ds = Movielens(data_file=str(f), mode="train", test_ratio=0.0)
        assert len(ds) == 2
        fields = ds[0]
        assert len(fields) == 8
        assert fields[-1].dtype == np.float32


class TestConll05:
    def test_two_column(self, tmp_path):
        f = tmp_path / "srl.txt"
        f.write_text("The -\ncat A0\nsat V\n\nDogs A0\nrun V\n")
        ds = Conll05st(data_file=str(f))
        assert len(ds) == 2
        wid, pred, lid = ds[0]
        assert wid.shape == (3,) and lid.shape == (3,)


class TestWMT16:
    def test_pairs(self, tmp_path):
        f = tmp_path / "wmt16.tar.gz"
        with tarfile.open(f, "w:gz") as t:
            _add_text(t, "wmt16/vocab_en", "hello\nworld\n")
            _add_text(t, "wmt16/vocab_de", "hallo\nwelt\n")
            _add_text(t, "wmt16/train", "hello world\thallo welt\n")
        ds = WMT16(data_file=str(f), mode="train", lang="en")
        src, trg, trg_next = ds[0]
        assert src.tolist() == [ds.src_dict["hello"], ds.src_dict["world"]]
        assert trg[0] == 0 and trg_next[-1] == 1  # BOS / EOS


class TestHub:
    def test_local_hubconf(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "def tiny(scale=1):\n"
            "    \"\"\"A tiny entrypoint.\"\"\"\n"
            "    return {'scale': scale}\n")
        assert "tiny" in hub.list(str(tmp_path), source="local")
        assert "tiny entrypoint" in hub.help(str(tmp_path), "tiny",
                                             source="local")
        assert hub.load(str(tmp_path), "tiny", source="local",
                        scale=3) == {"scale": 3}


class TestDownload:
    def test_md5check(self, tmp_path):
        f = tmp_path / "x.bin"
        f.write_bytes(b"hello")
        import hashlib
        good = hashlib.md5(b"hello").hexdigest()
        assert _md5check(str(f), good)
        assert not _md5check(str(f), "0" * 32)

    def test_no_network_raises_clear_error(self, tmp_path, monkeypatch):
        from paddle_tpu.utils import download as dl
        monkeypatch.setattr(dl, "WEIGHTS_HOME", str(tmp_path))
        with pytest.raises(DownloadError, match="egress"):
            dl.get_path_from_url("http://203.0.113.1/none.tgz")
