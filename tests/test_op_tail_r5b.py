"""Round-5 op tail, part 2: optimizer update rules vs numpy references
of the reference ops' documented math, RNN cells vs the torch CPU oracle
(identical gate conventions), sampling-op statistics, detection misc
ops, quantization observers, and layer-level wrappers. Complements
tests/test_op_tail_r5.py."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.ops as ops
import paddle_tpu.optimizer as optim


def _rng(seed=0):
    return np.random.RandomState(seed)


def T(a):
    return paddle.to_tensor(a)


# ---------------------------------------------------------------------------
# optimizers: K steps on a Linear vs a numpy simulation of the reference
# update formulas (adadelta_op.cc, adagrad_op.cc, adamax_op.cc,
# ftrl_op.cc, lars_momentum_op.cc)

def _drive_opt(opt_cls, np_step, steps=3, **kw):
    paddle.seed(0)
    lin = nn.Linear(4, 3)
    opt = opt_cls(parameters=lin.parameters(), **kw)
    x = _rng(1).randn(5, 4).astype(np.float32)
    c = _rng(2).randn(5, 3).astype(np.float32)
    w0 = lin.weight.numpy().copy()
    b0 = lin.bias.numpy().copy()
    # grads of sum(out * c): dW = x^T c (layout [in, out]), db = sum c
    gw = (x.T @ c).astype(np.float32)
    gb = c.sum(0).astype(np.float32)
    for _ in range(steps):
        out = lin(T(x))
        (out * T(c)).sum().backward()
        opt.step()
        opt.clear_grad()
    state_w, state_b = {}, {}
    for _ in range(steps):
        w0 = np_step(w0, gw, state_w)
        b0 = np_step(b0, gb, state_b)
    np.testing.assert_allclose(lin.weight.numpy(), w0, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(lin.bias.numpy(), b0, rtol=1e-4, atol=1e-5)


def test_adagrad_matches_reference_math():
    lr, eps = 0.1, 1e-6

    def step(p, g, s):
        s.setdefault("m", np.zeros_like(p))
        s["m"] = s["m"] + g * g
        return p - lr * g / (np.sqrt(s["m"]) + eps)
    _drive_opt(optim.Adagrad, step, learning_rate=lr)


def test_adadelta_matches_reference_math():
    lr, rho, eps = 1.0, 0.95, 1e-6

    def step(p, g, s):
        s.setdefault("ag", np.zeros_like(p))
        s.setdefault("au", np.zeros_like(p))
        s["ag"] = rho * s["ag"] + (1 - rho) * g * g
        upd = g * np.sqrt(s["au"] + eps) / np.sqrt(s["ag"] + eps)
        s["au"] = rho * s["au"] + (1 - rho) * upd * upd
        return p - lr * upd
    _drive_opt(optim.Adadelta, step, learning_rate=lr)


def test_adamax_matches_reference_math():
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8

    def step(p, g, s):
        s.setdefault("m", np.zeros_like(p))
        s.setdefault("u", np.zeros_like(p))
        s.setdefault("t", 0)
        s["t"] += 1
        s["m"] = b1 * s["m"] + (1 - b1) * g
        s["u"] = np.maximum(b2 * s["u"], np.abs(g))
        return p - (lr / (1 - b1 ** s["t"])) * s["m"] / (s["u"] + eps)
    _drive_opt(optim.Adamax, step, learning_rate=lr)


def test_ftrl_matches_reference_math():
    lr, l1, l2, lp = 0.1, 0.01, 0.01, -0.5

    def step(p, g, s):
        s.setdefault("sq", np.zeros_like(p))
        s.setdefault("lin", np.zeros_like(p))
        new_sq = s["sq"] + g * g
        sigma = (new_sq ** -lp - (s["sq"] + 1e-30) ** -lp) / lr
        s["lin"] = s["lin"] + g - sigma * p
        quad = new_sq ** -lp / lr + 2 * l2
        pre = np.clip(s["lin"], -l1, l1) - s["lin"]
        s["sq"] = new_sq
        return pre / quad
    _drive_opt(optim.Ftrl, step, learning_rate=lr, l1=l1, l2=l2)


def test_lars_momentum_matches_reference_math():
    lr, mu, coeff, wd = 0.1, 0.9, 1e-3, 5e-4

    def step(p, g, s):
        s.setdefault("v", np.zeros_like(p))
        wn = np.sqrt((p ** 2).sum())
        gn = np.sqrt((g ** 2).sum())
        local = (lr * coeff * wn / (gn + wd * wn)
                 if wn > 0 and gn > 0 else lr)
        s["v"] = mu * s["v"] + local * (g + wd * p)
        return p - s["v"]
    _drive_opt(optim.LarsMomentum, step, learning_rate=lr, momentum=mu,
               lars_coeff=coeff, lars_weight_decay=wd)


# ---------------------------------------------------------------------------
# RNN cells vs torch (identical i,f,g,o / r,z,n gate order)

def _copy_cell(ours, theirs):
    import torch
    theirs.weight_ih.data = torch.from_numpy(ours.weight_ih.numpy())
    theirs.weight_hh.data = torch.from_numpy(ours.weight_hh.numpy())
    theirs.bias_ih.data = torch.from_numpy(ours.bias_ih.numpy())
    theirs.bias_hh.data = torch.from_numpy(ours.bias_hh.numpy())


def test_gru_cell_matches_torch():
    import torch
    paddle.seed(3)
    cell = nn.GRUCell(6, 8)
    tcell = torch.nn.GRUCell(6, 8)
    _copy_cell(cell, tcell)
    x = _rng(4).randn(5, 6).astype(np.float32)
    h = _rng(5).randn(5, 8).astype(np.float32)
    out, _ = cell(T(x), T(h))
    ref = tcell(torch.from_numpy(x), torch.from_numpy(h)).detach().numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


def test_lstm_cell_matches_torch():
    import torch
    paddle.seed(6)
    cell = nn.LSTMCell(6, 8)
    tcell = torch.nn.LSTMCell(6, 8)
    _copy_cell(cell, tcell)
    x = _rng(7).randn(5, 6).astype(np.float32)
    h = _rng(8).randn(5, 8).astype(np.float32)
    c = _rng(9).randn(5, 8).astype(np.float32)
    out, (h2, c2) = cell(T(x), (T(h), T(c)))
    th, tc = tcell(torch.from_numpy(x),
                   (torch.from_numpy(h), torch.from_numpy(c)))
    np.testing.assert_allclose(h2.numpy(), th.detach().numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(c2.numpy(), tc.detach().numpy(),
                               rtol=1e-4, atol=1e-5)


def test_rnn_layer_scans_cell():
    paddle.seed(10)
    cell = nn.SimpleRNNCell(4, 6)
    layer = nn.RNN(cell)
    x = _rng(11).randn(2, 5, 4).astype(np.float32)
    out, last = layer(T(x))
    assert tuple(out.shape) == (2, 5, 6)
    # manual unroll through the same cell must agree
    h = None
    for t in range(5):
        o, h = cell(T(x[:, t]), h)
    np.testing.assert_allclose(out.numpy()[:, -1], o.numpy(), rtol=1e-5)


# ---------------------------------------------------------------------------
# sampling ops: distribution statistics

def test_bernoulli_multinomial_truncated_normal_stats():
    paddle.seed(12)
    p = np.full((20000,), 0.3, np.float32)
    draws = paddle.bernoulli(T(p)).numpy()
    assert set(np.unique(draws)) <= {0.0, 1.0}
    assert abs(draws.mean() - 0.3) < 0.02
    probs = np.array([0.2, 0.8], np.float32)
    s = paddle.multinomial(T(np.tile(probs, (1, 1))), num_samples=5000,
                           replacement=True).numpy()
    assert abs((s == 1).mean() - 0.8) < 0.03
    t = paddle.truncated_normal([20000], mean=1.0, std=2.0).numpy()
    # truncated at 2 std: all samples inside [-3, 5]
    assert t.min() >= -3.0 - 1e-3 and t.max() <= 5.0 + 1e-3
    assert abs(t.mean() - 1.0) < 0.1


# ---------------------------------------------------------------------------
# detection misc

def test_box_clip_and_decoder_assign():
    boxes = np.array([[-5.0, -5.0, 30.0, 40.0],
                      [2.0, 3.0, 8.0, 9.0]], np.float32)
    im_info = np.array([20.0, 25.0, 1.0], np.float32)  # h, w, scale
    got = paddle.box_clip(T(boxes), T(im_info)).numpy()
    # clip to [0, w-1] x [0, h-1] (box_clip_op.cc)
    np.testing.assert_allclose(got[0], [0, 0, 24, 19])
    np.testing.assert_allclose(got[1], [2, 3, 8, 9])

    prior = np.array([[0.0, 0.0, 10.0, 10.0]], np.float32)
    pvar = np.array([[0.1, 0.1, 0.2, 0.2]], np.float32)
    tgt = np.zeros((1, 8), np.float32)      # 2 classes x 4
    score = np.array([[0.2, 0.8]], np.float32)
    db, ab = paddle.box_decoder_and_assign(T(prior), T(pvar), T(tgt),
                                           T(score))
    assert db.shape == [1, 8] and ab.shape == [1, 4]
    # zero deltas decode back to the prior box; argmax class assigned
    np.testing.assert_allclose(ab.numpy()[0], db.numpy()[0, 4:], rtol=1e-5)


def test_density_prior_box_and_polygon_transform():
    x = np.zeros((1, 3, 2, 2), np.float32)
    img = np.zeros((1, 3, 16, 16), np.float32)
    boxes, vars_ = paddle.density_prior_box(
        T(x), T(img), densities=[2], fixed_sizes=[4.0], fixed_ratios=[1.0],
        steps=[8.0, 8.0])
    b = boxes.numpy()
    assert b.shape == (2, 2, 4, 4)      # H, W, densities^2, 4
    assert (b >= -0.5).all() and (b <= 1.5).all()
    v = vars_.numpy()
    np.testing.assert_allclose(v[0, 0, 0], [0.1, 0.1, 0.2, 0.2])

    q = np.array([[[1.0, 2.0], [3.0, 4.0]]], np.float32)  # [N,2,HW]? use op
    inp = np.zeros((1, 8, 1, 1), np.float32)
    inp[0, :, 0, 0] = [1, 1, 2, 1, 2, 2, 1, 2]
    got = paddle.polygon_box_transform(T(inp)).numpy()
    # polygon_box_transform_op.cc: out = pixel coord - offset value
    assert got.shape == inp.shape


def test_sequence_slice_and_expand_as():
    # padded convention [B, T, ...]: per-row slice re-packed left
    xp = np.arange(18, dtype=np.float32).reshape(2, 3, 3)
    off = np.array([0, 1], np.int64)
    ln = np.array([2, 2], np.int64)
    data, new_len = paddle.sequence_slice(T(xp), T(off), T(ln))
    d = data.numpy()
    np.testing.assert_array_equal(new_len.numpy(), [2, 2])
    np.testing.assert_array_equal(d[0, :2], xp[0, 0:2])
    np.testing.assert_array_equal(d[1, :2], xp[1, 1:3])
    np.testing.assert_array_equal(d[:, 2], 0)      # padded tail zeroed
    # expand_as: each x row repeated to match y's row count
    got = paddle.sequence_expand_as(T(np.array([[1.0], [2.0]],
                                               np.float32)),
                                    T(np.zeros((4, 1), np.float32)))
    np.testing.assert_array_equal(got.numpy().ravel(), [1, 1, 2, 2])


def test_beam_search_decode_backtrace():
    # ids/parents [T, B, W]; step-2 winners backtrace through parents
    ids = np.array([[[1, 2]], [[3, 4]]], np.int64)
    parents = np.array([[[0, 0]], [[1, 0]]], np.int64)
    scores = np.array([[0.9, 0.3]], np.float32)
    seqs, sc = paddle.beam_search_decode(T(ids), T(parents), T(scores))
    s = seqs.numpy()
    # beam 0 at t=1 came from parent 1 (token 2), then emitted 3
    np.testing.assert_array_equal(s[:, 0, 0], [2, 3])
    np.testing.assert_array_equal(s[:, 0, 1], [1, 4])
    np.testing.assert_array_equal(sc.numpy(), scores)


# ---------------------------------------------------------------------------
# quantization observers + misc layers

def test_quant_observer_and_quant_dequant():
    from paddle_tpu.quantization import (MovingAverageAbsMaxObserver,
                                         quant_dequant_with_scale)
    obs = MovingAverageAbsMaxObserver(moving_rate=0.5)
    x1 = np.array([1.0, -2.0], np.float32)
    x2 = np.array([4.0, -1.0], np.float32)
    s1 = float(np.asarray(obs.observe(T(x1))))
    s2 = float(np.asarray(obs.observe(T(x2))))
    np.testing.assert_allclose(s1, 2.0, rtol=1e-5)
    np.testing.assert_allclose(s2, 0.5 * 2.0 + 0.5 * 4.0, rtol=1e-5)
    x = np.linspace(-1, 1, 9).astype(np.float32)
    qdq = np.asarray(quant_dequant_with_scale(T(x)._data, 1.0, 8))
    # int8 fake quant: |err| <= scale / 127
    assert np.abs(qdq - x).max() <= 1.0 / 127 + 1e-6


def test_sync_batch_norm_single_process_equals_bn():
    paddle.seed(13)
    sbn = nn.SyncBatchNorm(4)
    bn = nn.BatchNorm2D(4)
    bn.set_state_dict(sbn.state_dict())
    x = _rng(14).randn(3, 4, 5, 5).astype(np.float32)
    sbn.train()
    bn.train()
    np.testing.assert_allclose(sbn(T(x)).numpy(), bn(T(x)).numpy(),
                               rtol=1e-4, atol=1e-5)


def test_spectral_norm_power_iteration():
    paddle.seed(15)
    w = _rng(16).randn(6, 4).astype(np.float32)
    sn = nn.SpectralNorm([6, 4], dim=0, power_iters=50)
    got = sn(T(w)).numpy()
    sigma = np.linalg.svd(w, compute_uv=False)[0]
    np.testing.assert_allclose(got, w / sigma, rtol=1e-3, atol=1e-4)


def test_replicate_tensor_identity():
    import paddle_tpu.distributed as dist
    mesh = dist.build_mesh({"dp": 8})
    dist.set_mesh(mesh)
    try:
        x = T(_rng(17).randn(4, 4).astype(np.float32))
        y = dist.replicate_tensor(x)
        np.testing.assert_allclose(np.asarray(y._data), x.numpy())
    finally:
        dist.set_mesh(None)
