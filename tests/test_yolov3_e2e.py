"""YOLOv3 end-to-end (BASELINE workload 4): model shapes, hapi training
with decreasing loss, size-bucketed multi-scale training without
recompiles, decode+NMS, and the VOCDetection->transforms->train
integration. Reference: fluid/operators/detection/yolov3_loss_op.cc,
yolo_box_op.cc, multiclass_nms_op.cc; model capability =
PaddleDetection YOLOv3-DarkNet53."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.optimizer as optim
from paddle_tpu.metric import DetectionMAP
from paddle_tpu.vision.models import YOLOv3, YOLOv3Loss, darknet53


def _tiny(num_classes=4, num_max_boxes=6):
    paddle.seed(7)
    return YOLOv3(num_classes=num_classes, width_mult=0.125,
                  num_max_boxes=num_max_boxes)


def _batch(rng, n, s, num_max_boxes=6, num_classes=4):
    img = rng.rand(n, 3, s, s).astype(np.float32)
    gt_box = np.zeros((n, num_max_boxes, 4), np.float32)
    gt_label = np.zeros((n, num_max_boxes), np.int64)
    for i in range(n):
        m = rng.randint(1, 3)
        for b in range(m):
            cx, cy = rng.uniform(0.2, 0.8, 2)
            w, h = rng.uniform(0.1, 0.3, 2)
            gt_box[i, b] = [cx, cy, w, h]
            gt_label[i, b] = rng.randint(0, num_classes)
    return img, gt_box, gt_label


def test_forward_pyramid_shapes():
    m = _tiny()
    x = paddle.to_tensor(np.zeros((2, 3, 64, 64), np.float32))
    outs = m(x)
    a, c = 3, 4
    assert [tuple(o.shape) for o in outs] == [
        (2, a * (5 + c), 2, 2), (2, a * (5 + c), 4, 4),
        (2, a * (5 + c), 8, 8)]
    # darknet pyramid channels at width 1.0
    d = darknet53()
    assert d.out_channels == [256, 512, 1024]


def test_train_loss_decreases():
    m = _tiny()
    model = paddle.Model(m)
    model.prepare(optim.Momentum(learning_rate=1e-3, momentum=0.9,
                                 parameters=m.parameters()),
                  YOLOv3Loss(m))
    rng = np.random.RandomState(0)
    img, gt_box, gt_label = _batch(rng, 2, 64)
    losses = []
    for _ in range(25):
        l, _ = model.train_batch([paddle.to_tensor(img)],
                                 [paddle.to_tensor(gt_box),
                                  paddle.to_tensor(gt_label)])
        losses.append(l)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


def test_bucketed_multiscale_no_recompile():
    """Two size buckets train interleaved; each bucket compiles exactly
    once (the hapi train-step LRU) — the TPU answer to the reference's
    per-step random-resize multi-scale training."""
    m = _tiny()
    model = paddle.Model(m)
    model.prepare(optim.SGD(learning_rate=1e-3,
                            parameters=m.parameters()),
                  YOLOv3Loss(m))
    builds = []
    orig = model._build_train_step

    def counting(sig):
        builds.append(sig)
        return orig(sig)
    model._build_train_step = counting
    rng = np.random.RandomState(1)
    batches = {s: _batch(rng, 1, s) for s in (64, 96)}
    for step in range(6):
        s = (64, 96)[step % 2]
        img, gt_box, gt_label = batches[s]
        l, _ = model.train_batch([paddle.to_tensor(img)],
                                 [paddle.to_tensor(gt_box),
                                  paddle.to_tensor(gt_label)])
        assert np.isfinite(l)
    assert len(builds) == 2, f"recompiled: {len(builds)} builds"
    assert len(model._train_fns) == 2


def test_decode_shapes_and_valid_boxes():
    m = _tiny()
    rng = np.random.RandomState(2)
    img, _, _ = _batch(rng, 2, 64)
    outs = m(paddle.to_tensor(img))
    dets, counts = m.decode(outs,
                            paddle.to_tensor(np.array([[64, 64]] * 2,
                                                      np.int32)),
                            conf_thresh=0.05, keep_top_k=20)
    d = dets.numpy()
    assert d.shape == (2, 20, 6)
    cnt = counts.numpy()
    for n in range(2):
        valid = d[n, :cnt[n]]
        valid = valid[valid[:, 0] >= 0]
        if len(valid):
            assert (valid[:, 0] < 4).all()          # class in range
            assert (valid[:, 1] >= 0.0).all()       # scores
            assert (valid[:, 2:6] >= -1).all() and (valid[:, 2:6] <= 65).all()


@pytest.mark.slow
def test_voc_pipeline_to_train_integration(tmp_path):
    from test_voc_flowers_datasets import _write_voc_devkit
    from paddle_tpu.vision.datasets import VOCDetection
    from paddle_tpu.vision.transforms import (
        DetCompose, ResizeImage, RandomFlipImage, NormalizeBox,
        BoxXYXY2XYWH, PadBox, NormalizeImage, Permute)
    _write_voc_devkit(str(tmp_path))
    pipe = DetCompose([ResizeImage(64), RandomFlipImage(0.5),
                       NormalizeBox(), BoxXYXY2XYWH(), PadBox(6),
                       NormalizeImage(), Permute()])
    ds = VOCDetection(str(tmp_path), mode="train", transform=pipe)
    imgs, boxes, labels = [], [], []
    for i in range(len(ds)):
        im, b, l, _ = ds[i]
        imgs.append(im), boxes.append(b), labels.append(l)
    img = np.stack(imgs).astype(np.float32)
    gt_box, gt_label = np.stack(boxes), np.stack(labels)

    paddle.seed(3)
    m = YOLOv3(num_classes=20, width_mult=0.125, num_max_boxes=6)
    model = paddle.Model(m)
    model.prepare(optim.Momentum(learning_rate=1e-3, momentum=0.9,
                                 parameters=m.parameters()),
                  YOLOv3Loss(m))
    losses = [model.train_batch([paddle.to_tensor(img)],
                                [paddle.to_tensor(gt_box),
                                 paddle.to_tensor(gt_label)])[0]
              for _ in range(15)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], (losses[0], losses[-1])

    # eval edge: decode + host-side mAP machinery consumes the dets
    m.eval()
    outs = m(paddle.to_tensor(img))
    dets, counts = m.decode(outs, paddle.to_tensor(
        np.array([[64, 64]] * img.shape[0], np.int32)))
    mp = DetectionMAP(20)
    # xyxy pixel gt for the metric: un-normalize the padded cxcywh
    wh = gt_box[..., 2:4] * 64
    ctr = gt_box[..., 0:2] * 64
    gt_xyxy = np.concatenate([ctr - wh / 2, ctr + wh / 2], axis=-1)
    mp.update(dets.numpy(), counts.numpy(), gt_xyxy, gt_label)
    assert 0.0 <= mp.accumulate() <= 1.0


def test_detection_map_known_values():
    mp = DetectionMAP(2, overlap_threshold=0.5)
    # image: 2 gts of class 0; detections: one TP (0.9), one FP (0.8),
    # one duplicate on the same gt (0.7 -> FP)
    dets = np.array([[[0, 0.9, 0, 0, 10, 10],
                      [0, 0.8, 50, 50, 60, 60],
                      [0, 0.7, 1, 1, 10, 10]]], np.float32)
    gt = np.array([[[0, 0, 10, 10], [20, 20, 30, 30]]], np.float32)
    gl = np.array([[0, 0]])
    mp.update(dets, np.array([3]), gt, gl)
    # PR: tp@0.9 (p=1, r=.5), fp@0.8, fp-dup@0.7 -> integral AP = 0.5
    np.testing.assert_allclose(mp.accumulate(), 0.5, atol=1e-6)
    # difficult gt matched -> detection ignored, not FP
    mp2 = DetectionMAP(2)
    mp2.update(np.array([[[0, 0.9, 0, 0, 10, 10]]], np.float32),
               np.array([1]), np.array([[[0, 0, 10, 10]]], np.float32),
               np.array([[0]]), np.array([[1]]))
    assert mp2.accumulate() == 0.0  # no countable gt, no FP
