"""Ring attention / sequence parallelism over the "sp" mesh axis
(parity-plus: SURVEY §5.7 records the reference has NO sequence
parallelism; this is the TPU-native capability the build plan calls for).
Numerics checked exactly against dense attention."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.fleet import (ring_attention, RingAttention,
                                          split_sequence)


def dense_attention(q, k, v, causal=False):
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = np.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        T = q.shape[2]
        mask = np.tril(np.ones((T, T), bool))
        s = np.where(mask[None, None], s, -np.inf)
    s = s - s.max(axis=-1, keepdims=True)
    p = np.exp(s)
    p = p / p.sum(axis=-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.fixture
def sp_mesh():
    dist.set_mesh(dist.build_mesh({"sp": 8}))
    yield dist.get_mesh()
    dist.set_mesh(None)


class TestRingAttention:
    def _qkv(self, B=2, H=4, T=32, D=16, seed=0):
        rng = np.random.RandomState(seed)
        mk = lambda: rng.randn(B, H, T, D).astype(np.float32)
        return mk(), mk(), mk()

    def test_matches_dense(self, sp_mesh):
        q, k, v = self._qkv()
        out = ring_attention(jnp.asarray(q), jnp.asarray(k),
                             jnp.asarray(v), mesh=sp_mesh)
        np.testing.assert_allclose(np.asarray(out),
                                   dense_attention(q, k, v),
                                   rtol=2e-4, atol=2e-5)

    def test_causal_matches_dense(self, sp_mesh):
        q, k, v = self._qkv(seed=1)
        out = ring_attention(jnp.asarray(q), jnp.asarray(k),
                             jnp.asarray(v), mesh=sp_mesh, causal=True)
        np.testing.assert_allclose(np.asarray(out),
                                   dense_attention(q, k, v, causal=True),
                                   rtol=2e-4, atol=2e-5)

    def test_output_is_sequence_sharded(self, sp_mesh):
        q, k, v = self._qkv()
        out = ring_attention(jnp.asarray(q), jnp.asarray(k),
                             jnp.asarray(v), mesh=sp_mesh)
        assert "sp" in str(out.sharding.spec)
        shards = out.addressable_shards
        assert len(shards) == 8 and shards[0].data.shape[2] == 4

    @pytest.mark.slow
    def test_gradients_match_dense(self, sp_mesh):
        q, k, v = self._qkv(B=1, H=2, T=16, D=8, seed=2)

        def loss_ring(q_, k_, v_):
            return jnp.sum(ring_attention(q_, k_, v_, mesh=sp_mesh,
                                          causal=True) ** 2)

        def loss_dense(q_, k_, v_):
            scale = 1.0 / np.sqrt(q_.shape[-1])
            s = jnp.einsum("bhqd,bhkd->bhqk", q_, k_) * scale
            T = q_.shape[2]
            mask = jnp.tril(jnp.ones((T, T), bool))
            s = jnp.where(mask[None, None], s, -jnp.inf)
            p = jax.nn.softmax(s, axis=-1)
            return jnp.sum(jnp.einsum("bhqk,bhkd->bhqd", p, v_) ** 2)

        g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=2e-4)

    def test_layer_wrapper_with_tensors(self, sp_mesh):
        q, k, v = self._qkv(seed=3)
        attn = RingAttention(mesh=sp_mesh, causal=False)
        out = attn(paddle.to_tensor(q), paddle.to_tensor(k),
                   paddle.to_tensor(v))
        np.testing.assert_allclose(out.numpy(), dense_attention(q, k, v),
                                   rtol=2e-4, atol=2e-5)

    def test_split_sequence_helper(self, sp_mesh):
        x = jnp.zeros((2, 4, 32, 8))
        xs = split_sequence(x, mesh=sp_mesh)
        assert xs.addressable_shards[0].data.shape[2] == 4


class TestErnieAndOnnx:
    @pytest.mark.slow
    def test_ernie_forward_and_finetune_step(self):
        import paddle_tpu.optimizer as optim
        from paddle_tpu.models import (ErnieConfig,
                                       ErnieForSequenceClassification)
        paddle.seed(0)
        cfg = ErnieConfig(vocab_size=300, hidden_size=32, num_layers=2,
                          num_heads=2, intermediate_size=64,
                          max_position_embeddings=32,
                          hidden_dropout_prob=0.0,
                          attention_dropout_prob=0.0)
        net = ErnieForSequenceClassification(cfg, num_classes=3)
        opt = optim.AdamW(learning_rate=5e-3, parameters=net.parameters())
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, 300, (4, 16)).astype(np.int32))
        y = paddle.to_tensor(rng.randint(0, 3, (4,)).astype(np.int64))
        import paddle_tpu.nn as nn
        losses = []
        for _ in range(5):
            logits = net(ids)
            loss = nn.functional.cross_entropy(logits, y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]

    def test_onnx_export_facade(self, tmp_path):
        # round 5: .onnx export is REAL now (jaxpr->ONNX, opset 13);
        # numeric round-trip pinned in tests/test_onnx_export.py
        import paddle_tpu.nn as nn
        from paddle_tpu.static import InputSpec
        paddle.seed(0)
        lin = nn.Linear(4, 2)
        import os
        onnx_path = paddle.onnx.export(
            lin, str(tmp_path / "m.onnx"),
            input_spec=[InputSpec([1, 4], "float32")])
        assert os.path.getsize(onnx_path) > 100
        out = paddle.onnx.export(lin, str(tmp_path / "m"),
                                 input_spec=[InputSpec([1, 4], "float32")])
        assert os.path.exists(out + ".pdmodel")


class TestRingAttentionTape:
    @pytest.mark.slow
    def test_wrapper_backprop_produces_grads(self, sp_mesh):
        rng = np.random.RandomState(4)
        q = paddle.to_tensor(rng.randn(1, 2, 16, 8).astype(np.float32),
                             stop_gradient=False)
        k = paddle.to_tensor(rng.randn(1, 2, 16, 8).astype(np.float32),
                             stop_gradient=False)
        v = paddle.to_tensor(rng.randn(1, 2, 16, 8).astype(np.float32),
                             stop_gradient=False)
        attn = RingAttention(mesh=dist.get_mesh(), causal=True)
        out = attn(q, k, v)
        (out * out).sum().backward()
        for t in (q, k, v):
            assert t.grad is not None
            assert np.abs(t.grad.numpy()).sum() > 0
