"""Backward ring-flash attention (ROADMAP item 2): jax.grad through
``ring_flash_attention`` runs the flash recomputation schedule around the
K/V ring — no [Tl, Tl] score block in either direction.

Covers the ISSUE-15 acceptance surface:
- gradcheck vs dense-chunk ring AD on the 8-device mesh (causal and
  non-causal, f32 and bf16, non-pow2 Tl with a 16-multiple tail),
- dp×sp composition,
- compile-counter regression: warm ring calls trigger zero new traces
  (the shard-mapped callables are cached per signature),
- tuned-vs-default-blocks bitwise equivalence for the backward kernel,
- (slow) the S=32k dp×sp train step: loss curve matches the
  single-device flash path, which is only possible when neither walk
  materializes dense scores.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu.distributed as dist
import paddle_tpu.tuner as tuner
from paddle_tpu.distributed.fleet import sequence_parallel as sp


@pytest.fixture()
def sp8_mesh():
    mesh = dist.build_mesh({"sp": 8})
    dist.set_mesh(mesh)
    yield mesh
    dist.set_mesh(None)


@pytest.fixture()
def dp_sp_mesh():
    mesh = dist.build_mesh({"dp": 2, "sp": 4})
    dist.set_mesh(mesh)
    yield mesh
    dist.set_mesh(None)


@pytest.fixture()
def tune_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_TUNE_CACHE", str(tmp_path))
    tuner.clear_memo()
    yield tmp_path
    tuner.clear_memo()


def _arrs(B, H, T, D, dtype=jnp.float32, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, H, T, D) * 0.5,
                             jnp.float32).astype(dtype)
    return mk(), mk(), mk(), mk()           # q, k, v, do


def _ring_grads(fn, q, k, v, do, causal, batch_axes=None):
    def loss(q, k, v):
        out = fn(q, k, v, axis="sp", causal=causal, batch_axes=batch_axes)
        return jnp.sum((out * do).astype(jnp.float32))
    return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)


def _assert_close(got, ref, tol):
    """Normalized max-abs check: elementwise rtol is meaningless for the
    near-zero entries a causal mask produces."""
    for name, g, r in zip("qkv", got, ref):
        g = np.asarray(g, np.float32)
        r = np.asarray(r, np.float32)
        scale = max(np.abs(r).max(), 1e-6)
        err = np.abs(g - r).max() / scale
        assert np.all(np.isfinite(g)), f"d{name} has non-finite entries"
        assert err < tol, f"d{name}: normalized max err {err:.3e} >= {tol}"


class TestGradcheckVsDenseRing:
    """The dense-chunk ring differentiates via plain AD through
    scan+ppermute (pinned against jnp dense attention in
    test_sequence_parallel.py) — it is the reference schedule for the
    hand-written ring-flash custom_vjp."""

    # T=384 -> Tl=48: non-pow2 with a 16-multiple tail
    @pytest.mark.parametrize("T", [128, 384])
    @pytest.mark.parametrize("causal", [False, True])
    def test_f32(self, sp8_mesh, T, causal):
        q, k, v, do = _arrs(2, 2, T, 16, seed=T)
        ref = _ring_grads(sp.ring_attention, q, k, v, do, causal)
        got = _ring_grads(sp.ring_flash_attention, q, k, v, do, causal)
        _assert_close(got, ref, 1e-4)

    @pytest.mark.parametrize("causal", [False, True])
    def test_bf16(self, sp8_mesh, causal):
        q, k, v, do = _arrs(2, 2, 128, 16, jnp.bfloat16, seed=9)
        ref = _ring_grads(sp.ring_attention, q, k, v, do, causal)
        got = _ring_grads(sp.ring_flash_attention, q, k, v, do, causal)
        # bf16 inputs, f32 accumulators both sides: the two schedules
        # round differently per chunk
        _assert_close(got, ref, 3e-2)

    def test_dp_sp(self, dp_sp_mesh):
        q, k, v, do = _arrs(2, 2, 128, 16, seed=3)
        ref = _ring_grads(sp.ring_attention, q, k, v, do, True,
                          batch_axes="dp")
        got = _ring_grads(sp.ring_flash_attention, q, k, v, do, True,
                          batch_axes="dp")
        _assert_close(got, ref, 1e-4)

    def test_grad_guard_is_gone(self):
        assert not hasattr(sp, "_grad_guard"), (
            "_grad_guard (the forward-only marker) must be deleted now "
            "that the ring backward is real")


class TestRingCallableCache:
    def test_same_signature_same_callable(self, sp8_mesh):
        a = sp._ring_callable("flash", sp8_mesh, "sp", True, 0.25, None,
                              interpret=True)
        b = sp._ring_callable("flash", sp8_mesh, "sp", True, 0.25, None,
                              interpret=True)
        assert a is b
        c = sp._ring_callable("flash", sp8_mesh, "sp", False, 0.25, None,
                              interpret=True)
        assert c is not a
        d = sp._ring_callable("dense", sp8_mesh, "sp", True, 0.25, None)
        assert d is not a

    def test_warm_calls_zero_new_traces(self, sp8_mesh):
        """The compile-counter regression: after warmup, repeated eager
        forwards AND repeated jax.grad calls must re-trace nothing — the
        cached jit-wrapped callables hit the pjit trace cache."""
        q, k, v, do = _arrs(1, 2, 128, 16, seed=5)

        def floss(q, k, v):
            return jnp.sum(sp.ring_flash_attention(
                q, k, v, axis="sp", causal=True) * do)

        def dloss(q, k, v):
            return jnp.sum(sp.ring_attention(
                q, k, v, axis="sp", causal=True) * do)

        # warmup: one eager forward + one grad per variant
        sp.ring_flash_attention(q, k, v, axis="sp", causal=True)
        jax.grad(floss, argnums=(0, 1, 2))(q, k, v)
        sp.ring_attention(q, k, v, axis="sp", causal=True)
        jax.grad(dloss, argnums=(0, 1, 2))(q, k, v)

        before = dict(sp._TRACE_COUNTS)
        for _ in range(3):
            sp.ring_flash_attention(q, k, v, axis="sp", causal=True)
            jax.grad(floss, argnums=(0, 1, 2))(q, k, v)
            sp.ring_attention(q, k, v, axis="sp", causal=True)
            jax.grad(dloss, argnums=(0, 1, 2))(q, k, v)
        after = dict(sp._TRACE_COUNTS)
        assert after == before, (
            f"warm ring calls re-traced: {before} -> {after}")


class TestTunedBwdBlocks:
    """The backward block family (flash_bwd / ring_flash_bwd) resolves
    through the same 4-tier tuner as the forward, with the shared
    divisibility sanitizer guarding ring lookups."""

    def test_ring_bwd_winner_used(self, tune_cache):
        key = tuner.flash_key(64, 64, 16, "float32", False, ring=True,
                              bwd=True)
        tuner.record_winner(key, {"block_q": 32, "block_k": 32})
        assert sp._ring_blocks(64, 16, jnp.float32, bwd=True) == (32, 32)

    def test_ring_bwd_nondividing_winner_discarded(self, tune_cache):
        key = tuner.flash_key(64, 64, 16, "float32", False, ring=True,
                              bwd=True)
        tuner.record_winner(key, {"block_q": 48, "block_k": 48})
        # 48 does not divide 64: sanitizer rejects, default (64, 64)
        assert sp._ring_blocks(64, 16, jnp.float32, bwd=True) == (64, 64)

    def test_ring_bwd_falls_back_to_fwd_winner(self, tune_cache):
        fwd_key = tuner.flash_key(64, 64, 16, "float32", False, ring=True)
        tuner.record_winner(fwd_key, {"block_q": 16, "block_k": 32})
        assert sp._ring_blocks(64, 16, jnp.float32, bwd=True) == (16, 32)

    def test_sanitizer_shared(self):
        assert sp._sanitize_ring_blocks((32, 32), 64) == (32, 32)
        assert sp._sanitize_ring_blocks((48, 32), 64) is None   # 64 % 48
        assert sp._sanitize_ring_blocks((8, 32), 64) is None    # 8 % 16
        assert sp._sanitize_ring_blocks(None, 64) is None

    def test_tuned_equals_default_bitwise(self, tune_cache, sp8_mesh):
        """Recording a backward winner equal to the blocks the default
        heuristic picks must leave the computed gradients bit-identical:
        the tuner lookup selects a grid, it must never perturb numerics.
        A genuinely different (dividing) winner changes the reduction
        order, so it only matches within f32 tolerance."""
        q, k, v, do = _arrs(1, 2, 128, 16, seed=7)      # Tl=16
        base = _ring_grads(sp.ring_flash_attention, q, k, v, do, True)

        key = tuner.flash_key(16, 16, 16, "float32", False, ring=True,
                              bwd=True)
        # Tl=16: the heuristic default is (16, 16); record it as the
        # winner and the resolved path must be bitwise identical
        tuner.record_winner(key, {"block_q": 16, "block_k": 16})
        assert sp._ring_blocks(16, 16, jnp.float32, bwd=True) == (16, 16)
        tuned = _ring_grads(sp.ring_flash_attention, q, k, v, do, True)
        for b, t in zip(base, tuned):
            np.testing.assert_array_equal(np.asarray(b), np.asarray(t))

    def test_nonring_bwd_winner_used_bitwise(self, tune_cache):
        """Single-device path: _fa_core_bwd consults the flash_bwd
        family. A winner equal to the forward blocks is bitwise
        identical; sanity-check a different dividing winner still
        gradchecks against it."""
        from paddle_tpu.ops.pallas_attention import _fa_core
        rng = np.random.RandomState(11)
        q, k, v, do = (jnp.asarray(rng.randn(2, 128, 16) * 0.5,
                                   jnp.float32) for _ in range(4))
        sc = 1.0 / np.sqrt(16.0)

        def loss(q, k, v):
            out = _fa_core(q, k, v, True, sc, 64, 64, True, 128)
            return jnp.sum(out * do)

        base = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        key = tuner.flash_key(128, 128, 16, "float32", True, bwd=True)
        tuner.record_winner(key, {"block_q": 64, "block_k": 64})
        same = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        for b, t in zip(base, same):
            np.testing.assert_array_equal(np.asarray(b), np.asarray(t))

        tuner.record_winner(key, {"block_q": 32, "block_k": 128})
        tuner.clear_memo()
        other = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        _assert_close(other, base, 1e-4)


@pytest.mark.slow
@pytest.mark.timeout_s(1200)
def test_s32k_train_loss_curve_matches_single_device(dp_sp_mesh):
    """The acceptance shape: a dp×sp train step at S=32768 (Tl=8192 per
    rank). A dense-chunk reference is impossible here — one [Tl, Tl]
    score block alone is 256 MiB and AD would stack S of them — so the
    reference is the single-device flash path (O(S) memory, its own
    custom_vjp pinned in test_tuner.py): both train loops must produce
    the same decreasing loss curve."""
    from paddle_tpu.ops.pallas_attention import _fa_core

    B, H, T, D = 2, 1, 32768, 8
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(B, H, T, D) * 0.3, jnp.float32)
    y = jnp.asarray(rng.randn(B, H, T, D) * 0.3, jnp.float32)
    w0 = jnp.asarray(rng.randn(D, D) * 0.2, jnp.float32)

    def run(loss_fn, steps=2):
        w, losses = w0, []
        step = jax.jit(jax.value_and_grad(loss_fn))
        for _ in range(steps):
            loss, g = step(w)
            w = w - 0.5 * g
            losses.append(float(loss))
        return losses

    # Sum (not mean) over the sequence axis: a per-element mean over
    # B*H*T*D = 524288 entries shrinks |grad| to ~1e-6 and an SGD step
    # moves the f32 loss by less than one ulp — the curve would be flat
    # for purely numerical reasons. Summing over T keeps the step's
    # loss decrease ~1000 ulps at this scale.
    def ring_loss(w):
        q = x @ w
        att = sp.ring_flash_attention(q, x, x, axis="sp", causal=True,
                                      batch_axes="dp")
        return jnp.mean(jnp.sum((att - y) ** 2, axis=2))

    def flash_loss(w):
        q = (x @ w).reshape(B * H, T, D)
        kb = x.reshape(B * H, T, D)
        att = _fa_core(q, kb, kb, True, 1.0 / np.sqrt(D), 512, 512,
                       True, T)
        return jnp.mean(jnp.sum(((att.reshape(B, H, T, D) - y) ** 2),
                                axis=2))

    ring_losses = run(ring_loss)
    flash_losses = run(flash_loss)
    assert all(np.isfinite(ring_losses))
    assert ring_losses[-1] < ring_losses[0], (
        f"S=32k ring-flash training did not learn: {ring_losses}")
    np.testing.assert_allclose(ring_losses, flash_losses, rtol=1e-4)
