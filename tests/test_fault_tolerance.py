"""Fault-tolerance runtime unit tests (docs/fault_tolerance.md):
retry/backoff with an injectable clock, deadlines, deterministic fault
injection, checksum-verified checkpoint load, corrupt-shard fallback in
TrainEpochRange, and graceful-drain exit codes. The end-to-end elastic
launcher proof lives in test_elastic_launch.py."""
import json
import os
import signal
import subprocess
import sys

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as optim
from paddle_tpu.incubate.checkpoint import (
    save_sharded, load_sharded, TrainEpochRange,
    CheckpointIntegrityError, verify_checkpoint)
from paddle_tpu.utils.resilience import (
    retry, retry_call, RetryError, Deadline, DeadlineExceeded,
    FaultInjector, FaultInjected, FAULT_CRASH_EXIT_CODE)
from paddle_tpu.distributed.elastic import (
    PreemptionGuard, PREEMPTION_EXIT_CODE)


class FakeClock:
    def __init__(self):
        self.t = 0.0
        self.sleeps = []

    def __call__(self):
        return self.t

    def sleep(self, s):
        self.sleeps.append(s)
        self.t += s


class TestRetry:
    def test_succeeds_after_transient_failures_no_real_sleep(self):
        clock = FakeClock()
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        out = retry_call(flaky, max_attempts=5, backoff=0.5, jitter=0.0,
                         sleep=clock.sleep)
        assert out == "ok" and len(calls) == 3
        assert clock.sleeps == [0.5, 1.0]  # exponential, no jitter

    def test_exhaustion_raises_retry_error_with_cause(self):
        clock = FakeClock()

        def always():
            raise ValueError("nope")

        with pytest.raises(RetryError) as ei:
            retry_call(always, max_attempts=3, backoff=0.1, jitter=0.0,
                       sleep=clock.sleep)
        assert isinstance(ei.value.__cause__, ValueError)
        assert len(clock.sleeps) == 2  # no sleep after the final attempt

    def test_jitter_bounds(self):
        clock = FakeClock()

        def always():
            raise OSError("x")

        with pytest.raises(RetryError):
            retry_call(always, max_attempts=4, backoff=1.0, multiplier=1.0,
                       jitter=0.1, sleep=clock.sleep, rng=lambda: 1.0)
        assert all(abs(s - 1.1) < 1e-9 for s in clock.sleeps)

    def test_retry_on_filters_exception_types(self):
        def typeerr():
            raise TypeError("not retryable")

        with pytest.raises(TypeError):
            retry_call(typeerr, max_attempts=3, retry_on=(OSError,),
                       sleep=lambda s: None)

    def test_decorator_form(self):
        clock = FakeClock()
        state = {"n": 0}

        @retry(max_attempts=3, backoff=0.2, jitter=0.0, sleep=clock.sleep)
        def fn(x):
            state["n"] += 1
            if state["n"] < 2:
                raise OSError("once")
            return x * 2

        assert fn(21) == 42
        assert clock.sleeps == [0.2]

    def test_deadline_stops_retrying_early(self):
        clock = FakeClock()
        dl = Deadline(1.0, clock=clock)

        def always():
            raise OSError("x")

        with pytest.raises(RetryError):
            retry_call(always, max_attempts=100, backoff=0.6, jitter=0.0,
                       deadline=dl, sleep=clock.sleep)
        # 0.6 + 0.4 (clamped to remaining) then expired → 2 sleeps max
        assert len(clock.sleeps) <= 2


class TestDeadline:
    def test_remaining_and_expired(self):
        clock = FakeClock()
        dl = Deadline(2.0, clock=clock)
        assert dl.remaining() == 2.0 and not dl.expired()
        clock.t = 2.5
        assert dl.expired()
        with pytest.raises(DeadlineExceeded):
            dl.check("init")

    def test_none_means_unbounded(self):
        dl = Deadline(None)
        assert dl.remaining() == float("inf") and not dl.expired()
        dl.check()

    def test_from_env(self, monkeypatch):
        monkeypatch.setenv("X_TIMEOUT", "7.5")
        assert Deadline.from_env("X_TIMEOUT").seconds == 7.5
        monkeypatch.delenv("X_TIMEOUT")
        assert Deadline.from_env("X_TIMEOUT", 3.0).seconds == 3.0


class TestFaultInjector:
    def test_spec_parsing_and_occurrence_counting(self):
        fi = FaultInjector("load:2:corrupt,step:1:slow")
        assert fi.armed("load") and fi.armed("step") and not fi.armed("save")
        assert fi.fire("step") == "slow"
        assert fi.fire("step") is None      # occurrence 2: no rule
        assert fi.fire("load") is None      # occurrence 1
        assert fi.fire("load") == "corrupt"  # occurrence 2
        assert fi.fire("load") is None
        assert fi.fire("unknown_site") is None

    def test_empty_spec_is_inert(self):
        fi = FaultInjector("")
        assert not fi.armed()
        assert fi.fire("epoch") is None

    def test_bad_spec_rejected(self):
        with pytest.raises(ValueError, match="epoch:2:crash"):
            FaultInjector("epoch-2-crash")

    def test_raise_action(self):
        fi = FaultInjector("op:1:raise")
        with pytest.raises(FaultInjected, match="op:1"):
            fi.fire("op")

    def test_crash_action_hard_exits_with_reserved_code(self, tmp_path):
        # crash = os._exit(FAULT_CRASH_EXIT_CODE); prove it in a throwaway
        # interpreter (stdlib only — fast)
        code = (
            "import importlib.util\n"
            "spec = importlib.util.spec_from_file_location('resilience',\n"
            "    '/root/repo/paddle_tpu/utils/resilience.py')\n"
            "m = importlib.util.module_from_spec(spec)\n"
            "spec.loader.exec_module(m)\n"
            "m.FaultInjector('boom:1:crash').fire('boom')\n"
            "print('UNREACHABLE')\n")
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, timeout=60)
        assert proc.returncode == FAULT_CRASH_EXIT_CODE
        assert "UNREACHABLE" not in proc.stdout


def _flip_last_byte(ckpt_dir):
    fn = sorted(f for f in os.listdir(ckpt_dir) if f.startswith("shards_"))[0]
    full = os.path.join(ckpt_dir, fn)
    with open(full, "r+b") as f:
        f.seek(-1, os.SEEK_END)
        b = f.read(1)
        f.seek(-1, os.SEEK_END)
        f.write(bytes([b[0] ^ 0xFF]))
    return full


class TestCheckpointIntegrity:
    def test_checksums_written_into_metadata(self, tmp_path):
        ck = str(tmp_path / "ck")
        save_sharded({"a": jnp.arange(6.0)}, ck)
        with open(os.path.join(ck, "metadata_0.json")) as f:
            doc = json.load(f)
        assert doc["format"] == 2
        assert "shards_0.npz" in doc["checksums"]
        assert len(doc["checksums"]["shards_0.npz"]) == 64  # sha256 hex

    def test_corrupt_shard_raises_checksum_error(self, tmp_path):
        ck = str(tmp_path / "ck")
        save_sharded({"a": jnp.arange(6.0)}, ck)
        _flip_last_byte(ck)
        with pytest.raises(CheckpointIntegrityError, match="checksum"):
            load_sharded(ck)
        # verify=False is the escape hatch for forensics
        out = load_sharded(ck, verify=False)
        assert "a" in out

    def test_missing_shard_file_raises(self, tmp_path):
        ck = str(tmp_path / "ck")
        save_sharded({"a": jnp.arange(6.0)}, ck)
        os.remove(os.path.join(ck, "shards_0.npz"))
        with pytest.raises(CheckpointIntegrityError, match="missing"):
            load_sharded(ck)

    def test_torn_save_without_metadata_raises(self, tmp_path):
        ck = tmp_path / "ck"
        ck.mkdir()
        (ck / "shards_0.npz").write_bytes(b"partial garbage")
        with pytest.raises(CheckpointIntegrityError, match="torn"):
            verify_checkpoint(str(ck))

    def test_legacy_format1_checkpoint_still_loads(self, tmp_path):
        ck = str(tmp_path / "ck")
        save_sharded({"a": jnp.arange(4.0), "s": 5}, ck)
        mp = os.path.join(ck, "metadata_0.json")
        with open(mp) as f:
            doc = json.load(f)
        with open(mp, "w") as f:
            json.dump(doc["entries"], f)  # strip the format-2 envelope
        out = load_sharded(ck)
        np.testing.assert_allclose(out["a"].numpy(), np.arange(4.0))
        assert out["s"] == 5

    def test_fault_injected_corruption_on_load(self, tmp_path, monkeypatch):
        from paddle_tpu.utils import resilience
        ck = str(tmp_path / "ck")
        save_sharded({"a": jnp.arange(4.0)}, ck)
        monkeypatch.setenv("PADDLE_TPU_FAULT_SPEC", "load:1:corrupt")
        resilience._reset_fault_injector_for_tests()
        try:
            with pytest.raises(CheckpointIntegrityError):
                load_sharded(ck)
        finally:
            monkeypatch.delenv("PADDLE_TPU_FAULT_SPEC")
            resilience._reset_fault_injector_for_tests()


def _tiny_job(tmp_path, name="jobA", epochs=3, guard=None, keep_last=10):
    paddle.seed(11)
    net = nn.Linear(4, 2)
    opt = optim.SGD(learning_rate=0.1, parameters=net.parameters())
    r = TrainEpochRange(epochs, name, model=net, optimizer=opt,
                        checkpoint_path=str(tmp_path / "auto"),
                        keep_last=keep_last, preemption_guard=guard)
    return net, opt, r


def _step(net, opt):
    x = paddle.to_tensor(np.ones((8, 4), np.float32))
    loss = paddle.mean(net(x) ** 2)
    loss.backward()
    opt.step()
    opt.clear_grad()


class TestAutoCheckpointResilience:
    def test_corrupt_newest_epoch_falls_back_to_previous(self, tmp_path):
        net, opt, r = _tiny_job(tmp_path)
        for _ in r:
            _step(net, opt)
        job = tmp_path / "auto" / "jobA"
        _flip_last_byte(str(job / "epoch_2"))
        with pytest.warns(UserWarning, match="not intact"):
            _, _, r2 = _tiny_job(tmp_path)
        assert r2.restored_epoch == 1

    def test_half_deleted_epoch_falls_back(self, tmp_path):
        net, opt, r = _tiny_job(tmp_path)
        for _ in r:
            _step(net, opt)
        job = tmp_path / "auto" / "jobA"
        os.remove(str(job / "epoch_2" / "shards_0.npz"))
        with pytest.warns(UserWarning, match="not intact"):
            _, _, r2 = _tiny_job(tmp_path)
        assert r2.restored_epoch == 1

    def test_malformed_epoch_dir_does_not_abort_gc_or_restore(self, tmp_path):
        net, opt, r = _tiny_job(tmp_path, keep_last=1)
        job = tmp_path / "auto" / "jobA"
        job.mkdir(parents=True, exist_ok=True)
        (job / "epoch_2.tmp_partial").mkdir()  # crash debris, non-numeric
        for _ in r:  # commit path runs _gc over the stray entry
            _step(net, opt)
        assert (job / "epoch_2.tmp_partial").exists()  # skipped, not fatal
        _, _, r2 = _tiny_job(tmp_path, keep_last=1)
        assert r2.restored_epoch == 2

    def test_orphaned_partial_epochs_gced_on_restore(self, tmp_path):
        net, opt, r = _tiny_job(tmp_path)
        for _ in r:
            _step(net, opt)
        job = tmp_path / "auto" / "jobA"
        (job / "epoch_7").mkdir()  # newer than committed epoch 2 → orphan
        _, _, r2 = _tiny_job(tmp_path)
        assert r2.restored_epoch == 2
        assert not (job / "epoch_7").exists()

    def test_preempted_range_commits_and_exits_with_resume_code(
            self, tmp_path):
        guard = PreemptionGuard(install=False)
        net, opt, r = _tiny_job(tmp_path, name="jobP", epochs=5, guard=guard)
        done = []
        with pytest.raises(SystemExit) as ei:
            for epoch in r:
                _step(net, opt)
                done.append(epoch)
                if epoch == 1:
                    guard.preempt()  # platform preemption notice
        assert ei.value.code == PREEMPTION_EXIT_CODE
        assert done == [0, 1]
        # the final checkpoint was committed before exit → resume at 2
        _, _, r2 = _tiny_job(tmp_path, name="jobP", epochs=5)
        assert r2.restored_epoch == 1


class TestPreemptionGuard:
    def test_sigterm_sets_flag_and_exit_code(self):
        with PreemptionGuard() as g:
            assert not g.preempted
            os.kill(os.getpid(), signal.SIGTERM)
            assert g.preempted
            saved = []
            with pytest.raises(SystemExit) as ei:
                g.exit_if_preempted(save_fn=lambda: saved.append(1))
            assert ei.value.code == PREEMPTION_EXIT_CODE
            assert saved == [1]

    def test_noop_when_not_preempted(self):
        g = PreemptionGuard(install=False)
        g.exit_if_preempted(save_fn=lambda: pytest.fail("must not save"))

    def test_uninstall_restores_previous_handler(self):
        prev = signal.getsignal(signal.SIGTERM)
        g = PreemptionGuard()
        assert signal.getsignal(signal.SIGTERM) != prev
        g.uninstall()
        assert signal.getsignal(signal.SIGTERM) == prev

    def test_uninstall_leaves_third_party_reregistration_alone(self):
        # Regression: if someone re-registers the signal after our
        # install, uninstall must NOT clobber them with our saved
        # handler — that is the exact bug the chain exists to prevent.
        original = signal.getsignal(signal.SIGUSR1)

        def third_party(signum, frame):
            pass

        g = PreemptionGuard(signals=(signal.SIGUSR1,))
        try:
            signal.signal(signal.SIGUSR1, third_party)
            g.uninstall()
            assert signal.getsignal(signal.SIGUSR1) is third_party
        finally:
            signal.signal(signal.SIGUSR1, original)


class TestFaultToleranceCallback:
    class _ModelStub:
        def __init__(self):
            self.saved = []

        def save(self, path):
            self.saved.append(path)

    def test_preemption_saves_then_exits(self, tmp_path):
        from paddle_tpu.hapi.callbacks import FaultToleranceCallback
        guard = PreemptionGuard(install=False)
        cb = FaultToleranceCallback(str(tmp_path / "ft"), guard=guard)
        m = self._ModelStub()
        cb.set_model(m)
        cb.on_train_begin()
        cb.on_train_batch_end(0)       # not preempted: no exit
        guard.preempt()
        with pytest.raises(SystemExit) as ei:
            cb.on_train_batch_end(1)
        assert ei.value.code == PREEMPTION_EXIT_CODE
        assert m.saved and m.saved[0].endswith("preempted")

    def test_epoch_end_saves_latest(self, tmp_path):
        from paddle_tpu.hapi.callbacks import FaultToleranceCallback
        guard = PreemptionGuard(install=False)
        cb = FaultToleranceCallback(str(tmp_path / "ft"), guard=guard)
        m = self._ModelStub()
        cb.set_model(m)
        cb.on_epoch_end(0)
        assert m.saved == [os.path.join(str(tmp_path / "ft"), "latest")]
