"""End-to-end slice: LeNet digit classification in dygraph mode
(BASELINE config 1 — reference: python/paddle/vision/models/lenet.py:21 +
unittests/test_imperative_mnist.py). Synthetic separable data instead of the
MNIST download; the test asserts real learning (loss drops, accuracy high).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as optim


class LeNet(nn.Layer):
    """reference: python/paddle/vision/models/lenet.py:21."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1),
            nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0),
            nn.ReLU(),
            nn.MaxPool2D(2, 2))
        self.fc = nn.Sequential(
            nn.Linear(400, 120),
            nn.Linear(120, 84),
            nn.Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        x = paddle.flatten(x, 1)
        return self.fc(x)


def synthetic_digits(n, seed=0):
    """Separable synthetic 28x28 'digits': class k = blob at position k."""
    rng = np.random.RandomState(seed)
    xs = rng.rand(n, 1, 28, 28).astype(np.float32) * 0.25
    ys = rng.randint(0, 10, n)
    for i, y in enumerate(ys):
        r, c = divmod(int(y), 4)
        xs[i, 0, 4 + r * 7:4 + r * 7 + 6, 2 + c * 6:2 + c * 6 + 5] += 1.0
    return xs, ys.astype(np.int64)


@pytest.mark.slow
def test_lenet_mnist_convergence():
    paddle.seed(0)
    model = LeNet()
    opt = optim.Adam(1e-3, parameters=model.parameters())
    xs, ys = synthetic_digits(256)
    bs = 64
    first_loss = last_loss = None
    for epoch in range(6):
        for i in range(0, len(xs), bs):
            xb = paddle.to_tensor(xs[i:i + bs])
            yb = paddle.to_tensor(ys[i:i + bs])
            logits = model(xb)
            loss = F.cross_entropy(logits, yb)
            loss.backward()
            opt.step()
            opt.clear_grad()
            if first_loss is None:
                first_loss = float(loss)
            last_loss = float(loss)
    assert first_loss > 1.5, first_loss
    assert last_loss < 0.35, f"did not converge: {first_loss} -> {last_loss}"

    model.eval()
    with paddle.no_grad():
        logits = model(paddle.to_tensor(xs))
        acc = (logits.argmax(1).numpy() == ys).mean()
    assert acc > 0.9, acc


def test_lenet_eval_deterministic():
    model = LeNet()
    model.eval()
    x = paddle.randn([2, 1, 28, 28])
    with paddle.no_grad():
        a = model(x).numpy()
        b = model(x).numpy()
    np.testing.assert_allclose(a, b)
