"""Compressed gradient allreduce (EQuARX-style block-scaled int8/bf16
wire format, PAPERS.md) on the 8-virtual-device CPU mesh: error bounds
vs the dense exchange, replica bitwise identity, convergence parity,
the >=3x wire-bytes bar, and the fleet/DataParallel plumbing."""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.collective import (
    DEFAULT_COMPRESS_BLOCK, _block_dequantize_int8, _block_quantize_int8,
    build_compressed_train_step)


@pytest.fixture(autouse=True)
def _mesh():
    dist.set_mesh(dist.build_mesh({"dp": 8}))
    yield
    dist.set_mesh(None)


def spmd(fn, in_specs, out_specs, check=True):
    # check=False: the compressed allreduce's all_gather phase replicates
    # the result by construction, but the checker can't infer that
    return jax.shard_map(fn, mesh=dist.get_mesh(),
                         in_specs=in_specs, out_specs=out_specs,
                         check_vma=check)


class TestBlockQuantize:
    def test_roundtrip_error_bound(self):
        rng = np.random.default_rng(0)
        blocks = jnp.asarray(rng.standard_normal((16, 64)), jnp.float32)
        q, s = _block_quantize_int8(blocks)
        assert q.dtype == jnp.int8 and s.dtype == jnp.float32
        deq = _block_dequantize_int8(q, s)
        # symmetric round-to-nearest: error <= absmax/(2*127) per block
        bound = np.asarray(s)[:, None] / (2 * 127) + 1e-7
        assert np.all(np.abs(np.asarray(deq - blocks)) <= bound)

    def test_zero_block_is_exact(self):
        q, s = _block_quantize_int8(jnp.zeros((2, 8)))
        np.testing.assert_array_equal(np.asarray(q), 0)
        deq = _block_dequantize_int8(q, s)
        np.testing.assert_array_equal(np.asarray(deq), 0.0)


class TestCompressedGradSync:
    def _sync(self, x, **kw):
        return spmd(lambda v: dist.compressed_grad_sync(v, **kw),
                    P("dp"), P(), check=False)(x)

    def test_int8_matches_dense_mean(self):
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((8, 1000)), jnp.float32)
        out = self._sync(x, wire_dtype="int8", block=128)
        ref = np.asarray(x).mean(axis=0)
        # two quantize stages, each bounded by absmax/127 per block
        absmax = np.abs(np.asarray(x)).max()
        bound = 2.5 * absmax / 127
        assert np.abs(np.asarray(out) - ref).max() < bound

    def test_bf16_wire_is_tighter(self):
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal((8, 513)), jnp.float32)
        ref = np.asarray(x).mean(axis=0)
        e_bf16 = np.abs(np.asarray(
            self._sync(x, wire_dtype="bf16")) - ref).max()
        assert e_bf16 < 0.05

    def test_replicas_bitwise_identical(self):
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.standard_normal((8, 300)), jnp.float32)
        full = spmd(lambda v: dist.compressed_grad_sync(v),
                    P("dp"), P("dp"))(x)   # keep per-rank copies
        rows = np.asarray(full).reshape(8, -1)
        for r in range(1, 8):
            np.testing.assert_array_equal(rows[0], rows[r])

    def test_pytree_and_odd_sizes(self):
        rng = np.random.default_rng(4)
        tree = {"w": jnp.asarray(rng.standard_normal((8, 37)), jnp.float32),
                "b": jnp.asarray(rng.standard_normal((8, 3)), jnp.float32)}
        out = spmd(lambda t: dist.compressed_grad_sync(t), P("dp"), P(),
                   check=False)(tree)
        for k in tree:
            ref = np.asarray(tree[k]).mean(axis=0)
            assert np.abs(np.asarray(out[k]) - ref).max() < 0.1

    def test_bad_wire_dtype_raises(self):
        with pytest.raises(ValueError):
            spmd(lambda v: dist.compressed_grad_sync(v, wire_dtype="fp4"),
                 P("dp"), P())(jnp.zeros((8, 8)))


class TestWireBytes:
    def test_int8_beats_dense_3x(self):
        for n in (1 << 20, 1 << 24):
            comp = dist.compressed_allreduce_wire_bytes(n, 8, "int8")
            dense = dist.dense_allreduce_wire_bytes(n, 8)
            assert dense / comp >= 3.0, (n, dense / comp)

    def test_bf16_is_half(self):
        n = 1 << 20
        comp = dist.compressed_allreduce_wire_bytes(n, 8, "bf16")
        dense = dist.dense_allreduce_wire_bytes(n, 8)
        assert abs(dense / comp - 2.0) < 0.01

    def test_world_of_one_is_free(self):
        assert dist.compressed_allreduce_wire_bytes(1024, 1) == 0
        assert dist.dense_allreduce_wire_bytes(1024, 1) == 0

    def test_scale_sidecar_charged(self):
        n = 1 << 16
        small = dist.compressed_allreduce_wire_bytes(n, 8, "int8", block=64)
        large = dist.compressed_allreduce_wire_bytes(n, 8, "int8", block=512)
        assert small > large  # more blocks -> more scale bytes


class TestConvergence:
    def test_compressed_step_tracks_dense(self):
        """Linear regression: the compressed-sync step must reach the
        same loss neighborhood as the dense-sync step."""
        mesh = dist.get_mesh()
        rng = np.random.default_rng(7)
        feat, out, per = 16, 4, 8
        w_true = rng.standard_normal((feat, out)).astype(np.float32)
        x = rng.standard_normal((8 * per, feat)).astype(np.float32)
        y = (x @ w_true).astype(np.float32)

        def run(step_fn):
            w = jnp.zeros((feat, out), jnp.float32)
            b = jnp.zeros((out,), jnp.float32)
            losses = []
            for _ in range(25):
                w, b, loss = step_fn(w, b, jnp.asarray(x), jnp.asarray(y))
                losses.append(float(loss))
            return losses

        comp = run(jax.jit(build_compressed_train_step(mesh, lr=0.05)))
        assert comp[-1] < 0.05 * comp[0]          # converges
        dense = run(jax.jit(build_compressed_train_step(
            mesh, wire_dtype="bf16", lr=0.05)))
        assert abs(comp[-1] - dense[-1]) < 0.1    # same neighborhood


class TestPublicAPI:
    def test_world_of_one_identity(self):
        t = paddle.to_tensor(np.arange(6.0, dtype=np.float32))
        dist.compressed_all_reduce(t)
        np.testing.assert_allclose(t.numpy(), np.arange(6.0))

    def test_unsupported_op_raises(self):
        t = paddle.to_tensor(np.ones(4, np.float32))
        with pytest.raises(NotImplementedError):
            dist.compressed_all_reduce(t, op=dist.ReduceOp.MAX)

    def test_bad_dtype_raises(self):
        t = paddle.to_tensor(np.ones(4, np.float32))
        with pytest.raises(ValueError):
            dist.compressed_all_reduce(t, wire_dtype="int4")

    def test_mapped_context(self):
        x = np.arange(8.0, dtype=np.float32)
        out = spmd(lambda v: dist.compressed_all_reduce(v),
                   P("dp"), P("dp"))(jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(out), np.full(8, x.sum()),
                                   atol=x.max() / 20)


class TestFleetWiring:
    def test_strategy_flags_reach_data_parallel(self):
        st = fleet.DistributedStrategy()
        st.compressed_allreduce = True
        st.compressed_allreduce_dtype = "bf16"
        fleet.init(is_collective=True, strategy=st)
        import paddle_tpu.nn as nn
        model = fleet.distributed_model(nn.Linear(4, 2))
        assert model._compressed_allreduce is True
        assert model._compressed_dtype == "bf16"

    def test_bad_strategy_dtype_rejected(self):
        st = fleet.DistributedStrategy()
        st.compressed_allreduce = True
        st.compressed_allreduce_dtype = "int4"
        with pytest.raises(ValueError, match="int8"):
            fleet.init(is_collective=True, strategy=st)

    def test_dgc_error_names_replacement(self):
        st = fleet.DistributedStrategy()
        st.dgc = True
        with pytest.raises(NotImplementedError, match="compressed_allreduce"):
            fleet.init(is_collective=True, strategy=st)

    def test_data_parallel_rejects_bad_dtype(self):
        import paddle_tpu.nn as nn
        with pytest.raises(ValueError):
            dist.DataParallel(nn.Linear(2, 2), compressed_allreduce=True,
                              compressed_allreduce_dtype="fp8")


class TestTunerLane:
    def test_block_candidates_and_key(self):
        from paddle_tpu import tuner
        cands = tuner.compress_block_candidates(1 << 20)
        assert {c["block"] for c in cands} >= {64, 128, 256, 512}
        k1 = tuner.compress_key(900_000, "int8", platform="cpu")
        k2 = tuner.compress_key(1_000_000, "int8", platform="cpu")
        assert k1 == k2  # pow2 bucketing shares a winner

    def test_default_block_without_winner(self):
        from paddle_tpu.distributed.collective import _compress_block_for
        assert _compress_block_for(12345, "int8") in (
            64, 128, 256, 512, 1024, DEFAULT_COMPRESS_BLOCK)
