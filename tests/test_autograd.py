"""Tape autograd engine tests
(pattern: reference unittests/test_imperative_basic.py + basic_engine.cc paths)."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestBackward:
    def test_simple_chain(self):
        x = paddle.to_tensor([2.0], stop_gradient=False)
        y = x * x * x  # y = x^3, dy/dx = 3x^2 = 12
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [12.0])

    def test_fanout_accumulation(self):
        # x used by two branches; grads must sum (gradient_accumulator.cc)
        x = paddle.to_tensor([3.0], stop_gradient=False)
        a = x * 2
        b = x * 5
        (a + b).backward()
        np.testing.assert_allclose(x.grad.numpy(), [7.0])

    def test_diamond(self):
        x = paddle.to_tensor([2.0], stop_gradient=False)
        a = x * x      # 4
        b = a + x      # 6
        c = a * b      # 24; dc/dx = da/dx*b + a*db/dx = 4*6+4*(4+1)=44
        c.backward()
        np.testing.assert_allclose(x.grad.numpy(), [44.0])

    def test_stop_gradient_blocks(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        y = paddle.to_tensor([1.0])  # stop_gradient=True
        z = x * y
        z.backward()
        np.testing.assert_allclose(x.grad.numpy(), [1.0])
        assert y.grad is None

    def test_detach(self):
        x = paddle.to_tensor([2.0], stop_gradient=False)
        y = (x * 3).detach()
        z = y * x
        z.backward()
        np.testing.assert_allclose(x.grad.numpy(), [6.0])

    def test_no_grad_context(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        with paddle.no_grad():
            y = x * 2
        assert y._grad_node is None and y.stop_gradient

    def test_backward_twice_raises(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        y = x * 2
        y.backward(retain_graph=False)
        with pytest.raises(RuntimeError):
            y.backward()

    def test_retain_graph(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        y = x * 2
        y.backward(retain_graph=True)
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [4.0])

    def test_grad_accumulate_across_backwards(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        (x * 2).backward()
        (x * 3).backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0])
        x.clear_grad()
        assert x.grad is None

    def test_non_scalar_backward_seed(self):
        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        y = x * 3
        y.backward(paddle.to_tensor([1.0, 10.0]))
        np.testing.assert_allclose(x.grad.numpy(), [3.0, 30.0])

    def test_hook(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        seen = []

        def hook(g):
            seen.append(np.asarray(g))
            return g * 2

        x.register_hook(hook)
        (x * 3).backward()
        np.testing.assert_allclose(x.grad.numpy(), [6.0])
        assert len(seen) == 1

    def test_multi_output_op(self):
        x = paddle.to_tensor(np.array([[4.0, 1.0, 3.0]], np.float32),
                             stop_gradient=False)
        v, i = paddle.topk(x, 2)
        v.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [[1.0, 0.0, 1.0]])


class TestFunctionalGrad:
    def test_paddle_grad(self):
        x = paddle.to_tensor([3.0], stop_gradient=False)
        y = x * x
        (gx,) = paddle.grad([y], [x])
        np.testing.assert_allclose(gx.numpy(), [6.0])
        assert x.grad is None  # paddle.grad must not write .grad

    def test_allow_unused(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        z = paddle.to_tensor([1.0], stop_gradient=False)
        y = x * 2
        gx, gz = paddle.grad([y], [x, z], allow_unused=True)
        np.testing.assert_allclose(gx.numpy(), [2.0])
        assert gz is None


class TestNanCheck:
    def test_check_nan_inf_flag(self):
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        try:
            x = paddle.to_tensor([1.0])
            with pytest.raises(Exception):
                paddle.log(x - 2.0) * 1.0  # log(-1) = nan
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})
