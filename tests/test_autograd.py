"""Tape autograd engine tests
(pattern: reference unittests/test_imperative_basic.py + basic_engine.cc paths)."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestBackward:
    def test_simple_chain(self):
        x = paddle.to_tensor([2.0], stop_gradient=False)
        y = x * x * x  # y = x^3, dy/dx = 3x^2 = 12
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [12.0])

    def test_fanout_accumulation(self):
        # x used by two branches; grads must sum (gradient_accumulator.cc)
        x = paddle.to_tensor([3.0], stop_gradient=False)
        a = x * 2
        b = x * 5
        (a + b).backward()
        np.testing.assert_allclose(x.grad.numpy(), [7.0])

    def test_diamond(self):
        x = paddle.to_tensor([2.0], stop_gradient=False)
        a = x * x      # 4
        b = a + x      # 6
        c = a * b      # 24; dc/dx = da/dx*b + a*db/dx = 4*6+4*(4+1)=44
        c.backward()
        np.testing.assert_allclose(x.grad.numpy(), [44.0])

    def test_stop_gradient_blocks(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        y = paddle.to_tensor([1.0])  # stop_gradient=True
        z = x * y
        z.backward()
        np.testing.assert_allclose(x.grad.numpy(), [1.0])
        assert y.grad is None

    def test_detach(self):
        x = paddle.to_tensor([2.0], stop_gradient=False)
        y = (x * 3).detach()
        z = y * x
        z.backward()
        np.testing.assert_allclose(x.grad.numpy(), [6.0])

    def test_no_grad_context(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        with paddle.no_grad():
            y = x * 2
        assert y._grad_node is None and y.stop_gradient

    def test_backward_twice_raises(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        y = x * 2
        y.backward(retain_graph=False)
        with pytest.raises(RuntimeError):
            y.backward()

    def test_retain_graph(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        y = x * 2
        y.backward(retain_graph=True)
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [4.0])

    def test_grad_accumulate_across_backwards(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        (x * 2).backward()
        (x * 3).backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0])
        x.clear_grad()
        assert x.grad is None

    def test_non_scalar_backward_seed(self):
        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        y = x * 3
        y.backward(paddle.to_tensor([1.0, 10.0]))
        np.testing.assert_allclose(x.grad.numpy(), [3.0, 30.0])

    def test_hook(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        seen = []

        def hook(g):
            seen.append(np.asarray(g))
            return g * 2

        x.register_hook(hook)
        (x * 3).backward()
        np.testing.assert_allclose(x.grad.numpy(), [6.0])
        assert len(seen) == 1

    def test_multi_output_op(self):
        x = paddle.to_tensor(np.array([[4.0, 1.0, 3.0]], np.float32),
                             stop_gradient=False)
        v, i = paddle.topk(x, 2)
        v.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [[1.0, 0.0, 1.0]])


class TestFunctionalGrad:
    def test_paddle_grad(self):
        x = paddle.to_tensor([3.0], stop_gradient=False)
        y = x * x
        (gx,) = paddle.grad([y], [x])
        np.testing.assert_allclose(gx.numpy(), [6.0])
        assert x.grad is None  # paddle.grad must not write .grad

    def test_allow_unused(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        z = paddle.to_tensor([1.0], stop_gradient=False)
        y = x * 2
        gx, gz = paddle.grad([y], [x, z], allow_unused=True)
        np.testing.assert_allclose(gx.numpy(), [2.0])
        assert gz is None


class TestNanCheck:
    def test_check_nan_inf_flag(self):
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        try:
            x = paddle.to_tensor([1.0])
            with pytest.raises(Exception):
                paddle.log(x - 2.0) * 1.0  # log(-1) = nan
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})


class TestDoubleGrad:
    """Round-3: create_graph=True (reference: partial_grad_engine.cc) and
    PyLayer (reference: imperative/py_layer_fwd.h)."""

    def test_second_derivative_polynomial(self):
        x = paddle.to_tensor(np.array([2.0, -1.0], np.float32),
                             stop_gradient=False)
        y = x * x * x
        (gx,) = paddle.grad(y, x, create_graph=True)
        np.testing.assert_allclose(gx.numpy(), 3 * x.numpy() ** 2,
                                   rtol=1e-6)
        (ggx,) = paddle.grad((gx * gx).sum(), x)
        np.testing.assert_allclose(ggx.numpy(), 36 * x.numpy() ** 3,
                                   rtol=1e-5)

    def test_gradient_penalty_matches_numeric(self):
        # WGAN-GP shape: penalty = (||d f/d x|| - 1)^2, grads wrt W
        rng = np.random.RandomState(0)
        W0 = rng.randn(3, 4).astype(np.float32)
        xv = rng.randn(2, 3).astype(np.float32)

        def penalty(Wnp):
            h = np.tanh(xv @ Wnp)
            gx = (1 - h ** 2) @ Wnp.T
            return (np.sqrt((gx ** 2).sum()) - 1.0) ** 2

        W = paddle.to_tensor(W0, stop_gradient=False)
        xt = paddle.to_tensor(xv, stop_gradient=False)
        s = paddle.tanh(paddle.matmul(xt, W)).sum()
        (gx,) = paddle.grad(s, xt, create_graph=True)
        pen = (paddle.sqrt((gx * gx).sum()) - 1.0) ** 2
        pen.backward()
        eps = 1e-3
        num = np.zeros_like(W0)
        for i in range(W0.shape[0]):
            for j in range(W0.shape[1]):
                Wp, Wm = W0.copy(), W0.copy()
                Wp[i, j] += eps
                Wm[i, j] -= eps
                num[i, j] = (penalty(Wp) - penalty(Wm)) / (2 * eps)
        np.testing.assert_allclose(W.grad.numpy(), num, rtol=2e-2,
                                   atol=1e-4)

    def test_third_order(self):
        x = paddle.to_tensor(np.array([1.3], np.float32),
                             stop_gradient=False)
        y = paddle.exp(x)
        (g1,) = paddle.grad(y, x, create_graph=True)
        (g2,) = paddle.grad(g1, x, create_graph=True)
        (g3,) = paddle.grad(g2, x)
        np.testing.assert_allclose(g3.numpy(), np.exp([1.3]), rtol=1e-5)


class TestPyLayer:
    def test_forward_backward(self):
        class Cube(paddle.PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x * x

            @staticmethod
            def backward(ctx, gy):
                (x,) = ctx.saved_tensor()
                return gy * 3.0 * x * x

        x = paddle.to_tensor(np.array([2.0, -1.0], np.float32),
                             stop_gradient=False)
        y = Cube.apply(x)
        np.testing.assert_allclose(y.numpy(), x.numpy() ** 3)
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), 3 * x.numpy() ** 2,
                                   rtol=1e-6)

    def test_double_grad_through_pylayer(self):
        class Cube(paddle.PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x * x

            @staticmethod
            def backward(ctx, gy):
                (x,) = ctx.saved_tensor()
                return gy * 3.0 * x * x

        x = paddle.to_tensor(np.array([2.0], np.float32),
                             stop_gradient=False)
        (g,) = paddle.grad(Cube.apply(x), x, create_graph=True)
        (gg,) = paddle.grad(g, x)
        np.testing.assert_allclose(gg.numpy(), [12.0], rtol=1e-6)

    def test_multi_io_and_wrong_arity_raises(self):
        class MulAdd(paddle.PyLayer):
            @staticmethod
            def forward(ctx, a, b):
                ctx.save_for_backward(a, b)
                return a * b, a + b

            @staticmethod
            def backward(ctx, g1, g2):
                a, b = ctx.saved_tensor()
                return g1 * b + g2, g1 * a + g2

        a = paddle.to_tensor(np.array([3.0], np.float32),
                             stop_gradient=False)
        b = paddle.to_tensor(np.array([4.0], np.float32),
                             stop_gradient=False)
        o1, o2 = MulAdd.apply(a, b)
        (o1.sum() + 2 * o2.sum()).backward()
        np.testing.assert_allclose(a.grad.numpy(), [6.0])
        np.testing.assert_allclose(b.grad.numpy(), [5.0])

        class Bad(paddle.PyLayer):
            @staticmethod
            def forward(ctx, a, b):
                return a + b

            @staticmethod
            def backward(ctx, g):
                return g  # one grad for two tensor inputs

        with pytest.raises(RuntimeError, match="grads"):
            Bad.apply(a, b).sum().backward()


class TestDoubleGradThroughToStatic:
    def test_create_graph_over_compiled_fn(self):
        f = paddle.jit.to_static(lambda x: x * x * x)
        x = paddle.to_tensor(np.array([2.0], np.float32),
                             stop_gradient=False)
        y = f(x)
        (g,) = paddle.grad(y, x, create_graph=True)
        np.testing.assert_allclose(g.numpy(), [12.0], rtol=1e-5)
        (gg,) = paddle.grad(g, x)
        np.testing.assert_allclose(gg.numpy(), [12.0], rtol=1e-5)
