"""Chaos campaign for the async checkpointer (docs/fault_tolerance.md,
"Async checkpointing" crash matrix).

A real training subprocess (TrainEpochRange with ``async_save=True``) is
hard-killed at randomized points of the commit pipeline — snapshot fetch,
shard write, just before and just after the atomic rename, and (for
re-saves over the same path) inside the swap window where the previous
commit is parked as ``*.old`` — via the
``kill_during_commit`` fault action (``os._exit``, no cleanup, same as a
SIGKILL from the checkpoint's point of view), plus one case with an
actual ``SIGKILL`` landed from outside while ``slow_io`` holds the commit
window open. After every crash:

* no published (non-``.tmp``) checkpoint is torn — each one passes full
  checksum verification, and
* a plain rerun resumes from the newest intact commit and finishes with a
  final state_dict bit-identical to an uninterrupted run.

Unit-level protocol tests live in tests/test_async_checkpoint.py; this
file is the end-to-end proof.
"""
import os
import random
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from paddle_tpu.incubate.checkpoint import (OLD_SUFFIX, STAGING_SUFFIX,
                                            verify_checkpoint)
from paddle_tpu.utils.resilience import FAULT_CRASH_EXIT_CODE

#: the four commit-pipeline stations, in pipeline order
SITES = ("ckpt_fetch", "ckpt_shard_write", "ckpt_pre_rename",
         "ckpt_post_rename")

# 4 epochs, save every epoch, async writer: the first save and the final
# drained save are always processed even under maximal coalescing, so any
# occurrence in {1, 2} of every site is guaranteed to fire.
TRAIN_SCRIPT = """
    import os, sys
    os.environ.pop("JAX_PLATFORMS", None)
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, "/root/repo")
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as optim
    from paddle_tpu.incubate.checkpoint import TrainEpochRange

    ckpt_dir, out_npz = sys.argv[1], sys.argv[2]
    paddle.seed(11)
    net = nn.Linear(4, 2)
    opt = optim.SGD(learning_rate=0.05, parameters=net.parameters())
    rng = np.random.RandomState(3)
    X = rng.randn(16, 4).astype(np.float32)
    Y = rng.randn(16, 2).astype(np.float32)

    r = TrainEpochRange(4, "job_chaos", model=net, optimizer=opt,
                        checkpoint_path=ckpt_dir, async_save=True,
                        keep_last=8)
    for epoch in r:
        x, y = paddle.to_tensor(X), paddle.to_tensor(Y)
        loss = paddle.mean((net(x) - y) ** 2)
        loss.backward()
        opt.step()
        opt.clear_grad()
        print("epoch", epoch, flush=True)

    state = {k: np.asarray(v.numpy())
             for k, v in net.state_dict().items()}
    np.savez(out_npz, **state)
    print("TRAIN DONE", flush=True)
"""


def _write_script(tmp_path):
    p = tmp_path / "train.py"
    p.write_text(textwrap.dedent(TRAIN_SCRIPT))
    return str(p)


# Re-saves over the SAME path (FaultToleranceCallback's "latest" pattern):
# the swap parks commit #1 as *.old before publishing commit #2, so a kill
# inside that window must leave the parked commit recoverable — never a
# zero-checkpoint state.
RESAVE_SCRIPT = """
    import os, sys
    os.environ.pop("JAX_PLATFORMS", None)
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, "/root/repo")
    import numpy as np
    from paddle_tpu.incubate.checkpoint import commit_checkpoint
    path = sys.argv[1]
    commit_checkpoint({"w": np.arange(4.0)}, path, step=1)
    commit_checkpoint({"w": np.arange(4.0) * 2}, path, step=2)
    print("RESAVE DONE", flush=True)
"""


def _run(script, ckpt_dir, out_npz, extra_env=None, timeout=240):
    env = {k: v for k, v in os.environ.items()
           if k != "PADDLE_TPU_FAULT_SPEC"}
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, script, str(ckpt_dir), str(out_npz)],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd="/root/repo")


def _assert_no_torn_survivor(job_dir):
    """Every PUBLISHED checkpoint must be intact — the atomic-rename
    protocol means a crash can leave staging debris but never a
    half-written final directory."""
    if not os.path.isdir(job_dir):
        return
    for name in sorted(os.listdir(job_dir)):
        full = os.path.join(job_dir, name)
        if not os.path.isdir(full) or name.endswith(STAGING_SUFFIX):
            continue
        if name.startswith("epoch_"):
            verify_checkpoint(full)  # raises CheckpointIntegrityError if torn


def _assert_bit_identical(golden_npz, got_npz):
    a, b = np.load(golden_npz), np.load(got_npz)
    assert sorted(a.files) == sorted(b.files)
    for k in a.files:
        assert a[k].dtype == b[k].dtype
        assert np.array_equal(a[k], b[k]), (
            f"state {k} diverged after crash+resume")


@pytest.fixture(scope="module")
def golden(tmp_path_factory):
    """One uninterrupted run; (script_path, final-state npz path)."""
    root = tmp_path_factory.mktemp("chaos_golden")
    script = _write_script(root)
    out = str(root / "golden.npz")
    proc = _run(script, root / "ck_golden", out)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    return script, out


class TestChaosMatrix:
    @pytest.mark.parametrize("site", SITES)
    def test_kill_during_commit_resumes_bit_identical(self, site, tmp_path,
                                                      golden):
        script, golden_npz = golden
        # randomized-but-reproducible kill point within the pipeline
        occurrence = random.Random(f"chaos-{site}").choice((1, 2))
        ckpt_dir = tmp_path / "ck"
        out = str(tmp_path / "out.npz")

        crashed = _run(script, ckpt_dir, out, extra_env={
            "PADDLE_TPU_FAULT_SPEC":
                f"{site}:{occurrence}:kill_during_commit"})
        assert crashed.returncode == FAULT_CRASH_EXIT_CODE, (
            site, occurrence, crashed.stdout, crashed.stderr)
        assert f"[FaultInjector] kill_during_commit at {site}" \
            in crashed.stdout + crashed.stderr
        assert not os.path.exists(out)  # died before finishing

        job_dir = str(ckpt_dir / "job_chaos")
        _assert_no_torn_survivor(job_dir)

        resumed = _run(script, ckpt_dir, out)
        assert resumed.returncode == 0, (resumed.stdout, resumed.stderr)
        _assert_bit_identical(golden_npz, out)
        # the rerun's startup sweep cleared any staging debris
        if os.path.isdir(job_dir):
            assert not [n for n in os.listdir(job_dir)
                        if n.endswith(STAGING_SUFFIX)]

    def test_kill_inside_swap_window_recovers_parked_commit(self, tmp_path):
        """Kill between parking the old checkpoint and publishing the new
        one, re-saving the SAME path — the window where the pre-fix
        protocol (rmtree before replace) left ZERO restorable checkpoints.
        The parked *.old commit must be recovered on restart."""
        import numpy as np
        from paddle_tpu.incubate.checkpoint import (cleanup_stale_staging,
                                                    load_sharded)
        script = str(tmp_path / "resave.py")
        with open(script, "w") as f:
            f.write(textwrap.dedent(RESAVE_SCRIPT))
        path = str(tmp_path / "latest")

        # occurrence 1: the first commit has nothing to park, so the site
        # first fires during commit #2's swap
        crashed = _run(script, path, "unused", extra_env={
            "PADDLE_TPU_FAULT_SPEC": "ckpt_swap_window:1:kill_during_commit"})
        assert crashed.returncode == FAULT_CRASH_EXIT_CODE, (
            crashed.stdout, crashed.stderr)
        assert not os.path.isdir(path)          # mid-swap: final not yet in
        assert os.path.isdir(path + OLD_SUFFIX)  # ...but commit #1 is parked

        # the startup sweep un-parks commit #1 and drops the staged debris
        cleanup_stale_staging(str(tmp_path))
        verify_checkpoint(path)
        out = load_sharded(path, return_tensor=False)
        np.testing.assert_allclose(out["w"], np.arange(4.0))
        assert not os.path.isdir(path + OLD_SUFFIX)
        assert not os.path.isdir(path + STAGING_SUFFIX)

        # a clean rerun republishes the newer state over the recovered one
        ok = _run(script, path, "unused")
        assert ok.returncode == 0, (ok.stdout, ok.stderr)
        out = load_sharded(path, return_tensor=False)
        np.testing.assert_allclose(out["w"], np.arange(4.0) * 2)
        assert not os.path.isdir(path + OLD_SUFFIX)

    def test_external_sigkill_mid_commit_window(self, tmp_path, golden):
        """A real SIGKILL from outside, landed while slow_io holds the
        pre-rename window open (staging on disk, final not yet renamed) —
        the nastiest torn-state candidate."""
        script, golden_npz = golden
        ckpt_dir = tmp_path / "ck"
        out = str(tmp_path / "out.npz")
        job_dir = str(ckpt_dir / "job_chaos")

        env = {k: v for k, v in os.environ.items()
               if k != "PADDLE_TPU_FAULT_SPEC"}
        env["PADDLE_TPU_FAULT_SPEC"] = "ckpt_pre_rename:1:slow_io"
        env["PADDLE_TPU_FAULT_SLOW_IO_S"] = "60"
        proc = subprocess.Popen(
            [sys.executable, script, str(ckpt_dir), out],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env,
            cwd="/root/repo")
        try:
            deadline = time.monotonic() + 120
            staged = None
            while time.monotonic() < deadline:
                if os.path.isdir(job_dir):
                    staged = [n for n in os.listdir(job_dir)
                              if n.endswith(STAGING_SUFFIX)]
                    if staged:
                        break
                if proc.poll() is not None:
                    pytest.fail("trainer exited before staging appeared "
                                f"(rc={proc.returncode})")
                time.sleep(0.02)
            assert staged, "never saw a staging dir inside the slow_io window"
            proc.send_signal(signal.SIGKILL)
            assert proc.wait(timeout=30) == -signal.SIGKILL
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)

        _assert_no_torn_survivor(job_dir)
        resumed = _run(script, ckpt_dir, out)
        assert resumed.returncode == 0, (resumed.stdout, resumed.stderr)
        _assert_bit_identical(golden_npz, out)
        assert not [n for n in os.listdir(job_dir)
                    if n.endswith(STAGING_SUFFIX)]
