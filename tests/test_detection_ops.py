"""Detection op tests with numpy references (reference analogs:
unittests/test_yolo_box_op.py, test_yolov3_loss_op.py,
test_multiclass_nms_op.py, test_iou_similarity_op.py, test_box_coder_op.py
— same numpy-reference discipline as the OpTest harness)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import ops
from paddle_tpu.jit import to_static
import paddle_tpu.nn as nn


def np_iou(a, b):
    area_a = np.maximum(a[2] - a[0], 0) * np.maximum(a[3] - a[1], 0)
    area_b = np.maximum(b[2] - b[0], 0) * np.maximum(b[3] - b[1], 0)
    iw = max(min(a[2], b[2]) - max(a[0], b[0]), 0)
    ih = max(min(a[3], b[3]) - max(a[1], b[1]), 0)
    inter = iw * ih
    union = area_a + area_b - inter
    return inter / union if union > 0 else 0.0


class TestIouSimilarity:
    def test_matches_numpy(self):
        rng = np.random.RandomState(0)
        a = np.sort(rng.rand(5, 4).astype(np.float32) * 10, axis=-1)[:, [0, 1, 2, 3]]
        a = np.stack([a[:, 0], a[:, 1], a[:, 2], a[:, 3]], axis=1)
        b = np.sort(rng.rand(7, 4).astype(np.float32) * 10, axis=-1)
        # make valid x1<x2, y1<y2 boxes
        a = np.stack([np.minimum(a[:, 0], a[:, 2]), np.minimum(a[:, 1], a[:, 3]),
                      np.maximum(a[:, 0], a[:, 2]), np.maximum(a[:, 1], a[:, 3])], 1)
        b = np.stack([np.minimum(b[:, 0], b[:, 2]), np.minimum(b[:, 1], b[:, 3]),
                      np.maximum(b[:, 0], b[:, 2]), np.maximum(b[:, 1], b[:, 3])], 1)
        out = ops.iou_similarity(paddle.to_tensor(a), paddle.to_tensor(b))
        expected = np.array([[np_iou(x, y) for y in b] for x in a])
        np.testing.assert_allclose(out.numpy(), expected, atol=1e-5)


class TestYoloBox:
    def test_decode_matches_numpy(self):
        rng = np.random.RandomState(1)
        N, H, W, C = 2, 4, 4, 3
        anchors = [10, 13, 16, 30]
        A = 2
        x = rng.randn(N, A * (5 + C), H, W).astype(np.float32)
        img_size = np.array([[128, 128], [64, 96]], np.int32)
        ds = 32
        boxes, scores = ops.yolo_box(
            paddle.to_tensor(x), paddle.to_tensor(img_size), anchors, C,
            conf_thresh=0.01, downsample_ratio=ds, clip_bbox=True)
        assert boxes.shape == [N, A * H * W, 4]
        assert scores.shape == [N, A * H * W, C]

        def sig(v):
            return 1 / (1 + np.exp(-v))
        p = x.reshape(N, A, 5 + C, H, W)
        n, a, j, i = 0, 1, 2, 3
        bx = (sig(p[n, a, 0, j, i]) + i) / W
        by = (sig(p[n, a, 1, j, i]) + j) / H
        bw = np.exp(p[n, a, 2, j, i]) * anchors[2] / (W * ds)
        bh = np.exp(p[n, a, 3, j, i]) * anchors[3] / (H * ds)
        conf = sig(p[n, a, 4, j, i])
        iw, ih = img_size[n, 1], img_size[n, 0]
        exp_box = np.array([
            np.clip((bx - bw / 2) * iw, 0, iw - 1),
            np.clip((by - bh / 2) * ih, 0, ih - 1),
            np.clip((bx + bw / 2) * iw, 0, iw - 1),
            np.clip((by + bh / 2) * ih, 0, ih - 1)])
        if conf >= 0.01:
            flat = a * H * W + j * W + i
            np.testing.assert_allclose(boxes.numpy()[n, flat], exp_box,
                                       rtol=1e-4, atol=1e-3)
            np.testing.assert_allclose(
                scores.numpy()[n, flat],
                conf * sig(p[n, a, 5:, j, i]), rtol=1e-4)

    def test_low_conf_zeroed(self):
        x = np.full((1, 2 * 6, 2, 2), -20.0, np.float32)  # conf ~ 0
        boxes, scores = ops.yolo_box(
            paddle.to_tensor(x), paddle.to_tensor(np.array([[64, 64]], np.int32)),
            [10, 13, 16, 30], 1, conf_thresh=0.5, downsample_ratio=32)
        np.testing.assert_allclose(boxes.numpy(), 0.0)
        np.testing.assert_allclose(scores.numpy(), 0.0)


class TestMulticlassNMS:
    def test_suppression_and_padding(self):
        # two overlapping boxes + one distinct; class 0 is background
        bboxes = np.array([[[0, 0, 10, 10], [1, 1, 11, 11],
                            [50, 50, 60, 60]]], np.float32)
        scores = np.zeros((1, 2, 3), np.float32)
        scores[0, 1] = [0.9, 0.8, 0.7]  # class 1 scores per box
        out, counts = ops.multiclass_nms(
            paddle.to_tensor(bboxes), paddle.to_tensor(scores),
            score_threshold=0.1, nms_top_k=3, keep_top_k=4,
            nms_threshold=0.5, background_label=0)
        o = out.numpy()[0]
        assert int(counts.numpy()[0]) == 2  # box 1 suppressed by box 0
        # rows sorted by score: (1, 0.9, box0), (1, 0.7, box2), then padding
        assert o[0][0] == 1 and abs(o[0][1] - 0.9) < 1e-6
        np.testing.assert_allclose(o[0][2:], [0, 0, 10, 10])
        assert o[1][0] == 1 and abs(o[1][1] - 0.7) < 1e-6
        np.testing.assert_allclose(o[1][2:], [50, 50, 60, 60])
        assert (o[2:, 0] == -1).all()

    def test_multiclass_and_score_threshold(self):
        bboxes = np.array([[[0, 0, 10, 10], [20, 20, 30, 30]]], np.float32)
        scores = np.zeros((1, 3, 2), np.float32)
        scores[0, 1] = [0.9, 0.05]   # class 1: one above, one below threshold
        scores[0, 2] = [0.6, 0.8]    # class 2: both above
        out, counts = ops.multiclass_nms(
            paddle.to_tensor(bboxes), paddle.to_tensor(scores),
            score_threshold=0.1, nms_top_k=2, keep_top_k=5,
            nms_threshold=0.5, background_label=0)
        assert int(counts.numpy()[0]) == 3
        labels = out.numpy()[0, :3, 0]
        assert sorted(labels.tolist()) == [1.0, 2.0, 2.0]


class TestBoxCoder:
    def test_encode_decode_roundtrip(self):
        rng = np.random.RandomState(2)
        priors = np.abs(rng.rand(6, 4).astype(np.float32))
        priors[:, 2:] = priors[:, :2] + 0.5 + priors[:, 2:]
        targets = np.abs(rng.rand(6, 4).astype(np.float32))
        targets[:, 2:] = targets[:, :2] + 0.5 + targets[:, 2:]
        var = np.full((6, 4), 0.1, np.float32)
        enc = ops.box_coder(paddle.to_tensor(priors), paddle.to_tensor(var),
                            paddle.to_tensor(targets),
                            code_type="encode_center_size")
        # decode expects [M, 4] deltas aligned with priors
        dec = ops.box_coder(paddle.to_tensor(priors), paddle.to_tensor(var),
                            paddle.to_tensor(np.diagonal(
                                enc.numpy(), axis1=0, axis2=1).T
                                if enc.numpy().ndim == 3 else enc.numpy()),
                            code_type="decode_center_size")
        d = dec.numpy()
        if d.ndim == 3:
            d = np.stack([d[i, i] for i in range(6)])
        np.testing.assert_allclose(d, targets, atol=1e-4)


class TestPriorBox:
    def test_shapes_and_range(self):
        feat = paddle.to_tensor(np.zeros((1, 8, 4, 4), np.float32))
        img = paddle.to_tensor(np.zeros((1, 3, 64, 64), np.float32))
        boxes, var = ops.prior_box(feat, img, min_sizes=[16.0],
                                   aspect_ratios=[1.0, 2.0], flip=True,
                                   clip=True)
        assert boxes.shape[0] == 4 and boxes.shape[1] == 4
        assert boxes.shape[3] == 4
        b = boxes.numpy()
        assert (b >= 0).all() and (b <= 1).all()
        assert var.shape == boxes.shape


class TestYolov3Loss:
    def _data(self, good=False):
        rng = np.random.RandomState(3)
        N, H, W, C = 2, 4, 4, 3
        anchors = [10, 13, 16, 30, 33, 23]
        mask = [0, 1, 2]
        A = 3
        x = rng.randn(N, A * (5 + C), H, W).astype(np.float32) * 0.1
        gt_box = np.zeros((N, 5, 4), np.float32)
        gt_label = np.zeros((N, 5), np.int64)
        gt_box[0, 0] = [0.5, 0.5, 0.2, 0.3]
        gt_label[0, 0] = 1
        gt_box[1, 0] = [0.25, 0.25, 0.1, 0.1]
        gt_box[1, 1] = [0.75, 0.75, 0.3, 0.2]
        gt_label[1, 1] = 2
        return x, gt_box, gt_label, anchors, mask, C

    @pytest.mark.slow
    def test_loss_finite_positive_and_grad(self):
        x, gt_box, gt_label, anchors, mask, C = self._data()
        xt = paddle.to_tensor(x, stop_gradient=False)
        loss = ops.yolov3_loss(xt, paddle.to_tensor(gt_box),
                               paddle.to_tensor(gt_label), anchors, mask, C,
                               ignore_thresh=0.7, downsample_ratio=32)
        assert loss.shape == [2]
        l = loss.numpy()
        assert np.isfinite(l).all() and (l > 0).all()
        paddle.sum(loss).backward()
        g = xt.grad.numpy()
        assert np.isfinite(g).all() and np.abs(g).sum() > 0

    def test_perfect_prediction_low_loss(self):
        """Constructed predictions matching the gt must cost far less than
        random ones."""
        x, gt_box, gt_label, anchors, mask, C = self._data()
        rng = np.random.RandomState(0)
        rand_loss = ops.yolov3_loss(
            paddle.to_tensor(rng.randn(*x.shape).astype(np.float32) * 3),
            paddle.to_tensor(gt_box), paddle.to_tensor(gt_label),
            anchors, mask, C, ignore_thresh=0.7,
            downsample_ratio=32).numpy().sum()
        # all-negative objectness with no gt -> much smaller loss
        no_gt = np.zeros_like(gt_box)
        quiet = np.full(x.shape, -8.0, np.float32)
        quiet_loss = ops.yolov3_loss(
            paddle.to_tensor(quiet), paddle.to_tensor(no_gt),
            paddle.to_tensor(np.zeros_like(gt_label)),
            anchors, mask, C, ignore_thresh=0.7,
            downsample_ratio=32).numpy().sum()
        assert quiet_loss < rand_loss * 0.05

    def test_yolo_head_under_to_static(self):
        """A YOLO head (conv -> yolo_box) compiles under to_static
        (VERDICT item 9 acceptance)."""
        C = 3
        A = 2

        class Head(nn.Layer):
            def __init__(self):
                super().__init__()
                self.conv = nn.Conv2D(8, A * (5 + C), 1)

            def forward(self, feat, img_size):
                p = self.conv(feat)
                boxes, scores = ops.yolo_box(
                    p, img_size, [10, 13, 16, 30], C,
                    conf_thresh=0.01, downsample_ratio=32)
                return boxes, scores

        head = to_static(Head())
        feat = paddle.to_tensor(
            np.random.RandomState(0).randn(1, 8, 4, 4).astype(np.float32))
        img = paddle.to_tensor(np.array([[128, 128]], np.int32))
        boxes, scores = head(feat, img)
        assert boxes.shape == [1, 32, 4]
        assert scores.shape == [1, 32, C]
