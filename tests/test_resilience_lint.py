"""The silent-except lint (tools/lint_silent_except.py) runs as part of
tier-1: failures in the resilience paths (launcher, elastic supervisor,
checkpoint layer, retry substrate) must never be silently swallowed."""
import importlib.util
import os
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "lint_silent_except", os.path.join(REPO, "tools", "lint_silent_except.py"))
lint = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(lint)


class TestDetector:
    def _check(self, tmp_path, src):
        p = tmp_path / "mod.py"
        p.write_text(textwrap.dedent(src))
        return lint.check_file(str(p))

    def test_flags_bare_except(self, tmp_path):
        offs = self._check(tmp_path, """
            try:
                work()
            except:
                pass
        """)
        assert len(offs) == 1 and "bare" in offs[0][2]

    def test_flags_swallowed_exception(self, tmp_path):
        offs = self._check(tmp_path, """
            try:
                work()
            except (ValueError, Exception):
                pass
        """)
        assert len(offs) == 1 and "swallows" in offs[0][2]

    def test_flags_ellipsis_body(self, tmp_path):
        offs = self._check(tmp_path, """
            try:
                work()
            except Exception:
                ...
        """)
        assert len(offs) == 1

    def test_allows_handled_broad_except(self, tmp_path):
        offs = self._check(tmp_path, """
            try:
                work()
            except Exception as e:
                log(e)
                raise
        """)
        assert offs == []

    def test_allows_narrow_except_pass(self, tmp_path):
        # narrow swallows (e.g. FileNotFoundError on cleanup) are fine
        offs = self._check(tmp_path, """
            try:
                os.remove(p)
            except FileNotFoundError:
                pass
        """)
        assert offs == []


class TestRepoIsClean:
    def test_no_silent_excepts_in_resilience_paths(self):
        offenders = lint.find_offenders()
        assert offenders == [], "\n".join(
            f"{p}:{ln}: {msg}" for p, ln, msg in offenders)
