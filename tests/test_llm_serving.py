"""paddle_tpu.serving.llm: static-slot KV cache, single-compile decode,
continuous batching, drain, and the /generate HTTP route."""
import json
import signal
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.serving.llm import (LLMEngine, LLMEngineConfig,
                                    StaticKVCache)
from paddle_tpu.serving.llm.kvcache import (SlotsExhausted, append_token_kv,
                                            valid_mask, write_prompt_kv)
from paddle_tpu.serving.request import DeadlineExceeded, EngineDraining

import jax
import jax.numpy as jnp


def _tiny_model(seed=0, vocab=64, hidden=32, layers=2, heads=4, max_pos=128):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden, num_layers=layers,
                    num_heads=heads, max_position_embeddings=max_pos,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    net = GPTForCausalLM(cfg)
    net.eval()
    return net


@pytest.fixture(scope="module")
def model():
    return _tiny_model()


@pytest.fixture(scope="module")
def engine(model):
    eng = LLMEngine(model, LLMEngineConfig(
        num_slots=4, max_seq=64, prefill_buckets=(8, 16), warmup=True))
    yield eng
    if not eng._stopped.is_set():
        eng.drain(timeout=60)


# -- StaticKVCache units -----------------------------------------------------

class TestStaticKVCache:
    def test_alloc_free_reset(self):
        kv = StaticKVCache(num_slots=3, num_layers=2, max_seq=8,
                           num_heads=2, head_dim=4)
        assert kv.free_slots == 3
        a, b2 = kv.alloc(), kv.alloc()
        assert (a, b2) == (0, 1) and kv.active_slots == (0, 1)
        kv.free(a)
        assert kv.free_slots == 2 and kv.alloc() == 0  # lowest-index reuse
        with pytest.raises(ValueError):
            kv.free(5)
        kv.alloc()                     # takes the last free slot (2)
        with pytest.raises(SlotsExhausted):
            kv.alloc()
        kv.reset()
        assert kv.free_slots == 3 and not kv.active_slots
        assert kv.host_lengths().tolist() == [0, 0, 0]

    def test_append_token_kv_writes_at_positions(self):
        kb = jnp.zeros((2, 4, 1, 2))
        vb = jnp.zeros((2, 4, 1, 2))
        kn = jnp.ones((2, 1, 2))
        vn = 2.0 * jnp.ones((2, 1, 2))
        pos = jnp.asarray([0, 3], jnp.int32)
        kb, vb = append_token_kv(kb, vb, kn, vn, pos)
        kb = np.asarray(kb)
        assert kb[0, 0].sum() == 2 and kb[0, 1:].sum() == 0
        assert kb[1, 3].sum() == 2 and kb[1, :3].sum() == 0
        assert np.asarray(vb)[1, 3, 0, 0] == 2.0

    def test_write_prompt_kv_into_slot_rows(self):
        buf = jnp.zeros((3, 2, 8, 1, 2))      # [S, L, max_seq, H, D]
        kp = jnp.ones((1, 2, 4, 1, 2))        # [B, L, Lp, H, D]
        kb, vb = write_prompt_kv(buf, buf, kp, 3.0 * kp,
                                 jnp.asarray([2], jnp.int32))
        kb, vb = np.asarray(kb), np.asarray(vb)
        assert kb[2, :, :4].sum() == 2 * 4 * 2 and kb[:2].sum() == 0
        assert kb[2, :, 4:].sum() == 0
        assert vb[2, 0, 0, 0, 0] == 3.0

    def test_valid_mask_additive_form(self):
        m = np.asarray(valid_mask(jnp.asarray([0, 2], jnp.int32), 4))
        assert m.shape == (2, 1, 1, 4)
        assert (m[0, 0, 0] == [0.0, -1e9, -1e9, -1e9]).all()
        assert (m[1, 0, 0] == [0.0, 0.0, 0.0, -1e9]).all()


# -- decode equivalence ------------------------------------------------------

class TestGenerateEquivalence:
    def test_greedy_static_matches_concat_and_recompute(self, model):
        ids = paddle.to_tensor(np.array([[3, 1, 4, 1, 5], [9, 2, 6, 5, 3]],
                                        np.int32))
        fast = model.generate(ids, max_length=16, use_cache=True).numpy()
        concat = model.generate(ids, max_length=16,
                                use_cache="concat").numpy()
        slow = model.generate(ids, max_length=16, use_cache=False).numpy()
        np.testing.assert_array_equal(fast, concat)
        np.testing.assert_array_equal(fast, slow)

    def test_seeded_topk_sampling_static_matches_concat(self, model):
        ids = paddle.to_tensor(np.array([[3, 1, 4, 1, 5]], np.int32))
        paddle.seed(11)
        fast = model.generate(ids, max_length=16,
                              decode_strategy="sampling", top_k=5,
                              temperature=0.8, use_cache=True).numpy()
        paddle.seed(11)
        concat = model.generate(ids, max_length=16,
                                decode_strategy="sampling", top_k=5,
                                temperature=0.8, use_cache="concat").numpy()
        np.testing.assert_array_equal(fast, concat)

    def test_eos_early_exit_shape_parity(self, model):
        ids = paddle.to_tensor(np.array([[3, 1, 4, 1, 5], [9, 2, 6, 5, 3]],
                                        np.int32))
        probe = model.generate(ids, max_length=8).numpy()
        eos = int(probe[0, 6])    # a token the greedy path actually emits
        fast = model.generate(ids, max_length=24, eos_token_id=eos,
                              use_cache=True).numpy()
        concat = model.generate(ids, max_length=24, eos_token_id=eos,
                                use_cache="concat").numpy()
        np.testing.assert_array_equal(fast, concat)
        # per-row freeze: once a row emits eos it stays eos
        for r in range(fast.shape[0]):
            row = fast[r, 5:]
            hit = np.where(row == eos)[0]
            if hit.size:
                assert (row[hit[0]:] == eos).all()


# -- the compile counter -----------------------------------------------------

class TestSingleCompile:
    def test_one_decode_trace_across_occupancy_changes(self, engine):
        """After warmup, 64+ tokens across 1-, 3- and 2-deep occupancy run
        through ZERO new decode-step traces and zero executable-cache
        misses — THE static-shape guarantee."""
        fn = engine.decoder.decode_fn(engine.config.num_slots,
                                      engine.config.max_seq)
        t0 = fn.trace_counter["traces"]
        m0 = engine.cache.stats()["misses"]
        assert t0 >= 1    # warmup traced it
        r1 = engine.submit([1, 2, 3], max_new_tokens=24)
        r1.result(timeout=60)
        rs = [engine.submit([i + 1, i + 2], max_new_tokens=16)
              for i in range(3)]
        for r in rs:
            r.result(timeout=60)
        r2 = [engine.submit([7, 8, 9, 10], max_new_tokens=8)
              for _ in range(2)]
        for r in r2:
            r.result(timeout=60)
        total = 24 + 3 * 16 + 2 * 8
        assert total >= 64
        assert fn.trace_counter["traces"] == t0, \
            "decode step re-traced despite static shapes"
        assert engine.cache.stats()["misses"] == m0, \
            "executable cache missed after warmup"

    def test_prefill_traces_bounded_by_buckets(self, engine):
        pf8 = engine.decoder.prefill_fn(1, 8)
        t0 = pf8.trace_counter["traces"]
        for prompt in ([1], [1, 2, 3], [1, 2, 3, 4, 5, 6]):   # all bucket 8
            engine.submit(prompt, max_new_tokens=2).result(timeout=60)
        assert pf8.trace_counter["traces"] == t0


# -- continuous batching e2e -------------------------------------------------

class TestContinuousBatching:
    def test_midstream_join_and_leave(self, engine):
        """A long request streams while a short one joins mid-flight,
        finishes first (leaves its slot), and a third reuses capacity —
        all without a new compile."""
        fn = engine.decoder.decode_fn(engine.config.num_slots,
                                      engine.config.max_seq)
        t0 = fn.trace_counter["traces"]
        long_req = engine.submit([1, 2, 3], max_new_tokens=40, stream=True)
        it = long_req.iter_tokens(timeout=60)
        first = [next(it) for _ in range(4)]   # long_req is mid-stream
        assert len(first) == 4
        short = engine.submit([4, 5], max_new_tokens=3)
        out_short = short.result(timeout=60)
        assert len(out_short["tokens"]) == 3
        assert out_short["finish_reason"] == "length"
        third = engine.submit([6], max_new_tokens=3)
        assert len(third.result(timeout=60)["tokens"]) == 3
        rest = list(it)
        assert len(first) + len(rest) == 40
        assert long_req.result(timeout=60)["tokens"] == first + rest
        assert fn.trace_counter["traces"] == t0

    def test_eos_finishes_early(self, model, engine):
        ids = paddle.to_tensor(np.array([[1, 2, 3]], np.int32))
        probe = model.generate(ids, max_length=4).numpy()[0, 3:]
        eos = int(probe[1])
        out = engine.submit([1, 2, 3], max_new_tokens=30,
                            eos_token_id=eos).result(timeout=60)
        assert out["finish_reason"] == "stop"
        assert out["tokens"][-1] == eos and len(out["tokens"]) <= 30
        # matches the generate() reference for the same prompt/eos
        ref = model.generate(ids, max_length=30,
                             eos_token_id=eos).numpy()[0, 3:]
        assert out["tokens"] == ref.tolist()

    def test_deadline_evicts_stalled_slot(self, model):
        eng = LLMEngine(model, LLMEngineConfig(
            num_slots=2, max_seq=64, prefill_buckets=(8,), warmup=True))
        try:
            before = eng.registry.get("serving.llm.evicted_midstream", 0)
            req = eng.submit([1, 2, 3], max_new_tokens=60, deadline=0.010)
            with pytest.raises(DeadlineExceeded):
                req.result(timeout=60)
            deadline = time.monotonic() + 30
            while eng._batcher.active and time.monotonic() < deadline:
                time.sleep(0.01)
            assert eng._batcher.active == 0       # slot reclaimed
            assert eng.registry.get("serving.llm.evicted_midstream", 0) \
                > before
            # the engine still serves after the eviction
            ok = eng.submit([4, 5], max_new_tokens=2).result(timeout=60)
            assert len(ok["tokens"]) == 2
        finally:
            eng.drain(timeout=60)

    def test_queue_rejects_oversize_prompt(self, engine):
        from paddle_tpu.serving.request import RequestTooLarge
        with pytest.raises(RequestTooLarge):
            engine.submit(list(range(17)), max_new_tokens=2)  # > bucket 16


# -- drain / preemption ------------------------------------------------------

class TestDrain:
    def test_drain_finishes_inflight_and_queued(self, model):
        eng = LLMEngine(model, LLMEngineConfig(
            num_slots=1, max_seq=64, prefill_buckets=(8,), warmup=True))
        inflight = eng.submit([1, 2], max_new_tokens=30)
        queued = eng.submit([3, 4], max_new_tokens=5)   # waits for the slot
        eng.begin_drain()
        with pytest.raises(EngineDraining):
            eng.submit([5], max_new_tokens=1)
        eng.drain(timeout=60)
        assert eng._stopped.is_set()
        assert len(inflight.result(timeout=1)["tokens"]) == 30
        assert len(queued.result(timeout=1)["tokens"]) == 5

    def test_sigterm_flag_path_finishes_midstream(self, model):
        """The async-signal-safe drain path: the flag-only handler fires
        while a sequence streams; the worker completes it before
        stopping."""
        eng = LLMEngine(model, LLMEngineConfig(
            num_slots=2, max_seq=64, prefill_buckets=(8,), warmup=True))
        req = eng.submit([1, 2, 3], max_new_tokens=25, stream=True)
        it = req.iter_tokens(timeout=60)
        got = [next(it) for _ in range(3)]
        eng._on_drain_signal(signal.SIGTERM, None)   # what SIGTERM runs
        assert eng.draining
        got += list(it)
        assert len(got) == 25                        # finished, not cut off
        eng._stopped.wait(timeout=60)
        assert eng._stopped.is_set()

    def test_preemption_guard_triggers_drain(self, model):
        from paddle_tpu.distributed.elastic import PreemptionGuard
        eng = LLMEngine(model, LLMEngineConfig(
            num_slots=1, max_seq=64, prefill_buckets=(8,), warmup=True))
        guard = PreemptionGuard(install=False)
        eng.arm_preemption(guard)
        guard._handler(signal.SIGTERM, None)   # what the real signal runs
        eng._stopped.wait(timeout=60)
        assert eng._stopped.is_set() and eng.draining
        before = eng.registry.get("serving.llm.preemption_drains", 0)
        assert before >= 1


# -- HTTP route --------------------------------------------------------------

class TestGenerateHTTP:
    @pytest.fixture()
    def server(self, engine):
        from paddle_tpu.serving.http import make_server
        httpd = make_server(None, port=0, llm_engine=engine)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        host, port = httpd.server_address[:2]
        yield f"http://{host}:{port}"
        httpd.shutdown()
        httpd.server_close()

    def _post(self, url, payload):
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        return urllib.request.urlopen(req, timeout=60)

    def test_generate_nonstream(self, server):
        with self._post(f"{server}/generate",
                        {"prompt": [1, 2, 3], "max_new_tokens": 5}) as r:
            out = json.loads(r.read())
        assert len(out["tokens"]) == 5
        assert out["finish_reason"] == "length"

    def test_generate_stream_ndjson(self, server):
        with self._post(f"{server}/generate",
                        {"prompt": [4, 5], "max_new_tokens": 6,
                         "stream": True}) as r:
            lines = [json.loads(ln) for ln in r.read().splitlines() if ln]
        toks = [ln["token"] for ln in lines if "token" in ln]
        assert len(toks) == 6
        assert lines[-1]["done"] is True
        assert lines[-1]["finish_reason"] == "length"

    def test_statsz_carries_llm_counters(self, server):
        with urllib.request.urlopen(f"{server}/statsz", timeout=30) as r:
            st = json.loads(r.read())
        llm = st["llm"]
        assert llm["slots"]["total"] == 4
        assert llm["stats"]["serving.llm.tokens_generated"] > 0
        assert "serving.llm.slots_in_use" in llm["stats"]
        assert "serving.llm.ttft_ms" in llm["histograms"]
        assert "serving.llm.tpot_ms" in llm["histograms"]
        assert "misses" in llm["executable_cache"]

    def test_healthz_ok(self, server):
        with urllib.request.urlopen(f"{server}/healthz", timeout=30) as r:
            assert json.loads(r.read())["status"] == "ok"

    def test_bad_request_400(self, server):
        try:
            self._post(f"{server}/generate", {"nope": 1})
            assert False, "expected HTTPError"
        except urllib.error.HTTPError as e:
            assert e.code == 400


# -- lint scope --------------------------------------------------------------

def test_pta002_covers_llm_hot_path():
    from tools.analyze.rules.pta002_host_sync import HOT_PREFIXES
    assert "paddle_tpu/serving/llm/" in HOT_PREFIXES
